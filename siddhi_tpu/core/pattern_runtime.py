"""Pattern / sequence NFA runtime (reference: core/query/input/stream/state/ —
StreamPreStateProcessor.java:46, StreamPostStateProcessor, Logical/Count/Absent
processors, runtimes under state/runtime/; parsed by
StateInputStreamParser.java:73).

The reference walks per-event pending-StateEvent linked lists. The TPU
redesign keeps, per pattern position p, a **fixed-capacity pending table** of
partial matches waiting for position p's event:

    pending[p]:
      frames      {ref: {attr: [P]}}   captured columns of earlier positions
      frame_valid {ref: [P]}           leg/absent frames may be missing
      start_ts    [P]                  first captured event ts (within expiry)
      last_seq    [P]                  arrival seq of latest captured event
      armed_ts    [P]                  when the entry reached this position
      valid       [P]

A micro-batch on a stream junction is matched against every position fed by
that stream **in ascending position order**, so intra-batch chains (A then B
in one batch) complete exactly as the reference's per-event walk would:

    [B,1] arrival frame x [P] pending frames -> [B,P] condition mask
    qualify &= arrival_seq > last_seq   (pattern: skip-till-any-match)
            or arrival_seq == last_seq+1 (sequence: strict contiguity)
    per-entry FIRST qualifying arrival consumes the entry (reference:
    pending state events are removed on match) -> advance or emit.

`every` re-arms position 0 permanently; non-every patterns consume the start
state on first match. `within` invalidates entries by start_ts. Absent
(`not X for T`) entries are killed by a matching X and complete on watermark
`now >= armed_ts + T` (heartbeat-driven — the reference's Scheduler TIMER,
AbsentStreamPreStateProcessor.java:35-57). Logical and/or positions hold two
legs filled in either order. Counts `<m:n>` expand at plan time into n
positions (optional beyond m), with `e[k]`-indexed frames.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..errors import DefinitionNotExistError, SiddhiAppCreationError
from ..extension.registry import Registry
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..ops.search import stable_partition_order
from ..ops.selector import CompiledSelector
from ..query_api.definition import Attribute, AttributeType, StreamDefinition
from ..query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    LogicalStateElement,
    NextStateElement,
    OutputAction,
    Query,
    SingleInputStream,
    StateInputStream,
    StateType,
    StreamStateElement,
)
from ..query_api.expression import Expression, Variable
from . import dtypes
from .context import SiddhiAppContext
from .event import EventBatch, EventType, StreamCodec
from .query_runtime import QueryCallback
from .stream import Receiver, StreamJunction

BIGSEQ = 2**62  # Python int literal — see ops/windows.py BIG note (tunnel cost)

#: junction key for the merged multi-stream sequence step
MERGED_SID = "#merged"


@dataclass
class _Leg:
    """One stream condition (a logical position has two)."""

    ref: str
    stream_id: str
    filters: tuple  # Expression ASTs


@dataclass
class _Position:
    index: int
    kind: str  # 'normal' | 'absent' | 'logical'
    legs: list  # [_Leg] (1 normal/absent, 2 logical)
    logical_op: Optional[str] = None  # 'and' | 'or'
    wait_ms: Optional[int] = None  # absent
    optional: bool = False  # count occurrences beyond min_count
    #: mid-pattern `every` (`A -> every B`): matches advance a COPY and the
    #: entry stays armed (reference: EveryInnerStateRuntime re-arming)
    sticky: bool = False

    @property
    def ref(self) -> str:
        return self.legs[0].ref


def _unwrap_chain(elem):
    """EveryStateElement.state may hold a nested ('chain', state, within).
    Returns (inner_element, group_within_ms) — a `within` scoped inside the
    every-group bounds EACH ITERATION (first→last captured event of one
    group traversal), per the reference's per-state within lists
    (StreamPreStateProcessor.java:119-136)."""
    if isinstance(elem, tuple) and elem and elem[0] in ("chain", "seq"):
        return elem[1], elem[2]
    return elem, None


@dataclass
class _EveryGroup:
    """A grouped `every ( ... )` — positions [head, end] form one iteration;
    the NEXT iteration arms only when the current one completes (reference:
    EveryInnerStateRuntime.java:30 re-arms on inner-runtime completion —
    see EveryPatternTestCase testQuery5: A A A A B yields 2 matches, the
    iterations pair up sequentially instead of one per arrival)."""

    head: int
    end: int
    within_ms: Optional[int] = None


class _PatternPlan:
    """Flattens the state AST into a linear position list."""

    def __init__(self, sis: StateInputStream, ctx) -> None:
        self.every = False
        self.positions: list[_Position] = []
        self.is_sequence = sis.state_type == StateType.SEQUENCE
        self.within_ms = sis.within_ms
        #: ref -> (base_ref, occurrence_index) for count groups
        self.count_groups: dict[str, list[str]] = {}
        #: (start_pos, end_pos) spans of zero-minimum count groups, in order
        self.zero_min_spans: list[tuple[int, int]] = []

        #: head every-group (None when the head `every` wraps one element)
        self.head_group: Optional[_EveryGroup] = None
        #: mid-pattern grouped everys, in position order
        self.mid_groups: list[_EveryGroup] = []

        chain = self._linearize(sis.state, top=True)
        first = chain[0]
        if isinstance(first, EveryStateElement):
            self.every = True
            inner, gw = _unwrap_chain(first.state)
            inner_list = self._linearize(inner)
            for e in inner_list:
                self._add_element(e, ctx)
            # a GROUP is a multi-element chain (`every (A -> B)`) or a
            # group-scoped within; a single count element (`every A<2:>`)
            # expands to several positions but keeps per-arrival re-arming
            if len(inner_list) > 1 or gw is not None:
                if self.is_sequence:
                    raise SiddhiAppCreationError(
                        "grouped `every ( ... )` inside a SEQUENCE is not "
                        "supported; use a pattern (`->`) instead")
                self.head_group = _EveryGroup(
                    0, len(self.positions) - 1, gw)
            chain = chain[1:]
        for e in chain:
            if isinstance(e, EveryStateElement):
                inner, gw = _unwrap_chain(e.state)
                inner_list = self._linearize(inner)
                if (len(inner_list) == 1 and gw is None
                        and isinstance(inner_list[0],
                                       (StreamStateElement,
                                        AbsentStreamStateElement))):
                    # mid-pattern every over ONE element: the position
                    # becomes STICKY (matches advance a copy, the entry
                    # stays armed)
                    self._add_element(inner_list[0], ctx)
                    self.positions[-1].sticky = True
                    continue
                # mid-pattern grouped every: `A -> every (B->C) -> D` — the
                # group's head entry stays armed; one iteration in flight
                # at a time, re-armed by each completion
                if gw is not None:
                    raise SiddhiAppCreationError(
                        "`within` scoped inside a MID-pattern `every (...)` "
                        "is not supported; apply within to the whole "
                        "pattern")
                if self.is_sequence:
                    raise SiddhiAppCreationError(
                        "mid-sequence `every` is not supported (strict "
                        "contiguity and re-arming conflict); use a pattern "
                        "(`->`) instead")
                head = len(self.positions)
                if head == 0:
                    raise SiddhiAppCreationError(
                        "`every` on the first element is the head form — "
                        "write `from every ...`")
                for el in inner_list:
                    self._add_element(el, ctx)
                end = len(self.positions) - 1
                for p in self.positions[head:end + 1]:
                    if p.kind != "normal" or p.optional:
                        raise SiddhiAppCreationError(
                            "mid-pattern `every ( ... )` groups support "
                            "plain stream elements only in this build")
                self.mid_groups.append(_EveryGroup(head, end, None))
                continue
            self._add_element(e, ctx)
        if not self.positions:
            raise SiddhiAppCreationError("empty pattern")
        if self.positions[0].kind == "notand":
            raise SiddhiAppCreationError(
                "logical absent (`not X and Y`) as the first pattern element "
                "is not yet supported")
        if self.is_sequence and any(p.kind == "notand"
                                    for p in self.positions):
            raise SiddhiAppCreationError(
                "logical absent (`not X and Y`) inside a SEQUENCE is not "
                "supported (strict contiguity and an open-ended absence "
                "conflict); use a pattern (`->`) instead")
        if self.is_sequence and any(p.sticky for p in self.positions):
            raise SiddhiAppCreationError(
                "mid-sequence `every` is not supported (strict contiguity "
                "and re-arming conflict); use a pattern (`->`) instead")
        if self.positions[0].sticky:
            raise SiddhiAppCreationError(
                "`every` on the first element is the head form — write "
                "`from every e1=... -> ...`")

    def _linearize(self, state, top: bool = False) -> list:
        if isinstance(state, tuple) and state and state[0] in ("chain", "seq"):
            # parenthesized group `( ... ) [within t]`: folding the group's
            # within into the plan is exact only when the group IS the whole
            # pattern — partial-scope withins would wrongly constrain the
            # rest
            _tag, inner, within_ms = state
            if within_ms is not None:
                if not top:
                    raise SiddhiAppCreationError(
                        "`within` on a partial pattern group is not "
                        "supported; apply within to the whole pattern")
                if self.within_ms is not None and self.within_ms != within_ms:
                    raise SiddhiAppCreationError(
                        "conflicting `within` scopes")
                self.within_ms = within_ms
            return self._linearize(inner, top=top)
        if isinstance(state, NextStateElement):
            return (self._linearize(state.state)
                    + self._linearize(state.next))
        return [state]

    def _ref_of(self, stream: SingleInputStream, fallback: str) -> str:
        return stream.alias or fallback

    def _add_element(self, e, ctx) -> None:
        i = len(self.positions)
        if isinstance(e, StreamStateElement):
            s = e.stream
            ref = self._ref_of(s, f"_p{i}")
            self.positions.append(_Position(
                i, "normal",
                [_Leg(ref, s.stream_id, tuple(s.handlers.filters))]))
        elif isinstance(e, AbsentStreamStateElement):
            s = e.stream
            if e.waiting_time_ms is None:
                raise SiddhiAppCreationError(
                    "absent patterns need `for <time>` in this build")
            ref = self._ref_of(s, f"_p{i}")
            self.positions.append(_Position(
                i, "absent",
                [_Leg(ref, s.stream_id, tuple(s.handlers.filters))],
                wait_ms=e.waiting_time_ms))
        elif isinstance(e, LogicalStateElement):
            l, r = e.left, e.right
            # `not X and Y` (either order): the absence holds until the AND
            # partner arrives (reference: LogicalAbsentPatternTestCase;
            # AbsentLogicalPreStateProcessor without a waiting time)
            absent = next((s for s in (l, r)
                           if isinstance(s, AbsentStreamStateElement)), None)
            if absent is not None:
                partner = r if absent is l else l
                if not isinstance(partner, StreamStateElement) or \
                        isinstance(partner, AbsentStreamStateElement):
                    raise SiddhiAppCreationError(
                        "logical absent needs exactly one `not` side and one "
                        "plain stream side")
                if e.logical_type != "and":
                    raise SiddhiAppCreationError(
                        "`not X or Y` is not supported in this build; "
                        "use `not X and Y` or split the query")
                aref = self._ref_of(absent.stream, f"_p{i}a")
                pref = self._ref_of(partner.stream, f"_p{i}b")
                # waiting_time_ms set => timed logical absent
                # (`not X for t and Y`): X within [armed, armed+t) kills;
                # the partner may arrive any time; completion fires at
                # max(armed+t, partner ts) once BOTH hold (reference:
                # AbsentLogicalPreStateProcessor with a waiting time —
                # LogicalAbsentPatternTestCase testQueryAbsent5/5_1/6/7/8)
                self.positions.append(_Position(
                    i, "notand",
                    [_Leg(aref, absent.stream.stream_id,
                          tuple(absent.stream.handlers.filters)),
                     _Leg(pref, partner.stream.stream_id,
                          tuple(partner.stream.handlers.filters))],
                    wait_ms=absent.waiting_time_ms))
                return
            if not (isinstance(l, StreamStateElement)
                    and isinstance(r, StreamStateElement)):
                raise SiddhiAppCreationError(
                    "logical patterns combine two plain stream conditions")
            lref = self._ref_of(l.stream, f"_p{i}a")
            rref = self._ref_of(r.stream, f"_p{i}b")
            self.positions.append(_Position(
                i, "logical",
                [_Leg(lref, l.stream.stream_id, tuple(l.stream.handlers.filters)),
                 _Leg(rref, r.stream.stream_id, tuple(r.stream.handlers.filters))],
                logical_op=e.logical_type))
        elif isinstance(e, CountStateElement):
            s = e.element.stream
            base = self._ref_of(s, f"_p{len(self.positions)}")
            lo = e.min_count
            hi = e.max_count
            if hi == CountStateElement.ANY:
                # UNBOUNDED counts (`A<2:>`, sequence `A+`/`A*`) expand to
                # lo + config.pattern_unbounded_count_extra positions — a
                # DOCUMENTED divergence from the reference's unbounded
                # accumulation (CountPreStateProcessor.java): occurrences
                # past the cap are not captured. Warn loudly at plan time
                # (PARITY.md "Known gaps"); raise the config to widen.
                hi = lo + dtypes.config.pattern_unbounded_count_extra
                import warnings
                warnings.warn(
                    f"unbounded pattern count `{base}<{lo}:>` is expanded "
                    f"to at most {hi} occurrences "
                    "(config.pattern_unbounded_count_extra beyond the "
                    "minimum); occurrences past that are NOT captured — "
                    "raise siddhi_tpu.config.pattern_unbounded_count_extra "
                    "if your matches repeat further", stacklevel=2)
            if lo < 0 or hi < max(lo, 1):
                raise SiddhiAppCreationError(f"bad count range <{lo}:{hi}>")
            refs = []
            span_start = len(self.positions)
            for k in range(hi):
                idx = len(self.positions)
                ref = f"{base}[{k}]"
                refs.append(ref)
                # lo == 0 (`A*` / `A?` / `<0:n>`): every position of the
                # group is optional, so entries epsilon straight through
                # (zero occurrences) and the step's startable-position scan
                # lets the pattern BEGIN past the group
                self.positions.append(_Position(
                    idx, "normal",
                    [_Leg(ref, s.stream_id, tuple(s.handlers.filters))],
                    optional=(lo == 0) or k >= lo))
            self.count_groups[base] = refs
            if lo == 0:
                self.zero_min_spans.append(
                    (span_start, len(self.positions) - 1))
        else:
            raise SiddhiAppCreationError(
                f"unsupported pattern element {type(e).__name__}")


class _RefRewriter:
    """Rewrites e1[0].attr / e1[last].attr / bare count refs onto expanded
    position frames."""

    def __init__(self, count_groups: dict[str, list[str]]):
        self.groups = count_groups

    def rewrite(self, expr):
        if expr is None:
            return None
        if isinstance(expr, Variable):
            sid = expr.stream_id
            if sid in self.groups:
                refs = self.groups[sid]
                if expr.is_last:
                    # e1[last].attr = the newest CAPTURED occurrence, which
                    # varies per match when the count has a range (reference:
                    # CountPreStateProcessor last-event semantics). Compile to
                    # an ifThenElse chain over frame validity, newest first.
                    from ..query_api.expression import (AttributeFunction,
                                                        IsNull, Not)
                    out = Variable(expr.attribute, stream_id=refs[0])
                    for ref in refs[1:]:
                        out = AttributeFunction("", "ifThenElse", (
                            Not(IsNull(stream_id=ref)),
                            Variable(expr.attribute, stream_id=ref),
                            out))
                    return out
                elif expr.stream_index is not None:
                    if expr.stream_index >= len(refs):
                        raise SiddhiAppCreationError(
                            f"{sid}[{expr.stream_index}] exceeds count bound")
                    new_sid = refs[expr.stream_index]
                else:
                    new_sid = refs[0]
                return Variable(expr.attribute, stream_id=new_sid)
            return expr
        kwargs = {}
        for a in ("left", "right", "expression"):
            sub = getattr(expr, a, None)
            if isinstance(sub, Expression):
                kwargs[a] = self.rewrite(sub)
        if hasattr(expr, "parameters") and getattr(expr, "parameters", None):
            return dataclasses.replace(expr, parameters=tuple(
                self.rewrite(p) for p in expr.parameters))
        if kwargs:
            return dataclasses.replace(expr, **kwargs)
        return expr


class PendingTable(NamedTuple):
    frames: dict  # {ref: {attr: [P]}}
    frame_valid: dict  # {ref: [P] bool}
    frame_ts: dict  # {ref: [P] int64}
    start_ts: jax.Array  # int64[P]
    last_seq: jax.Array  # int64[P]
    armed_ts: jax.Array  # int64[P]
    valid: jax.Array  # bool[P]
    #: logical positions: per-leg completion. Mid-every GROUP HEAD entries
    #: reuse lane 0 as the iteration-in-flight latch (cleared when the
    #: iteration completes past the group end)
    leg_done: jax.Array  # bool[P, 2]
    #: slot index (in the group head's table) of the context entry that
    #: spawned this in-group iteration entry; -1 outside mid-every groups.
    #: Defaults to None so pre-round-5 snapshots unpickle (restore backfills
    #: from the template — persistence._to_device)
    origin: jax.Array = None  # int32[P]


class PatternState(NamedTuple):
    pending: tuple  # PendingTable per position 1..S-1 (position 0 implicit)
    active0: jax.Array  # bool — start state armed (non-every consumes it)
    seq: jax.Array  # int64 global arrival counter
    sel_state: object
    #: int64 lifetime partial matches dropped: pending-table overflow
    #: (raise config.pattern_pending_capacity) AND sticky-position same-batch
    #: matches past config.pattern_sticky_passes
    dropped: jax.Array
    #: leading-absent arming instant (runtime build time); -2^62 when the
    #: pattern does not start with `not ... for`. Defaults to None so
    #: snapshots pickled before this field existed still unpickle; restore
    #: fills it from the freshly built runtime state (persistence._to_device)
    armed0_ts: jax.Array = None  # int64
    #: head every-group gate: the next iteration may start only with an
    #: arrival seq >= gate0_seq (set to completion seq + 1 when an
    #: iteration finishes — EveryPatternTestCase testQuery5 pairing).
    #: None-default for pre-round-5 snapshot tolerance
    gate0_seq: jax.Array = None  # int64


class PatternQueryRuntime:
    """Runtime for one pattern/sequence query."""

    def __init__(self, query: Query, ctx: SiddhiAppContext, junctions: dict,
                 tables: dict, registry: Registry, name: str) -> None:
        assert isinstance(query.input_stream, StateInputStream)
        sis: StateInputStream = query.input_stream
        self.query = query
        self.ctx = ctx
        self.name = name
        self.registry = registry
        self.callbacks: list[QueryCallback] = []
        self.output_junction = None
        self.table_executor = None
        self.tables = tables
        self.P = dtypes.config.pattern_pending_capacity

        self.plan = _PatternPlan(sis, ctx)
        plan = self.plan
        # Multi-stream sequences: strict contiguity needs ONE arrival order
        # across the participating streams (the reference's sequence
        # receivers consume streams in arrival order). Those queries run off
        # a MERGED junction — source junctions are tapped at send() time so
        # true per-event send order survives micro-batching; the merged
        # batch carries a stream tag + each stream's columns under
        # "<sid>::<attr>" names.
        self.merged_mode = False
        self.merged_junction: Optional[StreamJunction] = None
        self._tag_codes: dict[str, int] = {}
        if plan.is_sequence:
            jset = {leg.stream_id for pos in plan.positions for leg in pos.legs}
            self.merged_mode = len(jset) > 1

        # --- junctions / frames / codecs ---
        self.junctions: dict[str, StreamJunction] = {}
        frames: dict[str, dict] = {}
        codecs: dict[str, StreamCodec] = {}
        self.ref_types: dict[str, dict] = {}
        for pos in plan.positions:
            for leg in pos.legs:
                j = junctions.get(leg.stream_id)
                if j is None:
                    raise DefinitionNotExistError(
                        f"stream {leg.stream_id!r} is not defined")
                self.junctions[leg.stream_id] = j
                attr_types = {a.name: a.type for a in j.definition.attributes
                              if a.type != AttributeType.OBJECT}
                frames[leg.ref] = attr_types
                codecs[leg.ref] = j.codec
                self.ref_types[leg.ref] = attr_types
        if self.merged_mode:
            self._build_merged_junction()

        # bare stream names resolve when unambiguous
        sid_count: dict[str, int] = {}
        for pos in plan.positions:
            for leg in pos.legs:
                sid_count[leg.stream_id] = sid_count.get(leg.stream_id, 0) + 1
        for sid, n in sid_count.items():
            if n == 1 and sid not in frames:
                for pos in plan.positions:
                    for leg in pos.legs:
                        if leg.stream_id == sid:
                            frames[sid] = frames[leg.ref]
                            codecs[sid] = codecs[leg.ref]

        rewriter = _RefRewriter(plan.count_groups)
        # unionSet-projection provenance per leg frame (see expr_compile)
        set_projections = {}
        for pos in plan.positions:
            for leg in pos.legs:
                j = self.junctions[leg.stream_id]
                sp = {a.name for a in j.definition.attributes
                      if getattr(a, "set_projection", False)}
                if sp:
                    set_projections[leg.ref] = sp
        self.resolver = TypeResolver(frames, plan.positions[0].legs[0].ref,
                                     codecs, set_projections)

        # --- compile per-leg conditions (unqualified attrs resolve to the
        # leg's own arrival frame, like the reference's per-state meta) ---
        for pos in plan.positions:
            for leg in pos.legs:
                leg_resolver = TypeResolver(frames, leg.ref, codecs,
                                            set_projections)
                leg.compiled = [
                    compile_expression(rewriter.rewrite(f), leg_resolver, registry)
                    for f in leg.filters]

        # --- selector over all captured frames ---
        select_all = []
        seen = set()
        for pos in plan.positions:
            for leg in pos.legs:
                for n, t in self.ref_types[leg.ref].items():
                    if n not in seen:
                        seen.add(n)
                        select_all.append((n, t))
        sel = query.selector
        sel = dataclasses.replace(
            sel,
            attributes=tuple(dataclasses.replace(a, expression=rewriter.rewrite(a.expression))
                             for a in sel.attributes),
            having=rewriter.rewrite(sel.having),
            group_by=tuple(rewriter.rewrite(g) for g in sel.group_by))
        self.selector = CompiledSelector(
            sel, self.resolver, registry, ctx.effective_group_capacity,
            plan.positions[0].legs[0].ref, select_all_attrs=select_all)

        self.output_attributes = tuple(
            Attribute(n, t,
                      set_projection=n in self.selector.host_set_slots)
            for n, t in self.selector.out_types.items())
        self.output_definition = StreamDefinition(
            id=query.output_stream.target_id or f"{name}_out",
            attributes=self.output_attributes)
        self.output_codec = StreamCodec(self.output_definition, ctx.global_strings)

        # --- state & jitted steps (one per junction + heartbeat) ---
        self.state = self._init_state()
        if self.merged_mode:
            self._steps = {MERGED_SID: jax.jit(
                self._make_step(MERGED_SID), donate_argnums=(0,))}
        else:
            self._steps = {
                sid: jax.jit(self._make_step(sid), donate_argnums=(0,))
                for sid in self.junctions
            }
        self._heartbeat_step = jax.jit(self._make_step(None), donate_argnums=(0,))
        self.has_time_semantics = (
            plan.within_ms is not None
            or (plan.head_group is not None
                and plan.head_group.within_ms is not None)
            or any(p.kind == "absent" or
                   (p.kind == "notand" and p.wait_ms is not None)
                   for p in plan.positions))

    # ---------------------------------------------------------- merged stream

    def _build_merged_junction(self) -> None:
        """One tagged union junction over the sequence's source streams, fed
        by send-order taps so strict contiguity sees the true interleave."""
        participants = []
        for pos in self.plan.positions:
            for leg in pos.legs:
                if leg.stream_id not in participants:
                    participants.append(leg.stream_id)
        self._tag_codes = {sid: i for i, sid in enumerate(participants)}
        attrs = [Attribute("_tag", AttributeType.INT)]
        self._merged_slots: dict[str, tuple[int, list[int]]] = {}
        pad_of = {AttributeType.STRING: "", AttributeType.BOOL: False}
        pads: list = []
        for sid in participants:
            j = self.junctions[sid]
            src_idx = []
            base = len(attrs) - 1  # offset into the padded tail
            for i, a in enumerate(j.definition.attributes):
                if a.type == AttributeType.OBJECT:
                    continue
                attrs.append(Attribute(f"{sid}::{a.name}", a.type))
                src_idx.append(i)
                pads.append(pad_of.get(a.type, 0))
            self._merged_slots[sid] = (base, src_idx)
        merged_def = StreamDefinition(id=f"#seq:{self.name}",
                                      attributes=tuple(attrs))
        self.merged_junction = StreamJunction(merged_def, self.ctx)
        self._merged_pads = tuple(pads)

        for sid in participants:
            code = self._tag_codes[sid]
            base, src_idx = self._merged_slots[sid]
            merged = self.merged_junction

            def tap(ts, data, code=code, base=base, src_idx=src_idx,
                    merged=merged):
                tail = list(self._merged_pads)
                for k, i in enumerate(src_idx):
                    tail[base + k] = data[i]
                # single atomic append (GIL) — taps run on producer threads
                merged.stage_row(ts, (code, *tail))

            self.junctions[sid].taps.append(tap)

    def _leg_batch(self, batch: EventBatch, leg) -> EventBatch:
        """The leg's view of the incoming batch: identity on per-junction
        steps; tag-masked de-prefixed columns on the merged sequence step."""
        if not self.merged_mode:
            return batch
        code = self._tag_codes[leg.stream_id]
        cols = {a: batch.cols[f"{leg.stream_id}::{a}"]
                for a in self.ref_types[leg.ref]}
        valid = batch.valid & (batch.cols["_tag"] == code)
        return EventBatch(ts=batch.ts, cols=cols, valid=valid,
                          types=batch.types)

    # ------------------------------------------------------------------ state

    def _captured_refs(self, pos_index: int) -> list[str]:
        """Frame refs captured before reaching position pos_index (all legs of
        earlier positions)."""
        refs = []
        for pos in self.plan.positions[:pos_index]:
            for leg in pos.legs:
                refs.append(leg.ref)
        # logical (and timed logical-absent) positions also capture their
        # own legs progressively
        pos = self.plan.positions[pos_index]
        if pos.kind == "logical" or (pos.kind == "notand"
                                     and pos.wait_ms is not None):
            for leg in pos.legs:
                refs.append(leg.ref)
        return refs

    def _empty_pending(self, pos_index: int) -> PendingTable:
        P = self.P
        frames = {}
        fvalid = {}
        fts = {}
        for ref in self._captured_refs(pos_index):
            frames[ref] = {
                n: jnp.zeros((P,), dtypes.device_dtype(t))
                for n, t in self.ref_types[ref].items()}
            fvalid[ref] = jnp.zeros((P,), bool)
            fts[ref] = jnp.zeros((P,), dtypes.TS_DTYPE)
        return PendingTable(
            frames=frames, frame_valid=fvalid, frame_ts=fts,
            start_ts=jnp.zeros((P,), dtypes.TS_DTYPE),
            last_seq=jnp.zeros((P,), jnp.int64),
            armed_ts=jnp.zeros((P,), dtypes.TS_DTYPE),
            valid=jnp.zeros((P,), bool),
            leg_done=jnp.zeros((P, 2), bool),
            origin=jnp.full((P,), -1, jnp.int32),
        )

    def _init_state(self) -> PatternState:
        S = len(self.plan.positions)
        leading_absent = self.plan.positions[0].kind == "absent"
        return PatternState(
            pending=tuple(self._empty_pending(p) for p in range(1, S)),
            active0=jnp.bool_(True),
            seq=jnp.int64(0),
            sel_state=self.selector.init_state(),
            dropped=jnp.int64(0),
            armed0_ts=jnp.int64(
                (-1 if self.ctx.playback
                 else self.ctx.timestamp_generator.current_time())
                if leading_absent else -(2 ** 62)),
            gate0_seq=jnp.int64(0),
        )

    # ------------------------------------------------------------------- step

    def _leg_cond(self, leg, batch: EventBatch, pend: Optional[PendingTable],
                  now) -> jax.Array:
        """[B,P] (or [B,1] for position 0) filter mask for one leg."""
        B = batch.ts.shape[0]
        scope = Scope()
        cols_b = {k: v[:, None] for k, v in batch.cols.items()}
        scope.add_frame(leg.ref, cols_b, batch.ts[:, None],
                        batch.valid[:, None], default=True)
        # bare stream name alias
        scope.frames.setdefault(leg.stream_id, cols_b)
        scope.valids.setdefault(leg.stream_id, batch.valid[:, None])
        scope.ts.setdefault(leg.stream_id, batch.ts[:, None])
        if pend is not None:
            for ref, cols in pend.frames.items():
                if ref == leg.ref:
                    # logical positions capture their OWN legs in the pending
                    # table; the leg's frame here must stay the ARRIVING
                    # event, not the (possibly empty) capture — otherwise a
                    # leg filter evaluates against zeros and never matches
                    continue
                scope.add_frame(ref, cols, pend.frame_ts[ref],
                                pend.frame_valid[ref])
        scope.extras["now"] = now
        m = batch.valid[:, None]
        for ce in leg.compiled:
            m = m & ce(scope)
        P = pend.valid.shape[0] if pend is not None else 1
        return jnp.broadcast_to(m, (B, P))

    def _make_step(self, junction_sid: Optional[str]):
        plan = self.plan
        selector = self.selector
        stats = self.ctx.statistics
        qname = self.name
        S = len(plan.positions)
        P = self.P
        within = plan.within_ms
        is_seq = plan.is_sequence
        every = plan.every

        hg = plan.head_group
        mid_heads = {g.head: g for g in plan.mid_groups}
        # positions where a NEW match may begin: 0, plus the position after
        # each leading zero-minimum count group (`A*, B`: a B with zero A's
        # starts the match at B). Groups (every (...)) exclude themselves.
        startable = {0}
        _idx = 0
        for _s0, _e0 in plan.zero_min_spans:
            if _s0 != _idx:
                break
            _idx = _e0 + 1
            if _idx < S:
                in_group = (hg is not None and _idx <= hg.end) or any(
                    g.head <= _idx <= g.end for g in plan.mid_groups)
                if not in_group:
                    startable.add(_idx)

        def step(state: PatternState, batch: EventBatch, now):
            # trace-time: per-query compile counter (see Statistics)
            stats.track_compile(qname, batch.ts.shape[0])
            pending = list(state.pending)
            active0_box = [state.active0]
            gate0_box = [state.gate0_seq if state.gate0_seq is not None
                         else jnp.int64(0)]
            B = batch.ts.shape[0]

            n_valid = jnp.sum(batch.valid.astype(jnp.int64))
            # arrival sequence per lane (valid lanes, in lane order)
            lane_rank = jnp.cumsum(batch.valid.astype(jnp.int64)) - 1
            arr_seq = jnp.where(batch.valid, state.seq + lane_rank, BIGSEQ)

            # collected outputs: one block per completion source
            out_blocks = []  # (frames {ref: cols}, fvalid {ref}, fts, ts, valid)
            drop_acc = [jnp.int64(0)]  # pending-table insert overflow
            armed0_out = [state.armed0_ts]  # leading-absent lazy arming
            gate_ctx = {"active0": active0_box, "gate0": gate0_box}

            def expire(pend: PendingTable, pos_index: int) -> PendingTable:
                gw = (hg.within_ms if hg is not None
                      and hg.head < pos_index <= hg.end else None)
                if within is None and gw is None:
                    return pend
                ok = pend.valid
                if within is not None:
                    ok = ok & (now - pend.start_ts <= jnp.int64(within))
                if gw is not None:
                    # within scoped INSIDE `every (...)`: bounds each
                    # ITERATION (start_ts = the iteration's first capture)
                    ok = ok & (now - pend.start_ts <= jnp.int64(gw))
                died = pend.valid & ~ok
                if hg is not None and hg.head < pos_index <= hg.end:
                    # the in-flight head-group iteration expired: re-arm
                    # the gate or the every-loop would stall forever
                    active0_box[0] = active0_box[0] | died.any()
                for g in plan.mid_groups:
                    if g.head < pos_index <= g.end:
                        # clear the origin context entry's busy latch
                        P_ = pend.valid.shape[0]
                        o = jnp.where(died & (pend.origin >= 0),
                                      pend.origin, P_)
                        head_tbl = pending[g.head - 1]
                        pending[g.head - 1] = head_tbl._replace(
                            leg_done=head_tbl.leg_done.at[o, 0].set(
                                False, mode="drop"))
                return pend._replace(valid=ok)

            # in place: expire() may clear busy latches on EARLIER tables
            # (mid-every origins), which a rebinding comprehension would
            # discard
            for _i in range(len(pending)):
                pending[_i] = expire(pending[_i], _i + 1)

            merged = junction_sid == MERGED_SID

            def begin_at(pi: int, pos):
                """Start NEW match entries at position pi (pi=0, or a
                startable position past leading zero-min optionals): one
                shared protocol for gate + start-state consumption."""
                leg = pos.legs[0]
                leg_b = self._leg_batch(batch, leg)
                m = self._leg_cond(leg, leg_b, None, now)[:, 0]  # [B]
                gated = hg is not None and pi == 0
                if not every or gated:
                    # non-every: only the first match consumes the start
                    # state. Grouped head-every: the gate admits ONE
                    # iteration at a time — the first qualifying arrival
                    # past the previous completion's seq starts it, and
                    # the gate re-opens when the iteration leaves the
                    # group (EveryPatternTestCase testQuery5 pairing)
                    a0 = active0_box[0]
                    if gated:
                        m = m & (arr_seq >= gate0_box[0])
                    mseq = jnp.where(m, arr_seq, BIGSEQ)
                    only = jnp.zeros((B,), bool).at[jnp.argmin(mseq)].set(
                        True)
                    m = m & only & a0
                    active0_box[0] = a0 & ~m.any()
                frames = {leg.ref: dict(leg_b.cols)}
                fvalid = {leg.ref: m}
                fts = {leg.ref: batch.ts}
                for pos_e in plan.positions[:pi]:  # skipped zero-min refs
                    for lg in pos_e.legs:
                        frames[lg.ref] = {
                            n: jnp.zeros((B,), dtypes.device_dtype(t))
                            for n, t in self.ref_types[lg.ref].items()}
                        fvalid[lg.ref] = jnp.zeros((B,), bool)
                        fts[lg.ref] = jnp.zeros((B,), dtypes.TS_DTYPE)
                self._advance(pending, out_blocks, pi + 1, frames, fvalid,
                              fts, batch.ts, arr_seq, batch.ts, m, drop_acc,
                              gate_ctx=gate_ctx)

            def process_position(pi: int):
                pos = plan.positions[pi]
                active0 = active0_box[0]
                pend = pending[pi - 1] if pi > 0 else None
                feeds = junction_sid is not None and (merged or any(
                    leg.stream_id == junction_sid for leg in pos.legs))

                # ---- absent completion (time-driven, runs on every step) ----
                if pos.kind == "absent" and pi > 0:
                    due = pend.valid & (now >= pend.armed_ts +
                                        jnp.int64(pos.wait_ms))
                    killed_late = jnp.zeros_like(pend.valid)
                    if junction_sid is not None and \
                            (merged or pos.legs[0].stream_id == junction_sid):
                        # a matching event kills waiting entries first
                        kill = self._leg_cond(
                            pos.legs[0], self._leg_batch(batch, pos.legs[0]),
                            pend, now)
                        kill = kill & (arr_seq[:, None] > pend.last_seq[None, :])
                        in_period = (batch.ts[:, None] <
                                     pend.armed_ts[None, :] + jnp.int64(pos.wait_ms))
                        killed = (kill & in_period).any(axis=0)
                        # a match PAST the deadline lands in the NEXT (sticky
                        # re-armed) period: the completed period still fires,
                        # then the arming is consumed
                        killed_late = (kill & ~in_period).any(axis=0)
                        pend = pend._replace(valid=pend.valid & ~killed)
                        due = due & ~killed
                    # completions advance with an invalid (absent) frame
                    comp_frames = dict(pend.frames)
                    comp_fvalid = dict(pend.frame_valid)
                    comp_fts = dict(pend.frame_ts)
                    ref = pos.legs[0].ref
                    comp_frames[ref] = {
                        n: jnp.zeros((P,), dtypes.device_dtype(t))
                        for n, t in self.ref_types[ref].items()}
                    comp_fvalid[ref] = jnp.zeros((P,), bool)
                    comp_fts[ref] = jnp.zeros((P,), dtypes.TS_DTYPE)
                    comp_ts = pend.armed_ts + jnp.int64(pos.wait_ms)
                    self._advance(
                        pending, out_blocks, pi + 1,
                        comp_frames, comp_fvalid, comp_fts,
                        jnp.where(pend.valid, pend.start_ts, 0),
                        pend.last_seq, comp_ts, due, drop_acc,
                        origin=pend.origin, gate_ctx=gate_ctx)
                    if pos.sticky:
                        # `-> every not X for t`: one fire per elapsed quiet
                        # period — re-arm for the next period; a matching
                        # arrival consumes the arming permanently
                        # (EveryAbsentPatternTestCase testQueryAbsent4),
                        # whether it landed in the current period (killed
                        # above) or past its deadline (killed_late). A step
                        # crossing several periods fires once and catches
                        # up on later steps (batch granularity).
                        pend = pend._replace(
                            armed_ts=jnp.where(
                                due, pend.armed_ts + jnp.int64(pos.wait_ms),
                                pend.armed_ts),
                            valid=pend.valid & ~killed_late)
                    else:
                        pend = pend._replace(valid=pend.valid & ~due)
                    pending[pi - 1] = pend
                    return

                # ---- timed logical absent: `not X for t and Y` ---------
                # X within [armed, armed+t) kills the entry; the partner Y
                # may arrive before OR after the deadline (captured either
                # way); the match fires at max(armed+t, Y ts) once the
                # period elapses un-killed AND Y is captured (reference:
                # AbsentLogicalPreStateProcessor with waiting time —
                # LogicalAbsentPatternTestCase testQueryAbsent5/5_1/6/7/8).
                # Time-driven completion: runs on every step incl.
                # heartbeats.
                if pos.kind == "notand" and pos.wait_ms is not None \
                        and pi > 0:
                    a_leg, p_leg = pos.legs
                    Pn = pend.valid.shape[0]
                    deadline = pend.armed_ts + jnp.int64(pos.wait_ms)
                    if junction_sid is not None and (
                            merged or a_leg.stream_id == junction_sid):
                        kq = self._leg_cond(
                            a_leg, self._leg_batch(batch, a_leg), pend, now)
                        kq = kq & (arr_seq[:, None] > pend.last_seq[None, :])
                        kq = kq & (batch.ts[:, None] < deadline[None, :])
                        killed = kq.any(axis=0) & pend.valid
                        pend = pend._replace(valid=pend.valid & ~killed)
                    if junction_sid is not None and (
                            merged or p_leg.stream_id == junction_sid):
                        leg_b = self._leg_batch(batch, p_leg)
                        q = self._leg_cond(p_leg, leg_b, pend, now)
                        q = q & pend.valid[None, :] \
                            & ~pend.leg_done[:, 1][None, :] \
                            & (arr_seq[:, None] > pend.last_seq[None, :])
                        if within is not None:
                            q = q & (batch.ts[:, None]
                                     - pend.start_ts[None, :]
                                     <= jnp.int64(within))
                        qseq = jnp.where(q, arr_seq[:, None], BIGSEQ)
                        b_star = jnp.argmin(qseq, axis=0)
                        matched = q.any(axis=0)
                        cap = {n: v[b_star] for n, v in leg_b.cols.items()}
                        cap_ts = batch.ts[b_star]
                        nf = dict(pend.frames)
                        nfv = dict(pend.frame_valid)
                        nft = dict(pend.frame_ts)
                        nf[p_leg.ref] = {
                            n: jnp.where(matched, cap[n],
                                         pend.frames[p_leg.ref][n])
                            for n in cap}
                        nfv[p_leg.ref] = pend.frame_valid[p_leg.ref] | matched
                        nft[p_leg.ref] = jnp.where(
                            matched, cap_ts, pend.frame_ts[p_leg.ref])
                        pend = pend._replace(
                            frames=nf, frame_valid=nfv, frame_ts=nft,
                            leg_done=pend.leg_done.at[:, 1].set(
                                pend.leg_done[:, 1] | matched),
                            last_seq=jnp.where(
                                matched,
                                jnp.maximum(arr_seq[b_star], pend.last_seq),
                                pend.last_seq))
                    due = pend.valid & pend.leg_done[:, 1] & (now >= deadline)
                    comp_frames = dict(pend.frames)
                    comp_fv = dict(pend.frame_valid)
                    comp_ft = dict(pend.frame_ts)
                    aref = a_leg.ref
                    comp_frames[aref] = {
                        n: jnp.zeros((Pn,), dtypes.device_dtype(t))
                        for n, t in self.ref_types[aref].items()}
                    comp_fv[aref] = jnp.zeros((Pn,), bool)
                    comp_ft[aref] = jnp.zeros((Pn,), dtypes.TS_DTYPE)
                    comp_ts = jnp.maximum(deadline,
                                          pend.frame_ts[p_leg.ref])
                    new_pend = pend._replace(valid=pend.valid & ~due)
                    pending[pi - 1] = new_pend
                    self._advance(
                        pending, out_blocks, pi + 1,
                        comp_frames, comp_fv, comp_ft,
                        jnp.where(due, pend.start_ts, 0),
                        pend.last_seq, comp_ts, due, drop_acc,
                        origin=pend.origin, gate_ctx=gate_ctx)
                    return

                # ---- leading absent: `not S1 for t -> ...` -------------
                # armed once at runtime build (armed0_ts); a matching
                # arrival before the deadline kills the arming, the
                # deadline passing advances an empty-frame entry to
                # position 1. Granularity: arrivals in the SAME micro-batch
                # as the elapse may match position 1 regardless of their
                # intra-batch order (documented batch-granularity).
                if pos.kind == "absent" and pi == 0:
                    # playback (virtual time) arms LAZILY at the first
                    # observed instant — epoch-timestamp replays must not
                    # measure the quiet period from virtual 0 (which would
                    # both fire spuriously and disarm the kill); realtime
                    # arms at runtime build (reference: query start)
                    first_ts = jnp.min(jnp.where(
                        batch.valid, batch.ts, jnp.int64(2 ** 62)))
                    armed0 = jnp.where(
                        state.armed0_ts >= 0, state.armed0_ts,
                        jnp.minimum(first_ts, now))
                    deadline = armed0 + jnp.int64(pos.wait_ms)
                    km_any = jnp.bool_(False)
                    km_late_any = jnp.bool_(False)
                    kill_ts = jnp.int64(-(2 ** 62))
                    if junction_sid is not None and (
                            merged or pos.legs[0].stream_id == junction_sid):
                        leg0 = pos.legs[0]
                        km_all = self._leg_cond(
                            leg0, self._leg_batch(batch, leg0), None,
                            now)[:, 0]
                        km = km_all & (batch.ts < deadline)
                        km_any = km.any()
                        # a match past the deadline breaks the NEXT period
                        # (the completed one still fires below); measurement
                        # restarts from the latest matching arrival
                        km_late_any = (km_all & ~(batch.ts < deadline)).any()
                        kill_ts = jnp.max(jnp.where(
                            km_all, batch.ts, jnp.int64(-(2 ** 62))))
                    due = active0 & ~km_any & (now >= deadline)
                    ref = pos.legs[0].ref
                    ins_valid = jnp.zeros((P,), bool).at[0].set(due)
                    frames = {ref: {
                        n: jnp.zeros((P,), dtypes.device_dtype(t))
                        for n, t in self.ref_types[ref].items()}}
                    fvalid = {ref: jnp.zeros((P,), bool)}
                    fts = {ref: jnp.zeros((P,), dtypes.TS_DTYPE)}
                    self._advance(
                        pending, out_blocks, 1, frames, fvalid, fts,
                        jnp.full((P,), deadline),
                        jnp.full((P,), state.seq - 1),
                        jnp.full((P,), deadline), ins_valid, drop_acc,
                        gate_ctx=gate_ctx)
                    if every:
                        # `every not X for t -> ...`: perpetual quiet-period
                        # monitor (EveryAbsentPatternTestCase testQueryAbsent5
                        # — one entry advances per elapsed period) — re-arm
                        # at each fired boundary; a matching arrival (in the
                        # current period OR past its deadline) restarts
                        # measurement from its own timestamp
                        armed0 = jnp.where(
                            km_any | km_late_any, kill_ts,
                            jnp.where(due, deadline, armed0))
                    else:
                        active0_box[0] = active0 & ~km_any & ~due
                    armed0_out[0] = armed0
                    return

                if not feeds:
                    return

                # ---- normal / logical positions fed by this junction ----
                if pi == 0:
                    # virtual empty pending: [B,1]
                    if pos.kind == "logical":
                        raise SiddhiAppCreationError(
                            "logical conditions at the first pattern position "
                            "are not yet supported")
                    if not merged and pos.legs[0].stream_id != junction_sid:
                        return
                    begin_at(pi, pos)
                    return

                # ---- logical absent: `not X and Y` ---------------------
                # the absence holds until the AND partner arrives: an X
                # earlier than the first qualifying Y kills the entry, a Y
                # earlier than any X advances it (absent frame rides empty,
                # reference AbsentLogicalPreStateProcessor without a timer)
                if pos.kind == "notand":
                    pend = pending[pi - 1]
                    Pn = pend.valid.shape[0]
                    a_leg, p_leg = pos.legs
                    kseq = jnp.full((Pn,), BIGSEQ)
                    if merged or a_leg.stream_id == junction_sid:
                        kq = self._leg_cond(
                            a_leg, self._leg_batch(batch, a_leg), pend, now)
                        kq = kq & (arr_seq[:, None] > pend.last_seq[None, :])
                        kseq = jnp.min(jnp.where(kq, arr_seq[:, None],
                                                 BIGSEQ), axis=0)
                    pseq = jnp.full((Pn,), BIGSEQ)
                    b_star = jnp.zeros((Pn,), jnp.int64)
                    leg_b = None
                    if merged or p_leg.stream_id == junction_sid:
                        leg_b = self._leg_batch(batch, p_leg)
                        q = self._leg_cond(p_leg, leg_b, pend, now)
                        q = q & pend.valid[None, :] & (
                            arr_seq[:, None] > pend.last_seq[None, :])
                        if within is not None:
                            q = q & (batch.ts[:, None] - pend.start_ts[None, :]
                                     <= jnp.int64(within))
                        qs = jnp.where(q, arr_seq[:, None], BIGSEQ)
                        b_star = jnp.argmin(qs, axis=0)
                        pseq = jnp.min(qs, axis=0)
                    advanced = pend.valid & (pseq < kseq)
                    killed = pend.valid & (kseq < BIGSEQ) & ~advanced
                    if leg_b is not None:
                        cap = {n: v[b_star] for n, v in leg_b.cols.items()}
                        cap_ts = batch.ts[b_star]
                        ins_frames = dict(pend.frames)
                        ins_fvalid = dict(pend.frame_valid)
                        ins_fts = dict(pend.frame_ts)
                        ins_frames[p_leg.ref] = cap
                        ins_fvalid[p_leg.ref] = advanced
                        ins_fts[p_leg.ref] = cap_ts
                        ins_frames[a_leg.ref] = {
                            n: jnp.zeros((Pn,), dtypes.device_dtype(t))
                            for n, t in self.ref_types[a_leg.ref].items()}
                        ins_fvalid[a_leg.ref] = jnp.zeros((Pn,), bool)
                        ins_fts[a_leg.ref] = jnp.zeros((Pn,),
                                                       dtypes.TS_DTYPE)
                        pending[pi - 1] = pend._replace(
                            valid=pend.valid & ~(advanced | killed))
                        self._advance(
                            pending, out_blocks, pi + 1,
                            ins_frames, ins_fvalid, ins_fts,
                            jnp.where(advanced, pend.start_ts, 0),
                            jnp.where(advanced,
                                      jnp.maximum(pseq, pend.last_seq),
                                      pend.last_seq),
                            cap_ts, advanced, drop_acc,
                            origin=pend.origin, gate_ctx=gate_ctx)
                    else:
                        pending[pi - 1] = pend._replace(
                            valid=pend.valid & ~killed)
                    return

                def _joint_kill(pi=pi, pos=pos):
                    # strict kill computed JOINTLY over both legs (the next
                    # arrival may legitimately match EITHER remaining leg);
                    # re-run before every leg pass so a breaker that becomes
                    # "next" after an in-batch leg match is still caught
                    pend = pending[pi - 1]
                    q_any = jnp.zeros(
                        (B, pend.valid.shape[0]), bool)
                    for lj, lg in enumerate(pos.legs):
                        if not merged and lg.stream_id != junction_sid:
                            continue
                        ql = self._leg_cond(lg, self._leg_batch(batch, lg),
                                            pend, now)
                        q_any = q_any | (ql & ~pend.leg_done[None, :, lj])
                    nxt = (arr_seq[:, None] == pend.last_seq[None, :] + 1) \
                        & batch.valid[:, None]
                    killed = (nxt & ~q_any).any(axis=0) & pend.valid
                    pending[pi - 1] = pend._replace(
                        valid=pend.valid & ~killed)

                #: ordering snapshot for pattern-mode logical legs — sibling
                #: matches in this batch must not block the other leg's
                #: earlier arrival (legs complete in either order)
                if (pi in startable and pi > 0 and pos.kind == "normal"
                        and (merged
                             or pos.legs[0].stream_id == junction_sid)):
                    # zero-occurrence leading optionals: this arrival may
                    # BEGIN a match here (skipped refs ride as absent
                    # frames, like the reference's unsatisfied optional
                    # count states)
                    begin_at(pi, pos)

                pend0 = pending[pi - 1]
                mid_g = mid_heads.get(pi)
                leg_iters = list(enumerate(pos.legs))
                if is_seq and pos.kind == "logical":
                    # two passes: with strict contiguity, the second leg's
                    # arrival only becomes reachable (last_seq+1) after the
                    # first leg matched — which may happen later in THIS
                    # batch when arrivals came in the opposite leg order
                    leg_iters = leg_iters * 2
                if pos.sticky:
                    # sticky (mid-pattern every): each pass advances one
                    # more qualifying arrival per entry; arrivals beyond
                    # the pass bound in ONE batch are counted into
                    # `dropped` (monitored; cross-batch repetition is exact)
                    leg_iters = leg_iters * dtypes.config.pattern_sticky_passes
                for li, leg in leg_iters:
                    if is_seq and pos.kind == "logical":
                        _joint_kill()
                    if not merged and leg.stream_id != junction_sid:
                        continue
                    pend = pending[pi - 1]
                    leg_b = self._leg_batch(batch, leg)
                    q = self._leg_cond(leg, leg_b, pend, now)  # [B,P]
                    q = q & pend.valid[None, :]
                    if mid_g is not None:
                        # mid-every group head: an entry with an iteration
                        # in flight (busy latch) does not start another —
                        # re-armed when the iteration completes past the
                        # group end (_advance gate hook)
                        q = q & ~pend.leg_done[:, 0][None, :]
                    if is_seq:
                        q = q & (arr_seq[:, None] == pend.last_seq[None, :] + 1)
                    elif pos.kind == "logical":
                        q = q & (arr_seq[:, None] > pend0.last_seq[None, :])
                    else:
                        q = q & (arr_seq[:, None] > pend.last_seq[None, :])
                    if within is not None:
                        q = q & (batch.ts[:, None] - pend.start_ts[None, :]
                                 <= jnp.int64(within))

                    if is_seq and pos.kind != "logical":
                        # strict: an arrival at seq == last_seq+1 that does NOT
                        # match kills the entry
                        nxt = (arr_seq[:, None] == pend.last_seq[None, :] + 1) \
                            & batch.valid[:, None]
                        killed = (nxt & ~q).any(axis=0)
                        pend = pend._replace(valid=pend.valid & ~killed)
                        q = q & pend.valid[None, :]

                    # first qualifying arrival per entry
                    qseq = jnp.where(q, arr_seq[:, None], BIGSEQ)
                    b_star = jnp.argmin(qseq, axis=0)  # [P]
                    matched = q.any(axis=0)

                    cap = {n: v[b_star] for n, v in leg_b.cols.items()}
                    cap_ts = batch.ts[b_star]

                    if pos.kind == "logical":
                        other = 1 - li
                        # logical positions persist their legs in their own
                        # pending table (both legs are captured refs)
                        new_frames = dict(pend.frames)
                        new_fvalid = dict(pend.frame_valid)
                        new_fts = dict(pend.frame_ts)
                        new_frames[leg.ref] = {
                            n: jnp.where(matched, cap[n],
                                         pend.frames[leg.ref][n])
                            for n in cap}
                        new_fvalid[leg.ref] = pend.frame_valid[leg.ref] | matched
                        new_fts[leg.ref] = jnp.where(
                            matched, cap_ts, pend.frame_ts[leg.ref])
                        complete = (
                            matched if pos.logical_op == "or"
                            else (matched & pend.leg_done[:, other]))
                        pend = pend._replace(
                            frames=new_frames, frame_valid=new_fvalid,
                            frame_ts=new_fts,
                            leg_done=pend.leg_done.at[:, li].set(
                                pend.leg_done[:, li] | matched),
                            last_seq=jnp.where(
                                matched,
                                jnp.maximum(arr_seq[b_star], pend.last_seq),
                                pend.last_seq))
                        adv_valid = complete
                        ins_frames = pend.frames
                        ins_fvalid = pend.frame_valid
                        ins_fts = pend.frame_ts
                        consumed = complete
                        comp_ts = jnp.where(matched, cap_ts, pend.armed_ts)
                        pending[pi - 1] = pend._replace(
                            valid=pend.valid & ~consumed)
                    else:
                        # carry captured frames + the new arrival frame into
                        # the advance; pend's own structure is untouched
                        ins_frames = dict(pend.frames)
                        ins_fvalid = dict(pend.frame_valid)
                        ins_fts = dict(pend.frame_ts)
                        ins_frames[leg.ref] = cap
                        ins_fvalid[leg.ref] = matched
                        ins_fts[leg.ref] = cap_ts
                        adv_valid = matched
                        comp_ts = cap_ts
                        if pos.sticky:
                            # the entry stays armed; bumping last_seq lets
                            # the next pass advance the NEXT arrival
                            pending[pi - 1] = pend._replace(
                                last_seq=jnp.where(
                                    matched,
                                    jnp.maximum(arr_seq[b_star],
                                                pend.last_seq),
                                    pend.last_seq))
                        elif mid_g is not None:
                            # group-head context entry stays armed but
                            # busy-latched until this iteration completes
                            pending[pi - 1] = pend._replace(
                                leg_done=pend.leg_done.at[:, 0].set(
                                    pend.leg_done[:, 0] | matched),
                                last_seq=jnp.where(
                                    matched,
                                    jnp.maximum(arr_seq[b_star],
                                                pend.last_seq),
                                    pend.last_seq))
                        else:
                            pending[pi - 1] = pend._replace(
                                valid=pend.valid & ~matched)

                    adv_origin = (
                        jnp.arange(pend.valid.shape[0], dtype=jnp.int32)
                        if mid_g is not None else pend.origin)
                    self._advance(
                        pending, out_blocks, pi + 1,
                        ins_frames, ins_fvalid, ins_fts,
                        jnp.where(adv_valid, pend.start_ts, 0),
                        jnp.where(adv_valid,
                                  jnp.maximum(arr_seq[b_star], pend.last_seq),
                                  pend.last_seq),
                        comp_ts, adv_valid, drop_acc,
                        origin=adv_origin, gate_ctx=gate_ctx)

                if pos.sticky and (merged or
                                   pos.legs[0].stream_id == junction_sid):
                    # qualifying arrivals beyond the per-batch pass bound:
                    # counted as dropped (monitored truncation; raise
                    # config.pattern_sticky_passes or shrink batches)
                    pend = pending[pi - 1]
                    leg0 = pos.legs[0]
                    q_left = self._leg_cond(
                        leg0, self._leg_batch(batch, leg0), pend, now)
                    q_left = q_left & pend.valid[None, :] & (
                        arr_seq[:, None] > pend.last_seq[None, :])
                    if within is not None:
                        # arrivals outside the within window could never
                        # match — they are not truncation
                        q_left = q_left & (
                            batch.ts[:, None] - pend.start_ts[None, :]
                            <= jnp.int64(within))
                    drop_acc[0] = drop_acc[0] + jnp.sum(
                        q_left, dtype=jnp.int64)

            pi = 0
            while pi < S:
                g = hg if (hg is not None and pi == hg.head) else \
                    mid_heads.get(pi)
                if g is not None:
                    # every-group: several passes so iterations can chain
                    # start -> complete -> re-arm -> start within ONE
                    # micro-batch (bounded by pattern_sticky_passes;
                    # leftovers land in the `dropped` monitor below)
                    for _pass in range(dtypes.config.pattern_sticky_passes):
                        for pj in range(g.head, g.end + 1):
                            process_position(pj)
                    # iteration starts beyond the pass bound are LOST for
                    # this batch (events are not buffered): count them into
                    # the monitored `dropped` so operators see the
                    # truncation and can raise pattern_sticky_passes
                    head_pos = plan.positions[g.head]
                    leg0 = head_pos.legs[0]
                    if junction_sid is not None and (
                            merged or leg0.stream_id == junction_sid):
                        if g is hg:
                            m_left = self._leg_cond(
                                leg0, self._leg_batch(batch, leg0), None,
                                now)[:, 0]
                            m_left = m_left & (arr_seq >= gate0_box[0]) \
                                & batch.valid
                            cnt = jnp.sum(m_left, dtype=jnp.int64)
                            # the in-flight iteration's own start event is
                            # not a leftover (gate closed => one started)
                            cnt = jnp.maximum(
                                cnt - jnp.where(active0_box[0],
                                                jnp.int64(0), jnp.int64(1)),
                                0)
                            drop_acc[0] = drop_acc[0] + cnt
                        else:
                            pend_h = pending[g.head - 1]
                            ql = self._leg_cond(
                                leg0, self._leg_batch(batch, leg0), pend_h,
                                now)
                            ql = ql & pend_h.valid[None, :] & (
                                arr_seq[:, None] > pend_h.last_seq[None, :])
                            if within is not None:
                                ql = ql & (
                                    batch.ts[:, None]
                                    - pend_h.start_ts[None, :]
                                    <= jnp.int64(within))
                            drop_acc[0] = drop_acc[0] + jnp.sum(
                                ql, dtype=jnp.int64)
                    pi = g.end + 1
                else:
                    process_position(pi)
                    pi += 1

            # ---- merge output blocks through the selector ----
            new_sel, out = self._emit(state.sel_state, out_blocks, now)
            new_state = PatternState(
                pending=tuple(pending),
                active0=active0_box[0],
                seq=state.seq + n_valid,
                sel_state=new_sel,
                dropped=state.dropped + drop_acc[0],
                armed0_ts=armed0_out[0],
                gate0_seq=gate0_box[0],
            )
            return new_state, out

        return step

    # ------------------------------------------------------- pending inserts

    def _advance(self, pending: list, out_blocks: list, target_pos: int,
                 frames, fvalid, fts, start_ts, last_seq, armed_ts,
                 valid, drop_acc=None, origin=None, gate_ctx=None) -> None:
        """Move completed entries to `target_pos` (insert into its waiting
        table, or emit if past the last position). Optional count positions
        add an epsilon edge: entries also advance past them immediately
        (reference: CountPreStateProcessor forwards once min counts are met).
        Note: the epsilon copy and the stay-behind copy are independent
        entries; a documented round-1 divergence is that both may eventually
        complete (the reference consumes the shared state event once).

        `origin` carries the spawning context slot for mid-every-group
        iteration entries; `gate_ctx` lets group-boundary crossings re-arm
        their every-group (head gate scalars / mid busy latches)."""
        S = len(self.plan.positions)
        P = self.P
        if origin is None:
            origin = jnp.full(valid.shape, -1, jnp.int32)
        while True:
            if gate_ctx is not None:
                hg = self.plan.head_group
                if hg is not None and target_pos == hg.end + 1:
                    # head every-group completion: re-open the gate for
                    # arrivals past the completing event
                    # (EveryPatternTestCase testQuery4/5)
                    any_c = valid.any()
                    mx = jnp.max(jnp.where(valid, last_seq,
                                           jnp.int64(-BIGSEQ))) + 1
                    gate_ctx["active0"][0] = gate_ctx["active0"][0] | any_c
                    gate_ctx["gate0"][0] = jnp.where(
                        any_c, jnp.maximum(gate_ctx["gate0"][0], mx),
                        gate_ctx["gate0"][0])
                for g in self.plan.mid_groups:
                    if target_pos == g.end + 1:
                        # mid every-group completion: clear the origin
                        # context entry's busy latch and advance its seq
                        # watermark (testQuery6 sequential iterations)
                        head_tbl = pending[g.head - 1]
                        o = jnp.where(valid & (origin >= 0), origin, P)
                        pending[g.head - 1] = head_tbl._replace(
                            leg_done=head_tbl.leg_done.at[o, 0].set(
                                False, mode="drop"),
                            last_seq=head_tbl.last_seq.at[o].max(
                                last_seq, mode="drop"))
                        origin = jnp.full(valid.shape, -1, jnp.int32)
            if target_pos >= S:
                out_blocks.append((frames, fvalid, fts, armed_ts, valid))
                return
            pending[target_pos - 1], n_drop = self._insert_entries(
                pending[target_pos - 1], frames, fvalid, fts,
                start_ts, last_seq, armed_ts, valid, origin)
            if drop_acc is not None:
                drop_acc[0] = drop_acc[0] + n_drop
            if not self.plan.positions[target_pos].optional:
                return
            target_pos += 1

    def _insert_entries(self, dst: PendingTable, frames, fvalid, fts,
                        start_ts, last_seq, armed_ts, valid,
                        origin=None) -> PendingTable:
        """Insert [P]-aligned candidate entries into dst's free slots."""
        P = self.P
        free_order = stable_partition_order(~dst.valid)
        n_free = jnp.sum((~dst.valid).astype(jnp.int32))
        rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
        fits = valid & (rank < n_free)
        n_drop = jnp.sum(valid & ~fits, dtype=jnp.int64)
        slot = jnp.where(fits, free_order[jnp.clip(rank, 0, P - 1)], P)

        new_frames = {}
        new_fvalid = {}
        new_fts = {}
        for ref in dst.frames:
            src_cols = frames.get(ref)
            if src_cols is None:
                new_frames[ref] = dst.frames[ref]
                new_fvalid[ref] = dst.frame_valid[ref]
                new_fts[ref] = dst.frame_ts[ref]
                continue
            new_frames[ref] = {
                n: dst.frames[ref][n].at[slot].set(src_cols[n], mode="drop")
                for n in dst.frames[ref]}
            new_fvalid[ref] = dst.frame_valid[ref].at[slot].set(
                fvalid.get(ref, valid), mode="drop")
            new_fts[ref] = dst.frame_ts[ref].at[slot].set(
                fts.get(ref, jnp.zeros_like(dst.frame_ts[ref])), mode="drop")
        if origin is None:
            origin = jnp.full(valid.shape, -1, jnp.int32)
        return PendingTable(
            frames=new_frames, frame_valid=new_fvalid, frame_ts=new_fts,
            start_ts=dst.start_ts.at[slot].set(start_ts, mode="drop"),
            last_seq=dst.last_seq.at[slot].set(last_seq, mode="drop"),
            armed_ts=dst.armed_ts.at[slot].set(armed_ts, mode="drop"),
            valid=dst.valid.at[slot].set(valid, mode="drop"),
            leg_done=dst.leg_done.at[slot].set(
                jnp.zeros((slot.shape[0], 2), bool), mode="drop"),
            origin=dst.origin.at[slot].set(origin.astype(jnp.int32),
                                           mode="drop"),
        ), n_drop

    # ------------------------------------------------------------------ emit

    def _emit(self, sel_state, out_blocks, now):
        selector = self.selector
        all_refs = []
        for pos in self.plan.positions:
            for leg in pos.legs:
                all_refs.append(leg.ref)

        if not out_blocks:
            # empty output
            W = 1
            scope = Scope()
            for ref in all_refs:
                cols = {n: jnp.zeros((W,), dtypes.device_dtype(t))
                        for n, t in self.ref_types[ref].items()}
                scope.add_frame(ref, cols, jnp.zeros((W,), dtypes.TS_DTYPE),
                                jnp.zeros((W,), bool),
                                default=(ref == all_refs[0]))
            self._alias_bare_streams(scope)
            scope.extras["now"] = now
            chunk = EventBatch(ts=jnp.zeros((W,), dtypes.TS_DTYPE), cols={},
                               valid=jnp.zeros((W,), bool),
                               types=jnp.zeros((W,), jnp.int8))
            return selector.step(sel_state, chunk, scope)

        # concatenate blocks lane-wise
        scope = Scope()
        tss = jnp.concatenate([b[3] for b in out_blocks])
        valids = jnp.concatenate([b[4] for b in out_blocks])
        for ref in all_refs:
            cols_parts = []
            valid_parts = []
            ts_parts = []
            for frames, fvalid, fts, ts, v in out_blocks:
                W = ts.shape[0]
                if ref in frames:
                    cols_parts.append(frames[ref])
                    valid_parts.append(fvalid[ref] & v)
                    ts_parts.append(fts[ref])
                else:
                    cols_parts.append({
                        n: jnp.zeros((W,), dtypes.device_dtype(t))
                        for n, t in self.ref_types[ref].items()})
                    valid_parts.append(jnp.zeros((W,), bool))
                    ts_parts.append(jnp.zeros((W,), dtypes.TS_DTYPE))
            cols = {n: jnp.concatenate([c[n] for c in cols_parts])
                    for n in self.ref_types[ref]}
            fv = jnp.concatenate(valid_parts)
            # zero missing frames so projections emit nulls
            cols = {n: jnp.where(fv, v, jnp.zeros((), v.dtype))
                    for n, v in cols.items()}
            scope.add_frame(ref, cols, jnp.concatenate(ts_parts), fv,
                            default=(ref == all_refs[0]))
        self._alias_bare_streams(scope)
        scope.extras["now"] = now
        chunk = EventBatch(ts=tss, cols={}, valid=valids,
                           types=jnp.zeros((tss.shape[0],), jnp.int8))
        return selector.step(sel_state, chunk, scope)

    def _alias_bare_streams(self, scope: Scope) -> None:
        """Let unambiguous bare stream names resolve to their position frame."""
        sid_refs: dict[str, list[str]] = {}
        for pos in self.plan.positions:
            for leg in pos.legs:
                sid_refs.setdefault(leg.stream_id, []).append(leg.ref)
        for sid, refs in sid_refs.items():
            if len(refs) == 1 and sid not in scope.frames:
                ref = refs[0]
                scope.frames[sid] = scope.frames[ref]
                scope.valids[sid] = scope.valids[ref]
                scope.ts[sid] = scope.ts[ref]

    # ---------------------------------------------------------------- runtime

    def _feed_junction(self, sid: str) -> StreamJunction:
        return (self.merged_junction if sid == MERGED_SID
                else self.junctions[sid])

    def on_junction_batch(self, sid: str, batch: EventBatch, now: int) -> None:
        cap = self._feed_junction(sid).batch_size
        if batch.capacity < cap:
            # pattern steps bake lane math on the planned capacity; widen
            # bucketed deliveries back (new lanes invalid)
            batch = batch.pad_to(cap)
        self.state, out = self._steps[sid](self.state, batch, jnp.int64(now))
        self._distribute(out, now)

    def warmup(self, buckets=None) -> int:
        """AOT-compile every per-junction step (+ the heartbeat step when
        time semantics need it) at the planned capacity without executing
        (query_runtime.aot_warm)."""
        from .query_runtime import aot_warm
        n0 = self.ctx.statistics.compiles.get(self.name, 0)
        now = jnp.int64(self.ctx.timestamp_generator.current_time())
        for sid, step in self._steps.items():
            j = self._feed_junction(sid)
            empty = EventBatch.empty(j.definition, j.batch_size)
            aot_warm(step, self.state, empty, now)
        if self.has_time_semantics:
            any_j = next(iter(self.junctions.values()))
            empty = EventBatch.empty(any_j.definition, any_j.batch_size)
            aot_warm(self._heartbeat_step, self.state, empty, now)
        return self.ctx.statistics.compiles.get(self.name, 0) - n0

    def heartbeat(self, now: int) -> None:
        if not self.has_time_semantics:
            return
        any_j = next(iter(self.junctions.values()))
        empty = EventBatch.empty(any_j.definition, any_j.batch_size)
        self.state, out = self._heartbeat_step(self.state, empty, jnp.int64(now))
        self._distribute(out, now)

    def _selector_state(self):
        return self.state.sel_state

    def _distribute(self, out: EventBatch, now: int) -> None:
        from .query_runtime import QueryRuntime
        QueryRuntime._distribute(self, out, now)

    def _select_event_type(self, out, etype):
        from .query_runtime import QueryRuntime
        return QueryRuntime._select_event_type(out, etype)

    def add_callback(self, cb: QueryCallback) -> None:
        self.callbacks.append(cb)


class _PatternSideReceiver(Receiver):
    def __init__(self, runtime: PatternQueryRuntime, sid: str):
        self.runtime = runtime
        self.sid = sid

    def on_batch(self, batch: EventBatch, now: int) -> None:
        t0 = time.perf_counter_ns()
        self.runtime.on_junction_batch(self.sid, batch, now)
        tele = getattr(self.runtime.ctx, "telemetry", None)
        if tele is not None and tele.on:
            tele.record_query(self.runtime.name, time.perf_counter_ns() - t0)
