"""Stream junctions, input handlers, callbacks — the ingestion/dispatch plane.

Reference: core/stream/StreamJunction.java:64 is a per-stream pub/sub hub backed
by the LMAX Disruptor for async mode. The TPU replacement is a **host-side
columnar micro-batcher**: producers append rows into numpy staging buffers; a
flush converts the staged rows to one device EventBatch and synchronously
delivers it to every receiver (query runtimes consume device batches directly;
stream callbacks decode to host events). Micro-batch size is the backpressure /
latency knob that replaces the Disruptor ring size (StreamJunction.java:68).

Device-to-device chaining: a query whose output feeds another stream publishes
its output EventBatch straight into the target junction (`publish_batch`),
so multi-query pipelines stay on device until a host callback needs decoding.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ..errors import SiddhiAppCreationError, SiddhiAppRuntimeError
from ..util.locks import named_condition, named_lock, note_blocking
from ..query_api.definition import AttributeType, StreamDefinition
from . import dtypes
from .context import SiddhiAppContext
from .event import Event, EventBatch, EventType, StreamCodec


class Receiver:
    """Junction subscriber (reference: StreamJunction.Receiver)."""

    def on_batch(self, batch: EventBatch, now: int) -> None:
        raise NotImplementedError


class StreamCallback(Receiver):
    """User-facing stream subscriber (reference:
    core/stream/output/StreamCallback.java:38). Subclass and override
    `receive`, or wrap a plain function with FunctionStreamCallback."""

    _junction: "StreamJunction" = None

    def receive(self, events: list[Event]) -> None:
        raise NotImplementedError

    def on_batch(self, batch: EventBatch, now: int) -> None:
        events = batch.to_host_events(self._junction.codec)
        if events:
            self.receive(events)


class FunctionStreamCallback(StreamCallback):
    def __init__(self, fn: Callable[[list[Event]], None]):
        self.fn = fn

    def receive(self, events: list[Event]) -> None:
        self.fn(events)


class ColumnarBlock:
    """One delivered output micro-batch, as columns — the TPU-native analogue
    of the Event[] the reference hands its callbacks (StreamCallback.java:38).

    Columns are compacted numpy arrays in DEVICE dtypes (doubles arrive as
    float32, strings as int32 dictionary codes). `strings(name)` decodes a
    string column to Python values; `to_events()` materializes classic Event
    objects for code that wants them. Batch-level delivery skips per-event
    object construction entirely — on wide batches that is the difference
    between the public callback path keeping up with the device and not."""

    __slots__ = ("timestamps", "columns", "is_expired", "count", "_codec")

    def __init__(self, timestamps, columns, is_expired, count, codec):
        self.timestamps = timestamps  # int64[count]
        self.columns = columns  # name -> numpy[count] (device dtypes)
        self.is_expired = is_expired  # bool[count]
        self.count = count
        self._codec = codec

    def __len__(self) -> int:
        return self.count

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def strings(self, name: str) -> list:
        """Decode a string column's codes to Python strings (lazy — only
        callbacks that read the text pay the decode). Uses the same native
        map_codes fast path as the Event decode."""
        from .event import StringTable
        tbl = self._codec.string_tables[name]
        codes = self.columns[name]
        from .. import native as native_mod
        nat = native_mod.native
        if nat is not None and (codes.size == 0 or
                                int(codes.max()) < StringTable.TRANSIENT_BASE):
            return nat.map_codes(np.ascontiguousarray(codes), tbl._to_str)
        return tbl.decode_array(codes.tolist())

    def to_events(self) -> list[Event]:
        """Materialize classic Event objects — same decode (native
        build_events) as the per-Event callback path."""
        from .event import AttributeType
        from .. import native as native_mod
        nat = native_mod.native
        attrs = self._codec.definition.attributes
        cols = []
        for a in attrs:
            if a.type == AttributeType.OBJECT:
                cols.append([None] * self.count)
            elif a.type == AttributeType.STRING:
                cols.append(self.strings(a.name))
            elif a.type == AttributeType.BOOL:
                cols.append(self.columns[a.name].astype(bool).tolist())
            else:
                cols.append(self.columns[a.name].tolist())
        if nat is not None:
            return nat.build_events(
                Event, np.ascontiguousarray(self.timestamps),
                np.ascontiguousarray(self.is_expired).astype(np.uint8),
                tuple(cols))
        return [Event(t, d, is_expired=e)
                for t, d, e in zip(self.timestamps.tolist(), zip(*cols),
                                   self.is_expired.tolist())]


class BatchStreamCallback(Receiver):
    """Columnar (batch-level) stream subscriber: override `receive_batch`,
    or wrap a function via add_callback(..., columnar=True)."""

    _junction: "StreamJunction" = None

    def receive_batch(self, block: ColumnarBlock) -> None:
        raise NotImplementedError

    def on_batch(self, batch: EventBatch, now: int) -> None:
        import jax

        from .event import EventType
        tree = (batch.ts, batch.valid, batch.types, dict(batch.cols))
        # async delivery hands host numpy (device_get already done by the
        # fetch worker); the sync path hands device arrays — one tree fetch.
        # Multi-host: non-addressable shards need the allgather collective,
        # same as EventBatch.to_host_events
        if any(getattr(leaf, "is_fully_addressable", True) is False
               for leaf in jax.tree_util.tree_leaves(tree)):
            from jax.experimental import multihost_utils
            ts, valid, types, cols = \
                multihost_utils.process_allgather(tree, tiled=True)
        else:
            ts, valid, types, cols = jax.device_get(tree)
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return
        block = ColumnarBlock(
            timestamps=ts[idx],
            columns={k: v[idx] for k, v in cols.items()},
            is_expired=(types[idx] == int(EventType.EXPIRED)),
            count=int(idx.size),
            codec=self._junction.codec,
        )
        self.receive_batch(block)


class FunctionBatchCallback(BatchStreamCallback):
    def __init__(self, fn: Callable[[ColumnarBlock], None]):
        self.fn = fn

    def receive_batch(self, block: ColumnarBlock) -> None:
        self.fn(block)


def _wire_pack(batch: EventBatch):
    """Device-side wire packing for callback readbacks: int64 timestamps
    ship as (base + uint32 delta) and valid+types fold into one byte —
    ~28% fewer bytes over the tunnel, where d2h bandwidth (~25-50 MB/s
    measured) bounds callback throughput. `over` flags a >49-day timestamp
    span (then the fetch worker re-reads the raw batch instead)."""
    import jax.numpy as jnp
    big = jnp.int64(1) << jnp.int64(62)
    ts0 = jnp.min(jnp.where(batch.valid, batch.ts, big))
    ts0 = jnp.where(ts0 == big, jnp.int64(0), ts0)
    dts = jnp.where(batch.valid, batch.ts - ts0, 0)
    over = jnp.any(dts > jnp.int64(0xFFFFFFFF)) | jnp.any(dts < 0)
    flags = (batch.types.astype(jnp.uint8) << 1) | batch.valid.astype(jnp.uint8)
    return ts0, dts.astype(jnp.uint32), flags, batch.cols, over


_wire_pack_jit = None


def _wire_unpack(host) -> EventBatch:
    ts0, dts, flags, cols, _over = host
    return EventBatch(
        ts=np.int64(ts0) + dts.astype(np.int64),
        cols=cols,
        valid=(flags & 1).astype(bool),
        types=(flags >> 1).astype(np.int8),
    )


class AsyncDecoder:
    """Background device→host decode pipeline for stream callbacks.

    The reference's Disruptor hands callback work to consumer threads
    (StreamJunction.java:279-316); here the analogous decoupling matters even
    more because a callback decode is a device→host readback — ~100 ms
    through a tunneled TPU. Two stages:

      fetch workers (N)   device_get the batch into host numpy arrays —
                          the readback round trips OVERLAP across workers
                          (and release the GIL during the transfer)
      delivery thread (1) decodes + fires callbacks strictly in submit
                          order (a sequence-numbered reorder buffer)

    so pipelined throughput is bounded by bandwidth + Python decode, not by
    round trips × batches."""

    N_FETCH = int(os.environ.get("SIDDHI_DECODE_WORKERS", "2"))

    def __init__(self, maxsize: int = 32) -> None:
        import queue
        import threading

        import jax
        # wire packing only pays where a wire exists: co-located backends
        # skip the extra device pass (SIDDHI_WIRE_PACK=0 forces it off)
        self._pack = (jax.default_backend() not in ("cpu",)
                      and os.environ.get("SIDDHI_WIRE_PACK", "1") != "0")
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        #: max decoded-but-undelivered batches held in the reorder buffer
        self._max_lag = max(maxsize, self.N_FETCH + 1)
        self._seq = 0
        self._deliver_next = 0
        self._buffer: dict = {}
        self._cv = named_condition("stream.decoder")
        self._stopping = False
        self._threads = [
            threading.Thread(target=self._fetch_loop, daemon=True,
                             name=f"siddhi-fetch-{i}")
            for i in range(self.N_FETCH)]
        self._threads.append(threading.Thread(
            target=self._deliver_loop, daemon=True, name="siddhi-decoder"))
        for t in self._threads:
            t.start()

    def submit(self, receiver: Receiver, batch: EventBatch, now: int,
               junction: "StreamJunction" = None) -> None:
        import jax
        global _wire_pack_jit
        payload = batch
        if self._pack:
            try:
                if _wire_pack_jit is None:
                    _wire_pack_jit = jax.jit(_wire_pack)
                payload = (_wire_pack_jit(batch), batch)
            except Exception:  # pragma: no cover — fall back to raw fetch
                payload = batch
        try:
            leaves = jax.tree_util.tree_leaves(
                payload[0] if isinstance(payload, tuple) else payload)
            for leaf in leaves:
                start = getattr(leaf, "copy_to_host_async", None)
                if start is not None:
                    start()
        except Exception:  # pragma: no cover — transfer warm-up is advisory
            pass
        # the bounded put may block under the controller lock; safe
        # because decoder threads never block unboundedly on that lock
        # (the @OnError path acquires it with a timeout) so the queue
        # always drains — see docs/CONCURRENCY.md
        note_blocking("queue.put", allow=("app.controller",))
        self._q.put((self._seq, receiver, payload, now, junction))
        self._seq += 1

    def _fetch_loop(self) -> None:
        import jax
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                seq, receiver, payload, now, junction = item
                try:
                    if isinstance(payload, tuple):
                        packed, raw = payload
                        host = jax.device_get(packed)
                        if bool(host[4]):  # timestamp span overflow: re-read
                            host = jax.device_get(raw)
                        else:
                            host = _wire_unpack(host)
                    else:
                        host = jax.device_get(payload)
                except Exception:  # pragma: no cover — deliver raw instead
                    logging.getLogger("siddhi_tpu").exception(
                        "async readback failed")
                    host = (payload[1] if isinstance(payload, tuple)
                            else payload)
                with self._cv:
                    # backpressure the fetch→deliver stage too: the input
                    # queue only bounds submit→fetch, so a slow delivery
                    # thread would otherwise grow _buffer without limit.
                    # Safe from deadlock: at most N_FETCH seqs are in
                    # flight, every seq below the smallest in-flight one is
                    # already buffered/delivered, so delivery always
                    # progresses and notifies.
                    while (seq - self._deliver_next > self._max_lag
                           and not self._stopping):
                        self._cv.wait(timeout=0.2)
                    self._buffer[seq] = (receiver, host, now, junction)
                    self._cv.notify_all()
            finally:
                self._q.task_done()

    def _deliver_loop(self) -> None:
        while True:
            with self._cv:
                while (self._deliver_next not in self._buffer
                       and not self._stopping):
                    self._cv.wait(timeout=0.2)
                if self._stopping and self._deliver_next not in self._buffer:
                    return
                receiver, host, now, junction = self._buffer.pop(
                    self._deliver_next)
                self._deliver_next += 1
            try:
                receiver.on_batch(host, now)
            except Exception as e:  # noqa: BLE001 — async path must not die
                # preserve @OnError semantics (reference:
                # StreamJunction.java:371-463): route the failed batch like
                # the synchronous _deliver would, under the controller lock
                if junction is not None and (
                        junction.on_error is not None
                        or junction.on_error_action is not None):
                    # BOUNDED acquire, never a plain `with`: a producer can
                    # hold the controller lock while blocked on the bounded
                    # submit queue above — if this thread then waited on the
                    # same lock forever, nothing would drain the reorder
                    # buffer and the whole pipeline would wedge. Timing out
                    # keeps delivery moving (the buffer empties, the
                    # producer's put completes) at the cost of routing this
                    # one failure through the plain log.
                    got = junction.ctx.controller_lock.acquire(timeout=1.0)
                    if got:
                        try:
                            if junction.on_error is not None:
                                junction.on_error(e, host)
                            else:
                                junction._handle_error(e, host, now)
                        except Exception:  # pragma: no cover
                            logging.getLogger("siddhi_tpu").exception(
                                "async @OnError routing failed")
                        finally:
                            junction.ctx.controller_lock.release()
                    else:
                        logging.getLogger("siddhi_tpu").exception(
                            "async @OnError routing skipped (controller "
                            "lock busy): %s", e)
                else:
                    logging.getLogger("siddhi_tpu").exception(
                        "async stream callback failed")
            with self._cv:
                self._cv.notify_all()

    def drain(self) -> None:
        """Block until every submitted batch has been decoded+delivered."""
        self._q.join()  # all fetches done
        with self._cv:
            while self._deliver_next < self._seq:
                self._cv.wait(timeout=0.2)

    def stop(self) -> None:
        self.drain()
        for _ in range(self.N_FETCH):
            self._q.put(None)
        self._q.join()
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=30)


class StreamJunction:
    """Per-stream hub: staging buffers + receiver fan-out."""

    def __init__(self, definition: StreamDefinition, ctx: SiddhiAppContext,
                 codec: Optional[StreamCodec] = None) -> None:
        self.definition = definition
        self.ctx = ctx
        self.codec = codec or StreamCodec(definition, ctx.global_strings)
        self.receivers: list[Receiver] = []
        self.batch_size = ctx.effective_batch_size
        # @Async: the reference switches to a Disruptor ring with worker
        # consumers (StreamJunction.java:104-134, 279-316). Here:
        # buffer.size tunes the micro-batch AND, once the app starts, a C
        # MPSC staging ring (native/columnar.c) + feeder thread decouple
        # producers from the controller — send() stages in O(1) and the
        # feeder encodes/dispatches batches under the controller lock.
        ann = definition.annotation("async") if definition.annotations else None
        self.is_async = ann is not None
        self._ring = None
        self._ring_cap = 0
        self._feeder = None
        self._feeder_stop = None
        self._feeder_wake = None
        if ann is not None:
            bs = ann.element("buffer.size")
            if bs:
                self.batch_size = int(bs)
            self._ring_cap = max(4 * self.batch_size, 1024)
        # @Async(workers='N') — parallel ingress pipeline (core/ingress.py):
        # N decode/intern workers + a lock-free columnar ring + a
        # double-buffering feeder replace the MPSC ring. Opt-in per stream
        # via the annotation (reference parity: @Async's workers element) or
        # app-wide via SIDDHI_INGRESS_WORKERS; start_async gates on the
        # policies the pipeline cannot honor (WAL, taps, drop policies,
        # OBJECT attrs) and falls back to the MPSC ring.
        self._pipeline = None
        self.ingress_workers = 0
        if ann is not None:
            w = ann.element("workers")
            if w:
                self.ingress_workers = int(w)
            if self.ingress_workers == 0:
                import os as _os
                self.ingress_workers = int(
                    _os.environ.get("SIDDHI_INGRESS_WORKERS", "0") or 0)
        # --- overload protection (bounded ingress + backpressure signal) ---
        # @Async(buffer.size=N, overflow.policy=..., max.staged=...,
        #        block.timeout='1 sec', high.watermark=0.8, low.watermark=0.2)
        # caps staged rows with a pluggable policy for what a full buffer
        # sheds (reference: the Disruptor ring IS the bound; OverflowPolicy
        # here generalizes its blocking wait strategy):
        #   block     producers wait for room (MPSC ring path; default) —
        #             block.timeout bounds the wait, expiry drops + counts
        #   drop.new  shed the arriving row
        #   drop.old  evict the oldest staged row to admit the new one
        #   fault     divert the arriving row to the `!stream` fault stream
        #             or the ErrorStore (replayable), like @OnError
        # Watermarks pace attached sources: staged depth >= high*capacity
        # calls pause() on every attached Source, <= low*capacity resumes.
        self.capacity: Optional[int] = None
        self.overflow_policy = "block"
        self.block_timeout_s: Optional[float] = None
        self.high_watermark = 0.8
        self.low_watermark = 0.2
        #: sources feeding this junction (wiring registers them) — the
        #: pause()/resume() backpressure targets
        self.attached_sources: list = []
        self._bp_paused = False
        if ann is not None:
            pol = (ann.element("overflow.policy") or "block").lower()
            if pol not in ("block", "drop.new", "drop.old", "fault"):
                raise SiddhiAppCreationError(
                    f"@Async on {definition.id!r}: overflow.policy {pol!r} "
                    "must be block | drop.new | drop.old | fault")
            self.overflow_policy = pol
            ms = ann.element("max.staged")
            self.capacity = int(ms) if ms else self._ring_cap
            if self.capacity < self.batch_size and pol != "block":
                raise SiddhiAppCreationError(
                    f"@Async on {definition.id!r}: max.staged "
                    f"({self.capacity}) must be >= buffer.size "
                    f"({self.batch_size})")
            bt = ann.element("block.timeout")
            if bt:
                from .partition import _parse_annotation_time
                self.block_timeout_s = _parse_annotation_time(bt) / 1000.0
            hw = ann.element("high.watermark")
            lw = ann.element("low.watermark")
            self.high_watermark = float(hw) if hw else 0.8
            self.low_watermark = float(lw) if lw else 0.2
            if not 0.0 <= self.low_watermark < self.high_watermark <= 1.0:
                raise SiddhiAppCreationError(
                    f"@Async on {definition.id!r}: need "
                    "0 <= low.watermark < high.watermark <= 1")
        self._staged_rows: list = []
        self._staged_ts: list[int] = []
        #: send-order interceptors fn(ts, data) — multi-stream sequence
        #: queries tap their source junctions to build a merged arrival
        #: stream that preserves TRUE per-event send order across streams
        #: (the reference's sequence receivers consume streams in arrival
        #: order, core/query/input/stream/state/receiver/)
        self.taps: list[Callable] = []
        #: thread-safe pre-staging: a list of (ts, row) tuples appended from
        #: producer threads via stage_row() under its own small lock (an
        #: unlocked append could land on a list flush() just swapped out and
        #: drained — a silently lost event), drained into the staging
        #: buffers under the controller lock at flush
        self._tap_queue: list = []
        self._tap_lock = named_lock("junction.tap")
        self.on_error: Optional[Callable] = None
        #: write-ahead event journal (state/wal.py) — attached by the app
        #: runtime to INGRESS junctions only (user-defined streams). Rows
        #: are journaled before they enter the staging buffers; derived
        #: streams chain on device via publish_batch and are reproducible
        #: from their inputs, so they never journal.
        self.wal = None
        # per-THREAD re-entrancy guards (flushing during callbacks; drain
        # nesting): shared booleans would make one thread's activity no-op
        # another thread's barrier
        import threading as _threading
        self._reentry = _threading.local()
        # @OnError(action=LOG|STREAM|STORE) (reference:
        # StreamJunction.java:371-463, OnErrorAction); None = propagate
        on_error_ann = (definition.annotation("OnError")
                        if definition.annotations else None)
        self.on_error_action: Optional[str] = (
            (on_error_ann.element("action") or "log").lower()
            if on_error_ann is not None else None)
        #: fault junction (`!stream`), created by the app runtime for
        #: action=STREAM; schema = this stream's attrs + _error string
        self.fault_junction: Optional["StreamJunction"] = None
        #: blue-green cutover (core/upgrade.py): when set, every send into
        #: this junction forwards to the v2 junction with the ORIGINAL
        #: (pre-interning) values — v1 and v2 own separate string tables,
        #: so encoded columns/codes must never cross the boundary
        self._redirect: Optional["StreamJunction"] = None
        #: event-time gate (core/event_time.py) — attached by the app
        #: runtime when @app:eventTime names an attribute of this stream;
        #: interposes at _flush_rows so delivery is sorted by event time
        #: and watermark-older rows divert to the ErrorStore (kind="late")
        self._et = None

    def _pad_cap(self, m: int) -> int:
        """Delivery capacity for `m` staged rows: the smallest power-of-two
        lane bucket holding them (shape-bucketed dispatch — each query step
        then compiles at most one executable per ladder rung instead of
        paying the full-capacity kernel for near-empty batches), or the full
        batch size when bucketing is off / the app runs on a device mesh
        (bucket widths must stay mesh-aligned)."""
        if dtypes.config.shape_buckets and self.ctx.mesh is None:
            return dtypes.bucket_capacity(m, self.batch_size)
        return self.batch_size

    # ------------------------------------------------------------- subscribe

    def subscribe(self, receiver: Receiver) -> None:
        if isinstance(receiver, (StreamCallback, BatchStreamCallback)):
            receiver._junction = self
        self.receivers.append(receiver)

    # -------------------------------------------------------------- redirect

    def redirect_to(self, target: Optional["StreamJunction"]) -> None:
        """Atomically route every subsequent send into `target` (the v2
        junction during a blue-green upgrade; None undoes it on rollback).
        Callers set it under the controller lock with this junction quiesced
        (sources paused, async machinery stopped, staged rows flushed)."""
        self._redirect = target

    def _resolve_redirect(self) -> "StreamJunction":
        j = self
        while j._redirect is not None:
            j = j._redirect
        return j

    # ---------------------------------------------------------------- ingest

    def stage_row(self, ts: int, data: Sequence) -> None:
        """Thread-safe staging from arbitrary producer threads; rows enter
        the real staging buffers under the controller lock at the next
        flush. Used by sequence taps, which run on whichever thread called
        the source's send()."""
        with self._tap_lock:
            self._tap_queue.append((ts, data))
            full = len(self._tap_queue) >= self.batch_size
        self.ctx.timestamp_generator.observe_event_time(ts)
        if full:
            self.flush()

    def send_row(self, ts: int, data: Sequence) -> None:
        if self._redirect is not None:
            return self._resolve_redirect().send_row(ts, data)
        if self.wal is not None and not self._lock_owned():
            # journal+stage must be ONE atomic step w.r.t. persist()'s
            # snapshot+rotate critical section: interleaving there would
            # journal the row into the pre-snapshot segment, stage it after
            # the snapshot, and rotate its record away — lost on the next
            # crash. The controller lock is that atomicity (persist holds
            # it); durability mode trades the lock-free @Async ring for it
            # (_lock_owned() skips the ring path below).
            with self.ctx.controller_lock:
                return self.send_row(ts, data)
        if self.wal is not None:  # write-AHEAD: journal before acceptance
            self.wal.append_rows(self.definition.id, (ts,), (tuple(data),))
        for tap in self.taps:
            tap(ts, data)
        if self._bounded_mode() and not self._lock_owned():
            self.ctx.timestamp_generator.observe_event_time(ts)
            self._stage_bounded(((ts, tuple(data)),))
            return
        if self._pipeline is not None and not self._lock_owned():
            self.ctx.timestamp_generator.observe_event_time(ts)
            if self._pipeline.submit_rows((ts,), (tuple(data),)) == 1:
                return
            # pipeline stopping: fall through to synchronous staging
        if self._ring is not None and not self._lock_owned():
            self.ctx.timestamp_generator.observe_event_time(ts)
            # blocking backpressure when the ring is full, like the
            # Disruptor's blocking wait strategy. No per-send wake: the
            # feeder polls at 1 ms, and an Event.set() per row costs more
            # than the stage itself. Re-read the ring each spin: shutdown
            # detaches it, and late sends must fall back to the sync path.
            # block.timeout bounds the wait; expiry sheds the row, counted.
            push = self._ring_push
            deadline = (None if self.block_timeout_s is None
                        else time.monotonic() + self.block_timeout_s)
            while True:
                ring = self._ring
                if ring is None:
                    break
                if push(ring, ts, tuple(data)):
                    if self.attached_sources and not self._bp_paused:
                        self._check_pause(self._ring_size(ring))
                    return
                if deadline is not None and time.monotonic() >= deadline:
                    self.ctx.statistics.track_ingress_drop(
                        self.definition.id, "block.timeout", 1)
                    return
                self._feeder_wake.set()
                time.sleep(0.0002)
        if getattr(self.ctx, "autoflush_active", False) \
                and not self._lock_owned():
            # an auto-flush daemon may swap the staged lists concurrently:
            # the ts+row pair must land atomically w.r.t. that swap
            with self.ctx.controller_lock:
                self._staged_ts.append(ts)
                self._staged_rows.append(data)
        else:
            self._staged_ts.append(ts)
            self._staged_rows.append(data)
        self.ctx.timestamp_generator.observe_event_time(ts)
        if len(self._staged_rows) >= self.batch_size:
            self.flush()

    def send_rows(self, tss: Sequence[int], rows: Sequence) -> None:
        """Batched staging: one call stages many rows (InputHandler.send_batch).
        Per-row Python overhead (call dispatch, watermark observe, size check)
        is paid once per batch instead of once per event."""
        if not rows:
            return
        if self._redirect is not None:
            return self._resolve_redirect().send_rows(tss, rows)
        if self.taps:  # sequence taps need true per-row send order
            for ts, row in zip(tss, rows):
                self.send_row(ts, row)  # journals per row when WAL is on
            return
        if self.wal is not None and not self._lock_owned():
            with self.ctx.controller_lock:  # see send_row: atomic vs persist
                return self.send_rows(tss, rows)
        if self.wal is not None:  # one journal record for the whole batch
            self.wal.append_rows(self.definition.id, tss, rows)
        self.ctx.timestamp_generator.observe_event_time(int(max(tss)))
        if self._bounded_mode() and not self._lock_owned():
            self._stage_bounded((ts, tuple(row))
                                for ts, row in zip(tss, rows))
            return
        if self._pipeline is not None and not self._lock_owned():
            done = self._pipeline.submit_rows(tss, rows)
            if done >= len(rows):
                return
            # pipeline stopping mid-batch: the unconsumed remainder falls
            # back to synchronous staging (claimed prefix is in flight)
            tss, rows = tss[done:], rows[done:]
        if self._ring is not None and not self._lock_owned():
            push = self._ring_push
            for i, (ts, row) in enumerate(zip(tss, rows)):
                pushed = False
                while True:
                    ring = self._ring
                    if ring is None:
                        break
                    if push(ring, ts, tuple(row)):
                        pushed = True
                        break
                    self._feeder_wake.set()
                    time.sleep(0.0002)
                if not pushed:
                    # ring detached mid-batch (shutdown): only the
                    # remainder falls back to synchronous staging — rows
                    # already pushed will be drained by stop_async
                    tss, rows = tss[i:], rows[i:]
                    break
            else:
                return
        if getattr(self.ctx, "autoflush_active", False) \
                and not self._lock_owned():
            with self.ctx.controller_lock:
                self._staged_ts.extend(tss)
                self._staged_rows.extend(rows)
        else:
            self._staged_ts.extend(tss)
            self._staged_rows.extend(rows)
        if len(self._staged_rows) >= self.batch_size:
            self.flush()

    def send_column_batch(self, ts_arr: np.ndarray,
                          cols: dict[str, np.ndarray], n: int) -> None:
        """Columnar ingestion (InputHandler.send_columns): pre-encoded numpy
        columns enter the pipeline with zero per-row host work — chunked to
        the junction's compiled batch capacity and delivered directly."""
        if n == 0:
            return
        self.ctx.timestamp_generator.observe_event_time(int(ts_arr[:n].max()))
        cap = self.batch_size
        tele = getattr(self.ctx, "telemetry", None)
        tracing = tele is not None and tele.on
        with self.ctx.controller_lock:
            note_blocking("device.dispatch", allow=("app.controller",))
            self.flush()  # staged rows first: preserve arrival order
            now = self.ctx.timestamp_generator.current_time()
            for start in range(0, n, cap):
                t0 = time.perf_counter_ns() if tracing else 0
                m = min(cap, n - start)
                if m == cap:
                    ts_c = ts_arr[start:start + cap]
                    cols_c = {k: v[start:start + cap] for k, v in cols.items()}
                else:
                    pcap = self._pad_cap(m)
                    ts_c = np.empty(pcap, dtype=np.int64)
                    ts_c[:m] = ts_arr[start:start + m]
                    ts_c[m:] = ts_arr[start + m - 1]  # monotone pad
                    cols_c = {}
                    for k, v in cols.items():
                        pad = np.zeros(pcap, dtype=v.dtype)
                        pad[:m] = v[start:start + m]
                        cols_c[k] = pad
                if tracing:
                    h2d_t0 = time.perf_counter_ns()
                    batch = EventBatch.from_numpy(ts_c, cols_c, m)
                    trace = tele.mint(self.definition.id, m, t0=t0)
                    trace.h2d_ns = time.perf_counter_ns() - h2d_t0
                    batch._trace = trace
                    tele.record_lag(self.definition.id, int(ts_c[m - 1]))
                else:
                    batch = EventBatch.from_numpy(ts_c, cols_c, m)
                self._deliver(batch, now)

    # ------------------------------------------------------------ async mode

    def _lock_owned(self) -> bool:
        """True when THIS thread already holds the controller lock (a
        callback inside _deliver sending into an async stream): pushing to
        the ring there can deadlock — the only drainer needs the lock we
        hold — so those sends take the synchronous staging path."""
        try:
            return self.ctx.controller_lock._is_owned()
        except AttributeError:  # pragma: no cover — non-CPython RLock
            return getattr(self._reentry, "flushing", False) or \
                getattr(self._reentry, "draining", False)

    # ------------------------------------------------- bounded ingress (drop)

    def _bounded_mode(self) -> bool:
        """True when this junction runs producer-side admission control: a
        capacity with a non-block policy. Rows then enter the thread-safe
        pre-staging queue only (no inline flush, no MPSC ring — the ring's
        blocking push IS the block policy), and delivery is pull-driven by
        the feeder / auto-flusher / explicit flush(), so the bound — not
        delivery speed — caps host memory."""
        return self.capacity is not None and self.overflow_policy != "block"

    def _stage_bounded(self, items) -> None:
        """Admission control for drop/fault policies: each (ts, row) either
        enters the pre-staging queue or is shed per the policy, with every
        decision counted — the drop counters are exact by construction."""
        stats = self.ctx.statistics
        cap = self.capacity
        policy = self.overflow_policy
        diverted: list = []  # fault policy: routed outside the lock
        with self._tap_lock:
            q = self._tap_queue
            for ts, row in items:
                if len(q) < cap:
                    q.append((ts, row))
                elif policy == "drop.old":
                    q.pop(0)
                    q.append((ts, row))
                    stats.track_ingress_drop(self.definition.id, "drop.old", 1)
                elif policy == "drop.new":
                    stats.track_ingress_drop(self.definition.id, "drop.new", 1)
                else:  # fault
                    diverted.append((ts, row))
            depth = len(q)
        stats.track_queue_depth(self.definition.id, depth)
        if diverted:
            stats.track_ingress_drop(self.definition.id, "fault",
                                     len(diverted))
            self._divert_overflow(diverted)
        self._check_pause(depth)
        if self._feeder_wake is not None:
            self._feeder_wake.set()

    def _divert_overflow(self, rows: list) -> None:
        """`overflow.policy='fault'`: overflow rows leave through the same
        doors failed events do — the `!stream` fault junction when one
        exists, else the ErrorStore (replayable), else the log. Never
        silent: the `fault` drop counter is bumped by the caller either way."""
        msg = (f"ingress overflow: {self.definition.id!r} staging buffer "
               f"full (capacity={self.capacity})")
        if self.fault_junction is not None:
            for ts, row in rows:
                self.fault_junction.send_row(ts, tuple(row) + (msg,))
            self.fault_junction.flush()
            return
        store = getattr(self.ctx, "error_store", None)
        if store is not None:
            store.save(self.ctx.name, self.definition.id,
                       [(ts, tuple(row)) for ts, row in rows], msg,
                       kind="overflow")
            return
        logging.getLogger("siddhi_tpu").warning(
            "%s; %d row(s) dropped (no fault stream or error store to "
            "divert to)", msg, len(rows))

    # ------------------------------------------- backpressure (pause/resume)

    def _check_pause(self, depth: int) -> None:
        """High-watermark crossing pauses every attached source (reference:
        Source.pause:113-153 — the transport stops/pausing its consumer).
        Idempotent until the matching low-watermark resume."""
        if (self._bp_paused or not self.attached_sources
                or self.capacity is None):
            return
        if depth >= self.high_watermark * self.capacity:
            with self._tap_lock:  # exact pause/resume counts under races
                if self._bp_paused:
                    return
                self._bp_paused = True
            self.ctx.statistics.track_pause(self.definition.id)
            for s in self.attached_sources:
                try:
                    s.pause()
                except Exception:  # pragma: no cover — transport hiccup
                    logging.getLogger("siddhi_tpu").exception(
                        "pause() failed on source of %r", self.definition.id)

    def _staged_depth(self) -> int:
        depth = len(self._tap_queue) + len(self._staged_rows)
        ring = self._ring
        if ring is not None:
            depth += self._ring_size(ring)
        if self._pipeline is not None:
            depth += self._pipeline.size()
        return depth

    def _ring_size(self, ring) -> int:
        from .. import native as native_mod
        return native_mod.native.ring_size(ring)

    def _maybe_resume(self) -> None:
        """Low-watermark crossing resumes paused sources (their buffered
        payloads re-deliver through on_payload, re-entering admission).
        Called after every flush — the only place depth shrinks."""
        if not self._bp_paused or self.capacity is None:
            return
        if self._staged_depth() <= self.low_watermark * self.capacity:
            with self._tap_lock:  # pair of _check_pause's guarded flip
                if not self._bp_paused:
                    return
                self._bp_paused = False
            self.ctx.statistics.track_resume(self.definition.id)
            for s in self.attached_sources:
                try:
                    s.resume()
                except Exception:  # pragma: no cover — transport hiccup
                    logging.getLogger("siddhi_tpu").exception(
                        "resume() failed on source of %r", self.definition.id)

    def start_async(self) -> None:
        """Spin up the staging ring + feeder thread (app start; reference:
        StreamJunction.startProcessing starting the Disruptor)."""
        from .. import native as native_mod
        if not self.is_async or self._feeder is not None \
                or self._pipeline is not None:
            return
        if (self.ingress_workers > 0 and self.overflow_policy == "block"
                and self.wal is None and not self.taps
                and self._et is None
                and not self.codec.object_attrs):
            from .ingress import IngressPipeline
            try:
                self._pipeline = IngressPipeline(self, self.ingress_workers)
                self._pipeline.start()
                return
            except Exception:
                logging.getLogger("siddhi_tpu").exception(
                    "@Async(workers=%d) on %r: ingress pipeline failed to "
                    "start; falling back to the staging ring",
                    self.ingress_workers, self.definition.id)
                self._pipeline = None
        if self._bounded_mode():
            # drop/fault policies: producer-side accounting must stay exact,
            # so no MPSC ring — a plain feeder drains the bounded pre-staging
            # queue (the ring's blocking push is the block policy's engine)
            import threading
            self._feeder_stop = threading.Event()
            self._feeder_wake = threading.Event()
            self._feeder = threading.Thread(
                target=self._bounded_feed_loop, daemon=True,
                name=f"siddhi-feeder-{self.definition.id}")
            self._feeder.start()
            return
        if native_mod.native is None:
            logging.getLogger("siddhi_tpu").info(
                "@Async on %r: native ring unavailable (no C toolchain); "
                "staying synchronous", self.definition.id)
            return
        import threading
        self._ring_push = native_mod.native.ring_push
        self._ring = native_mod.native.ring_new(self._ring_cap)
        self._feeder_stop = threading.Event()
        self._feeder_wake = threading.Event()
        self._feeder = threading.Thread(
            target=self._feed_loop, daemon=True,
            name=f"siddhi-feeder-{self.definition.id}")
        self._feeder.start()

    def stop_async(self) -> None:
        if self._pipeline is not None:
            # detach FIRST: producers mid-submit fall back to the
            # synchronous staging path; stop() then delivers everything
            # already claimed (workers finish the queue, feeder flushes)
            p, self._pipeline = self._pipeline, None
            p.stop()
        if self._feeder is None:
            return
        self._feeder_stop.set()
        self._feeder_wake.set()
        # detach FIRST: producers mid-spin fall back to the synchronous
        # staging path instead of landing rows in a ring nobody will drain
        ring, self._ring = self._ring, None
        # generous: the feeder may sit inside a first-compile (~40 s on TPU)
        self._feeder.join(timeout=120)
        if self._feeder.is_alive():  # pragma: no cover — wedged device step
            logging.getLogger("siddhi_tpu").warning(
                "async feeder for %r did not stop; leaving its ring "
                "attached (a second consumer would race it)",
                self.definition.id)
            return
        # feeder is gone: drain anything still staged (under the lock so a
        # concurrent user flush cannot become a second consumer)
        with self.ctx.controller_lock:
            self._drain_ring(ring=ring)
        self._feeder = None

    def _feed_loop(self) -> None:
        from .. import native as native_mod
        n = native_mod.native
        while not self._feeder_stop.is_set():
            ring = self._ring
            if ring is None:  # detached by shutdown
                break
            if n.ring_size(ring) == 0:
                self._feeder_wake.wait(timeout=0.001)
                self._feeder_wake.clear()
                continue
            try:
                with self.ctx.controller_lock:
                    self._drain_ring(max_batches=4, ring=ring)
            except Exception:  # pragma: no cover — surfaced via @OnError/log
                logging.getLogger("siddhi_tpu").exception(
                    "async feeder error on %r", self.definition.id)

    def _bounded_feed_loop(self) -> None:
        """Drainer for bounded (drop/fault-policy) junctions: flush whenever
        the pre-staging queue holds rows. Overload shows up as the queue
        pinned at capacity with the policy counters climbing — never as
        unbounded host memory."""
        while not self._feeder_stop.is_set():
            if not self._tap_queue:
                self._feeder_wake.wait(timeout=0.001)
                self._feeder_wake.clear()
                continue
            try:
                self.flush()
            except Exception:  # pragma: no cover — surfaced via @OnError/log
                logging.getLogger("siddhi_tpu").exception(
                    "bounded feeder error on %r", self.definition.id)

    def _drain_ring(self, max_batches: Optional[int] = None,
                    ring=None) -> None:
        """Pop ring entries into the staging buffers and flush as batches.
        Single-consumer discipline: callers hold the controller lock. Owns
        the _draining flag so the nested flush() calls cannot re-enter the
        drain (which would defeat max_batches and hold the lock unbounded)."""
        from .. import native as native_mod
        ring = ring if ring is not None else self._ring
        if ring is None or getattr(self._reentry, "draining", False):
            return
        n = native_mod.native
        self._reentry.draining = True
        try:
            batches = 0
            while max_batches is None or batches < max_batches:
                tss, rows = n.ring_pop_batch(ring, self.batch_size)
                if not rows:
                    break
                self._staged_ts.extend(tss)
                self._staged_rows.extend(rows)
                self.flush()
                batches += 1
        finally:
            self._reentry.draining = False

    def publish_batch(self, batch: EventBatch, now: int) -> None:
        """Device-side publication (query output chaining). Staged host rows
        are flushed first to preserve arrival order."""
        with self.ctx.controller_lock:
            if self.taps:
                # taps need host rows; only derived streams feeding a
                # multi-stream sequence pay this decode
                for ev in batch.to_host_events(self.codec):
                    for tap in self.taps:
                        tap(ev.timestamp, tuple(ev.data))
            if self._staged_rows:
                self.flush()
            self._deliver(batch, now)

    # ----------------------------------------------------------------- flush

    def flush(self, now: Optional[int] = None) -> None:
        if getattr(self._reentry, "flushing", False):
            # same-thread re-entrant flush (a callback sending into its own
            # stream): defer to the outer delivery
            return
        if self._redirect is not None:
            # cutover leftovers (rows a producer staged while racing the
            # swap) forward to the v2 junction as ORIGINAL rows — v2
            # re-journals and re-encodes them under its own codec — then the
            # flush itself delegates
            target = self._resolve_redirect()
            with self.ctx.controller_lock:
                if self._tap_queue:
                    with self._tap_lock:
                        q, self._tap_queue = self._tap_queue, []
                    for ts, row in q:
                        self._staged_ts.append(ts)
                        self._staged_rows.append(row)
                if self._staged_rows:
                    rows, tss = self._staged_rows, self._staged_ts
                    self._staged_rows, self._staged_ts = [], []
                    target.send_rows(tss, rows)
            return target.flush(now)
        if self._pipeline is not None and not self._lock_owned():
            # barrier: every row submitted to the parallel pipeline before
            # this flush is delivered before it returns. Lock-holding
            # callers (auto-flusher, heartbeat, callbacks) skip the barrier
            # — the feeder needs the controller lock to make progress.
            self._pipeline.drain()
        # the staged-list swap and delivery run under the controller lock:
        # the feeder thread extends/flushes the same lists
        with self.ctx.controller_lock:
            if self._ring is not None and not getattr(self._reentry,
                                                      "draining", False):
                self._drain_ring()
            if self._tap_queue:
                with self._tap_lock:
                    q, self._tap_queue = self._tap_queue, []
                for ts, row in q:
                    self._staged_ts.append(ts)
                    self._staged_rows.append(row)
            if self._staged_rows:
                rows, tss = self._staged_rows, self._staged_ts
                self._staged_rows, self._staged_ts = [], []
                self._flush_rows(rows, tss, now)
        # flush is where staged depth shrinks: check the low watermark and
        # resume paused sources (their buffered payloads re-enter admission)
        self._maybe_resume()

    def _flush_rows(self, rows, tss, now) -> None:
        if self._et is not None:
            # event-time gate: late rows divert (kind="late"), the rest
            # buffer until the watermark passes them; what comes back is
            # sorted by event time, timestamped WITH event time, and (for
            # lateness > 0) grouped one delivery batch per distinct event
            # time, so the device plane sees an in-order stream with
            # arrival-permutation-invariant batch boundaries
            for g_tss, g_rows in self._et.admit(tss, rows):
                self._emit_rows(g_rows, g_tss, now)
            return
        self._emit_rows(rows, tss, now)

    def _emit_rows(self, rows, tss, now) -> None:
        cap = self.batch_size
        n = len(rows)
        tele = getattr(self.ctx, "telemetry", None)
        tracing = tele is not None and tele.on
        for start in range(0, n, cap):
            t0 = time.perf_counter_ns() if tracing else 0
            chunk_rows = rows[start:start + cap]
            chunk_ts = tss[start:start + cap]
            m = len(chunk_rows)
            pad = self._pad_cap(m)  # power-of-two lane bucket for partials
            ts_arr = np.zeros(pad, dtype=np.int64)
            ts_arr[:m] = chunk_ts
            # pad timestamps monotonically so searchsorted stays correct
            if m < pad and m > 0:
                ts_arr[m:] = chunk_ts[-1]
            cols = self.codec.rows_to_columns(chunk_rows, n_pad=pad)
            if tracing:
                h2d_t0 = time.perf_counter_ns()
                batch = EventBatch.from_numpy(ts_arr, cols, m)
                trace = tele.mint(self.definition.id, m, t0=t0)
                trace.h2d_ns = time.perf_counter_ns() - h2d_t0
                # plain instance attribute: invisible to pytree flatten, so
                # it never reaches a jitted step (EventBatch is a non-slots
                # dataclass); _deliver pops it
                batch._trace = trace
                if m > 0:
                    tele.record_lag(self.definition.id, int(chunk_ts[-1]))
            else:
                batch = EventBatch.from_numpy(ts_arr, cols, m)
            self._deliver(batch, now if now is not None else
                          self.ctx.timestamp_generator.current_time())

    def _handle_error(self, e: Exception, batch: EventBatch, now: int) -> None:
        """@OnError dispatch (reference: StreamJunction.java:371-463)."""
        action = self.on_error_action
        if action == "stream" and self.fault_junction is not None:
            # route failed events + error message into `!stream`
            for ev in batch.to_host_events(self.codec):
                self.fault_junction.send_row(ev.timestamp,
                                             tuple(ev.data) + (str(e),))
            self.fault_junction.flush(now)
            return
        if action == "store":
            store = getattr(self.ctx, "error_store", None)
            if store is not None:
                events = [(ev.timestamp, tuple(ev.data))
                          for ev in batch.to_host_events(self.codec)]
                store.save(self.ctx.name, self.definition.id, events, str(e))
                return
            logging.getLogger("siddhi_tpu").error(
                "@OnError(action='STORE') on %r but no error store configured; "
                "logging instead", self.definition.id)
        logging.getLogger("siddhi_tpu").exception(
            "error processing %r events: %s", self.definition.id, e)

    def _divert_breaker(self, br, batch: EventBatch, now: int,
                        err: Optional[Exception]) -> None:
        """Route a failed/blocked query's input batch to the fault stream or
        ErrorStore instead of executing it (reference intent: OnErrorAction,
        applied at query granularity). Empty batches (heartbeats) divert
        nothing — an open breaker must not spam the store with timer ticks."""
        qname = br.owner or "?"
        msg = (f"circuit breaker open for query {qname!r}" if err is None
               else f"query {qname!r} failed: {err}")
        events = batch.to_host_events(self.codec)
        if not events:
            return
        self.ctx.statistics.track_breaker_divert(qname, len(events))
        if self.fault_junction is not None:
            for ev in events:
                self.fault_junction.send_row(ev.timestamp,
                                             tuple(ev.data) + (msg,))
            self.fault_junction.flush(now)
            return
        store = getattr(self.ctx, "error_store", None)
        if store is not None:
            store.save(self.ctx.name, self.definition.id,
                       [(ev.timestamp, tuple(ev.data)) for ev in events],
                       msg, kind="breaker")
            return
        logging.getLogger("siddhi_tpu").error(
            "%s; %d event(s) dropped (no fault stream or error store)",
            msg, len(events))

    def _divert_late(self, rows: list) -> None:
        """Events older than the watermark leave through a REPLAYABLE side
        output — ErrorStore `kind="late"` entries carrying the original
        (event_ts, row) pairs so `/errors/replay` can re-admit them through
        the gate's bypass for corrected re-emission. Never silent: the late
        counters are exact by construction."""
        et = self._et
        msg = (f"late arrival on {self.definition.id!r}: event time behind "
               f"the watermark (allowed.lateness="
               f"{et.cfg.lateness_ms if et is not None else 0} ms)")
        self.ctx.statistics.track_late(self.definition.id, len(rows))
        tele = getattr(self.ctx, "telemetry", None)
        if tele is not None:
            tele.record_late(self.definition.id, len(rows))
        store = getattr(self.ctx, "error_store", None)
        if store is not None:
            store.save(self.ctx.name, self.definition.id,
                       [(ts, tuple(row)) for ts, row in rows], msg,
                       kind="late")
            return
        logging.getLogger("siddhi_tpu").warning(
            "%s; %d row(s) dropped (no error store to divert to)",
            msg, len(rows))

    def attach_event_time(self, cfg) -> None:
        """App runtime hook: install the @app:eventTime gate (build time,
        before start_async, so the pipeline gate below sees it)."""
        from .event_time import EventTimeGate
        self._et = EventTimeGate(self, cfg)

    def release_event_time(self, now: Optional[int] = None) -> None:
        """Drain the event-time gate: staged rows pass the gate first, then
        the watermark jumps to max_ts and every buffered row delivers in
        event-time order (end-of-stream / shutdown / explicit drain)."""
        if self._et is None:
            return
        with self.ctx.controller_lock:
            self.flush(now)
            for g_tss, g_rows in self._et.release_all():
                self._emit_rows(g_rows, g_tss, now)

    def heartbeat(self, now: int) -> None:
        """Advance time with no data: flush staged rows then deliver an empty
        batch so time-window expirations fire (the watermark analogue of the
        reference's Scheduler TIMER events, core/util/Scheduler.java:48)."""
        with self.ctx.controller_lock:
            self.flush(now)
            if self._et is not None:
                # idle.timeout elapsed with rows still held: release them —
                # an idle stream must not pin its panes open forever
                for g_tss, g_rows in self._et.maybe_idle():
                    self._emit_rows(g_rows, g_tss, now)
            # timer batches carry no rows: the smallest lane bucket keeps
            # idle heartbeats off the full-capacity kernel
            empty = EventBatch.empty(self.definition, self._pad_cap(0))
            self._deliver(empty, now)

    def _deliver(self, batch: EventBatch, now: int) -> None:
        note_blocking("device.dispatch", allow=("app.controller",))
        self._reentry.flushing = True
        tele = getattr(self.ctx, "telemetry", None)
        trace = None
        if tele is not None and tele.on:
            # adopt the trace minted at batch formation; derived-stream
            # publishes and heartbeats mint one here (size unknown without a
            # device sync — left None)
            trace = batch.__dict__.pop("_trace", None)
            if trace is None:
                trace = tele.mint(self.definition.id)
            trace.deliver_t0 = time.perf_counter_ns()
            tele.push_active(trace)
        try:
            n = int(batch.count()) if self.ctx.statistics.enabled else 0
            self.ctx.statistics.track_in(self.definition.id, n)
            self.ctx.statistics.track_batch(self.definition.id)
            decoder = self.ctx.decoder
            for r in self.receivers:
                br = (getattr(r, "breaker", None)
                      or getattr(getattr(r, "runtime", None), "breaker", None))
                if br is not None and not br.allow():
                    # OPEN breaker inside its cooldown: divert without
                    # dispatching — the poisoned query stops seeing traffic,
                    # siblings on this junction keep running
                    self._divert_breaker(br, batch, now, None)
                    continue
                try:
                    if decoder is not None and isinstance(
                            r, (StreamCallback, BatchStreamCallback)):
                        decoder.submit(r, batch, now, junction=self)
                    else:
                        r.on_batch(batch, now)
                    if br is not None:
                        br.record_success()
                except Exception as e:  # noqa: BLE001
                    if br is not None:
                        # breaker-guarded receivers never kill the app: the
                        # failure counts toward the trip and the failed
                        # batch leaves through the divert path
                        qname = br.owner or getattr(r, "name", "?")
                        self.ctx.statistics.track_breaker_failure(qname)
                        if br.record_failure():
                            self.ctx.statistics.track_breaker_open(qname)
                            rec = getattr(self.ctx, "recorder", None)
                            if rec is not None:
                                # freeze evidence at the trip, not later: the
                                # rings still hold the failing batches
                                rec.trigger(
                                    "breaker_open",
                                    reason=f"query {qname!r}: {e}")
                        self._divert_breaker(br, batch, now, e)
                    elif self.on_error is not None:
                        self.on_error(e, batch)
                    elif self.on_error_action is not None:
                        self._handle_error(e, batch, now)
                    else:
                        raise
        finally:
            self._reentry.flushing = False
            if trace is not None:
                tele.pop_active(trace)
        # deliver rows staged re-entrantly during callbacks
        if self._staged_rows and len(self._staged_rows) >= self.batch_size:
            self.flush()


class InputHandler:
    """User ingestion facade (reference: core/stream/input/InputHandler.java:28).
    send() stages rows; delivery happens on batch-full or runtime.flush()."""

    def __init__(self, junction: StreamJunction) -> None:
        self.junction = junction

    def send(self, data, timestamp: Optional[int] = None) -> None:
        if isinstance(data, Event):
            self.junction.send_row(data.timestamp, data.data)
            return
        if isinstance(data, (list,)) and data and isinstance(data[0], Event):
            for ev in data:
                self.junction.send_row(ev.timestamp, ev.data)
            return
        ts = timestamp if timestamp is not None else \
            self.junction.ctx.timestamp_generator.current_time()
        self.junction.send_row(ts, tuple(data))

    def send_batch(self, rows: Sequence[Sequence],
                   timestamps=None) -> None:
        """Batched ingestion: stage many rows in ONE call (reference parity:
        InputHandler.java:50 send(Event[]) — the reference's batch overload;
        here it is also the fast path, amortizing per-event Python overhead).
        `timestamps`: None (one arrival time for the whole batch), a single
        int, or a per-row sequence."""
        n = len(rows)
        if n == 0:
            return
        if timestamps is None or isinstance(timestamps, int):
            ts = timestamps if timestamps is not None else \
                self.junction.ctx.timestamp_generator.current_time()
            tss = [ts] * n
        else:
            if len(timestamps) != n:
                raise ValueError(
                    f"send_batch: {n} rows but {len(timestamps)} timestamps")
            tss = [int(t) for t in timestamps]
        self.junction.send_rows(tss, rows)

    def send_columns(self, columns: dict, timestamps=None,
                     count: Optional[int] = None) -> None:
        """Columnar ingestion — the TPU-native public fast path: numpy
        arrays (one per attribute) encode vectorized (string columns intern
        per DISTINCT value; numeric columns cast whole-array) and enter the
        pipeline with zero per-row Python work. String columns accept str
        object arrays or pre-encoded int32 codes."""
        # resolve a blue-green redirect BEFORE any WAL/codec use: journaling
        # or interning through the v1 junction would strand records in a
        # retired journal / string table
        j = self.junction._resolve_redirect()
        n = count if count is not None else \
            min(len(v) for v in columns.values())
        if n == 0:
            return
        if timestamps is None or isinstance(timestamps, int):
            ts = timestamps if timestamps is not None else \
                j.ctx.timestamp_generator.current_time()
            ts_arr = np.full(n, ts, dtype=np.int64)
        else:
            ts_arr = np.asarray(timestamps, dtype=np.int64)
            if ts_arr.shape[0] < n:
                raise ValueError(
                    f"send_columns: {n} rows but {ts_arr.shape[0]} timestamps")
        if j.taps or j._et is not None:
            # multi-stream sequences consume rows in send order, and the
            # event-time gate classifies/reorders host rows BEFORE batch
            # formation: both fall back to the row path with the ORIGINAL
            # (un-encoded) values, in declaration order with OBJECT attrs
            lists = []
            for a in j.definition.attributes:
                if a.name in columns:
                    lists.append(list(np.asarray(columns[a.name])[:n]))
                else:
                    lists.append([None] * n)
            for ts, row in zip(ts_arr[:n].tolist(), zip(*lists)):
                j.send_row(ts, row)
            return
        if (j._pipeline is not None and j.wal is None
                and not j._lock_owned()):
            # parallel ingress: claim ring slots here, encode + intern in
            # the worker pool, device transfer double-buffered by the
            # feeder — this producer thread returns as soon as the runs
            # are claimed
            j.ctx.timestamp_generator.observe_event_time(
                int(ts_arr[:n].max()))
            done = j._pipeline.submit_columns(ts_arr, columns, n)
            if done >= n:
                return
            ts_arr = ts_arr[done:]
            columns = {k: np.asarray(v)[done:] for k, v in columns.items()}
            n -= done
        # interning mutates the app-global StringTable: hold the controller
        # lock (RLock — send_column_batch re-enters it) so the Python-loop
        # fallback cannot race the async feeder's locked encode path
        with j.ctx.controller_lock:
            # a cutover completing while we waited on the lock re-points
            # the junction: re-resolve, and nest the LIVE junction's lock
            # (re-entrant no-op when unchanged; v1->v2 ordering matches the
            # upgrade path) so journal+encode hit the live one safely
            j = j._resolve_redirect()
            with j.ctx.controller_lock:
                if j.wal is not None:
                    # inside the lock (atomic vs persist's snapshot+rotate —
                    # see send_row), journaling the ORIGINAL pre-interning
                    # values: dictionary codes are process-local and would
                    # not survive a restart
                    j.wal.append_columns(
                        j.definition.id, ts_arr[:n].tolist(),
                        {k: np.asarray(v)[:n] for k, v in columns.items()})
                cols = j.codec.encode_columns(columns, n)
                j.send_column_batch(ts_arr, cols, n)
