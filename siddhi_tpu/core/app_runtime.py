"""SiddhiAppRuntime — one planned, running app.

Reference: core/SiddhiAppRuntimeImpl.java:103 (junction map:124, query map:122,
start():449, shutdown():552, persist():686). The TPU build keeps the same user
surface but execution is synchronous single-controller: sends stage rows into
junction buffers; flush() drives every staged batch through the jitted query
pipeline and cascades device-to-device until quiescent.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ..errors import (
    DefinitionNotExistError,
    SiddhiAppCreationError,
)
from ..extension.registry import Registry
from ..query_api import Query, SiddhiApp, StreamDefinition
from ..query_api.execution import OutputAction, SingleInputStream
from .context import SiddhiAppContext, Statistics, TimestampGenerator
from .event import StreamCodec
from .query_runtime import FunctionQueryCallback, QueryCallback, QueryRuntime
from .stream import (
    FunctionStreamCallback,
    InputHandler,
    StreamCallback,
    StreamJunction,
)


class SiddhiAppRuntime:
    def __init__(self, app: SiddhiApp, registry: Registry,
                 batch_size: int = 0, group_capacity: int = 0,
                 error_store=None, config_manager=None,
                 mesh=None, partition_capacity: int = 0,
                 async_callbacks: bool = False,
                 auto_flush_ms: Optional[float] = None,
                 aot_warmup: bool = False,
                 wal_dir: Optional[str] = None,
                 persistence_interval_s: Optional[float] = None,
                 optimize: Optional[bool] = None) -> None:
        self.app = app
        #: LintReport attached by SiddhiManager's SIDDHI_LINT gate
        #: (None when linting is off or the app was built directly)
        self.lint_report = None
        #: AOT-compile every query's step ladder at start() (also
        #: SIDDHI_AOT_WARMUP=1) so the first real batch never pays
        #: first-compile latency — see warmup()
        import os as _os
        self.aot_warmup = aot_warmup or \
            _os.environ.get("SIDDHI_AOT_WARMUP", "") not in ("", "0")
        playback_ann = app.annotation("app:playback")
        idle_ms = increment_ms = None
        if playback_ann is not None:
            from .partition import _parse_annotation_time
            idle = playback_ann.element("idle.time")
            inc = playback_ann.element("increment")
            idle_ms = _parse_annotation_time(idle) if idle else None
            increment_ms = _parse_annotation_time(inc) if inc else None
            if increment_ms is None and idle_ms is not None:
                increment_ms = idle_ms  # idle.time alone: bump by itself
        self.ctx = SiddhiAppContext(
            name=app.name,
            registry=registry,
            timestamp_generator=TimestampGenerator(
                playback=playback_ann is not None,
                playback_increment_ms=increment_ms or 0,
                idle_time_ms=idle_ms),
            batch_size=batch_size,
            group_capacity=group_capacity,
            mesh=mesh,
            partition_capacity=partition_capacity,
            playback=playback_ann is not None,
        )
        self.ctx.runtime = self
        self.ctx.async_callbacks = async_callbacks
        # wall-clock auto-flush — the Disruptor's immediate-consumption role
        # (reference: StreamJunction.java:68 batchSize knob +
        # core/util/Scheduler.java:48 timer re-entry): staged rows are
        # flushed within ~auto_flush_ms without the caller polling flush().
        # Enable per runtime (kwarg) or per app (@app:autoFlush('10 ms')).
        af_ann = app.annotation("app:autoFlush")
        if auto_flush_ms is None and af_ann is not None:
            from .partition import _parse_annotation_time
            v = af_ann.element("interval") or af_ann.element()
            auto_flush_ms = _parse_annotation_time(v) if v else 10.0
        self.auto_flush_ms = auto_flush_ms
        self._flusher_stop = None
        self._flusher_thread = None
        # crash recovery: @app:persist(interval='30 sec', wal.dir='/var/wal')
        # or the wal_dir / persistence_interval_s kwargs — a periodic
        # persistence scheduler plus a write-ahead ingress journal so
        # recover() = restore_last_revision() + WAL replay (state/wal.py)
        persist_ann = app.annotation("app:persist")
        if persist_ann is not None:
            from .partition import _parse_annotation_time
            iv = persist_ann.element("interval") or persist_ann.element()
            if persistence_interval_s is None and iv:
                persistence_interval_s = _parse_annotation_time(iv) / 1000.0
            wd = persist_ann.element("wal.dir")
            if wal_dir is None and wd:
                wal_dir = wd
        self.persistence_interval_s = persistence_interval_s
        self._persist_stop = None
        self._persist_thread = None
        self._recovering = False
        self.wal = None
        if wal_dir:
            from ..state.wal import WriteAheadLog
            self.wal = WriteAheadLog(wal_dir, app.name)
        self.ctx.error_store = error_store
        self.ctx.config_manager = config_manager
        # out-of-order event time: @app:eventTime(timestamp='ts',
        # allowed.lateness='5 sec', idle.timeout='1 min') — parsed BEFORE
        # _build() (query runtimes read ctx.event_time to put externalTime
        # windows into watermark-driven emission), gates attached AFTER
        # (they hang off the built ingress junctions)
        et_ann = app.annotation("app:eventTime")
        self.ctx.event_time = None
        if et_ann is not None:
            from .event_time import EventTimeConfig
            from .partition import _parse_annotation_time
            attr = et_ann.element("timestamp") or et_ann.element()
            if not attr:
                raise SiddhiAppCreationError(
                    "@app:eventTime needs a timestamp attribute: "
                    "@app:eventTime(timestamp='ts', ...)")
            lat = et_ann.element("allowed.lateness")
            idle = et_ann.element("idle.timeout")
            self.ctx.event_time = EventTimeConfig(
                attr=attr,
                lateness_ms=int(_parse_annotation_time(lat)) if lat else 0,
                idle_timeout_ms=int(_parse_annotation_time(idle))
                if idle else None)
        from .event import StringTable
        self.ctx.global_strings = StringTable()
        from ..telemetry import AppTelemetry
        self.ctx.telemetry = AppTelemetry(app.name)
        self._owns_jax_trace = False
        stats_ann = app.annotation("app:statistics")
        if stats_ann is not None:
            # @app:statistics('true'|'BASIC'|'DETAIL') (reference:
            # SiddhiAppParser.java:113-148, metrics/Level.java)
            val = (stats_ann.element() or "BASIC").upper()
            level = {"TRUE": "BASIC", "FALSE": "OFF"}.get(val, val)
            self.ctx.statistics = Statistics()
            try:
                self.ctx.statistics.set_level(level)
            except ValueError as e:
                raise SiddhiAppCreationError(str(e)) from e

        # device-resident supersteps: @app:superstep(k='8') batches K async
        # ingress chunks into one lax.scan dispatch (core/superstep.py).
        # Env SIDDHI_SUPERSTEP_K overrides the annotation (bench sweeps, CI
        # parity runs); ineligible plans decline loudly at first dispatch.
        ss_k = 1
        ss_ann = app.annotation("app:superstep")
        if ss_ann is not None:
            v = ss_ann.element("k") or ss_ann.element()
            try:
                ss_k = int(v) if v else 1
            except ValueError as e:
                raise SiddhiAppCreationError(
                    f"@app:superstep k must be an integer, got {v!r}") from e
        env_k = os.environ.get("SIDDHI_SUPERSTEP_K", "").strip()
        if env_k:
            try:
                ss_k = int(env_k)
            except ValueError:
                pass
        self.ctx.superstep_k = max(1, ss_k)

        self.junctions: dict[str, StreamJunction] = {}
        self.input_handlers: dict[str, InputHandler] = {}
        self.query_runtimes: dict[str, QueryRuntime] = {}
        self.tables: dict = {}
        self.windows: dict = {}
        self.triggers: dict = {}
        self.aggregations: dict = {}
        self.partitions: dict = {}
        self.sources: list = []
        self.sinks: list = []
        self.fault_junctions: dict[str, StreamJunction] = {}
        self._started = False

        # multi-tenant quotas (@app:tenant + per-query @tenant): registry
        # wired BEFORE _build() so build-time assignment enforces queries=
        # quotas, and onto the context as the always-on device-time meter
        from .tenant import tenants_from_app
        self.tenants = tenants_from_app(app)
        self.ctx.tenant_meter = self.tenants
        if self.tenants is not None:
            self.tenants.bind_telemetry(self.ctx.telemetry)
        #: bumped by every attach/detach (splice churn) — the flusher loop
        #: and other cached plan-shape decisions recompute when it moves
        self._plan_epoch = 0
        self._splice_seq = 0

        self._build()

        # multi-query shared execution (@app:optimize / SIDDHI_OPTIMIZE /
        # the optimize kwarg): fuse co-resident queries into shared compiled
        # steps AFTER the runtimes exist but BEFORE any traffic or warmup —
        # self.app stays the pre-optimization app, so plan fingerprints,
        # snapshots, and upgrade diffs see the unfused layout
        self.shared_groups: list = []
        self.optimizer_report: Optional[dict] = None
        from ..analysis.optimizer import optimizer_enabled
        if optimizer_enabled(app, optimize):
            from .shared import build_shared_groups
            self.optimizer_report = build_shared_groups(self)

        if self.wal is not None:
            # journal INGRESS junctions only: user-defined streams take rows
            # from outside the engine; derived/trigger/fault streams are
            # reproducible from their inputs
            for sid in app.stream_definitions:
                self.junctions[sid].wal = self.wal

        if self.ctx.event_time is not None:
            # event-time gates on INGRESS junctions carrying the annotated
            # attribute (derived streams inherit sorted order from their
            # inputs, so they never gate). WAL note: rows journal at send
            # time, BEFORE the gate — replay re-runs them through it, so
            # buffered/late classification survives a crash.
            cfg = self.ctx.event_time
            from ..query_api.definition import AttributeType as _AT
            gated = 0
            for sid, sd in app.stream_definitions.items():
                attr = next((a for a in sd.attributes
                             if a.name == cfg.attr), None)
                if attr is None:
                    continue
                if attr.type not in (_AT.INT, _AT.LONG):
                    raise SiddhiAppCreationError(
                        f"@app:eventTime: attribute {cfg.attr!r} on stream "
                        f"{sid!r} must be INT or LONG (epoch ms), got "
                        f"{attr.type.name}")
                self.junctions[sid].attach_event_time(cfg)
                gated += 1
            if gated == 0:
                raise SiddhiAppCreationError(
                    f"@app:eventTime: no stream defines the timestamp "
                    f"attribute {cfg.attr!r}")

        # SLO engine (@app:slo / per-query @slo; None when undeclared) and
        # the always-on flight recorder — built AFTER _build() so objective
        # binding can resolve query names and the recorder can snapshot a
        # fully-wired runtime. SLO breaches trigger the recorder.
        from ..telemetry.recorder import FlightRecorder
        from ..telemetry.slo import slo_engine_from_app
        diag_ann = app.annotation("app:diagnostics")
        diag_dir = diag_ann.element("dir") if diag_ann is not None else None
        self.ctx.recorder = FlightRecorder(self, bundle_dir=diag_dir)
        self.slo_engine = slo_engine_from_app(self)
        if self.slo_engine is not None:
            rec = self.ctx.recorder
            self.slo_engine.on_breach = lambda o, ev: rec.trigger(
                "slo_breach", reason=f"{o.id} burn fast="
                f"{o.last_fast.get('burn_rate', 0):.2f} slow="
                f"{o.last_slow.get('burn_rate', 0):.2f}")
        self._slo_stop = None
        self._slo_thread = None

    # ------------------------------------------------------------------ build

    def _build(self) -> None:
        app, ctx = self.app, self.ctx

        if app.function_definitions:
            # app-scoped registry: `define function` must not leak across apps
            ctx.registry = ctx.registry.copy()
            from .function import bind_app_functions
            bind_app_functions(app, ctx.registry)

        from ..io.wiring import build_sink, build_source
        from ..query_api.definition import Attribute, AttributeType
        for sd in app.stream_definitions.values():
            junction = StreamJunction(sd, ctx)
            self.junctions[sd.id] = junction
            if junction.on_error_action == "stream":
                # `!stream` fault junction: original attrs + _error message
                # (reference: StreamJunction fault streams :371-463); the
                # `fault` overflow policy and breaker diverts route through
                # it too when the stream declares @OnError(action='STREAM')
                fd = StreamDefinition(
                    id=f"!{sd.id}",
                    attributes=tuple(sd.attributes)
                    + (Attribute("_error", AttributeType.STRING),))
                junction.fault_junction = StreamJunction(fd, ctx)
                self.fault_junctions[sd.id] = junction.fault_junction
            for ann in sd.annotations or ():
                if ann.name.lower() == "source":
                    self.sources.append(build_source(ann, junction, ctx))
                elif ann.name.lower() == "sink":
                    self.sinks.append(build_sink(ann, junction, ctx))

        from .table import InMemoryTable
        for td in app.table_definitions.values():
            store_ann = (td.annotation("store") or td.annotation("Store")) \
                if td.annotations else None
            if store_ann is not None:
                from ..io.record_table import RecordTableRuntime
                self.tables[td.id] = RecordTableRuntime(
                    td, ctx, self.ctx.registry)
            else:
                self.tables[td.id] = InMemoryTable(td, ctx)

        from .window import NamedWindow
        for wd in app.window_definitions.values():
            self.windows[wd.id] = NamedWindow(wd, ctx, self.ctx.registry)

        from .trigger import TriggerRuntime, trigger_stream_definition
        for td in app.trigger_definitions.values():
            sd = trigger_stream_definition(td)
            self.junctions[sd.id] = StreamJunction(sd, ctx)
            self.triggers[td.id] = TriggerRuntime(td, self.junctions[sd.id], ctx)

        from .aggregation import AggregationRuntime
        for ad in app.aggregation_definitions.values():
            junction = self.junctions.get(ad.input_stream_id)
            if junction is None:
                raise DefinitionNotExistError(
                    f"aggregation {ad.id!r}: stream {ad.input_stream_id!r} "
                    "is not defined")
            self.aggregations[ad.id] = AggregationRuntime(
                ad, ctx, junction, self.ctx.registry)

        for i, query in enumerate(app.queries):
            self._add_query(query, f"query{i + 1}")

        from .partition import PartitionRuntime
        for i, p in enumerate(app.partitions):
            pr = PartitionRuntime(p, self, i + 1)
            self.partitions[pr.name] = pr

    def _add_query(self, query: Query, default_name: str) -> None:
        from ..query_api.execution import JoinInputStream
        name = query.name or default_name

        if self.tenants is not None:
            # quota check BEFORE any runtime state exists: an over-quota
            # tenant's attach raises here with nothing to unwind
            from .tenant import query_tenant
            tid = query_tenant(query)
            if tid is not None:
                self.tenants.assign(name, tid)

        from ..query_api.execution import StateInputStream
        if isinstance(query.input_stream, JoinInputStream):
            qr = self._add_join_query(query, name)
        elif isinstance(query.input_stream, StateInputStream):
            qr = self._add_pattern_query(query, name)
        elif isinstance(query.input_stream, SingleInputStream):
            sid = query.input_stream.stream_id
            if query.input_stream.is_fault:
                junction = self.fault_junctions.get(sid)
                if junction is None:
                    raise DefinitionNotExistError(
                        f"stream {sid!r} has no fault stream (add "
                        "@OnError(action='STREAM'))")
                qr = QueryRuntime(query, self.ctx, junction, self.ctx.registry,
                                  name=name, tables=self.tables)
                junction.subscribe(qr)
                self.query_runtimes[name] = qr
                self._wire_output(qr, query)
                return
            junction = self.junctions.get(sid)
            if junction is None and sid in self.windows:
                # `from W ...` consumes the named window's emissions
                # (reference: WindowWindowProcessor via core/window/Window.java)
                if query.input_stream.handlers.window is not None:
                    raise SiddhiAppCreationError(
                        f"named window {sid!r} cannot take a further window "
                        "in FROM (a window cannot be windowed)")
                junction = self.windows[sid].output_junction
            if junction is None:
                raise DefinitionNotExistError(f"stream {sid!r} is not defined")
            qr = QueryRuntime(query, self.ctx, junction, self.ctx.registry,
                              name=name, tables=self.tables)
            junction.subscribe(qr)
        else:
            raise SiddhiAppCreationError(
                f"{type(query.input_stream).__name__} queries are not yet supported")
        if getattr(qr, "breaker", None) is None:
            # join/pattern runtimes don't build one themselves; single-input
            # QueryRuntime already did (core/breaker.py)
            from .breaker import breaker_from_annotations
            qr.breaker = breaker_from_annotations(query, name=name)
        self.query_runtimes[name] = qr

        self._wire_output(qr, query)

    def _add_join_query(self, query: Query, name: str):
        from .join_runtime import JoinQueryRuntime, _JoinSideReceiver
        qr = JoinQueryRuntime(query, self.ctx, self.junctions, self.tables,
                              self.ctx.registry, name, windows=self.windows,
                              aggregations=self.aggregations)
        if qr.left.junction is not None:
            qr.left.junction.subscribe(_JoinSideReceiver(qr, True))
        if qr.right.junction is not None:
            qr.right.junction.subscribe(_JoinSideReceiver(qr, False))
        return qr

    def _add_pattern_query(self, query: Query, name: str):
        from .pattern_runtime import (MERGED_SID, PatternQueryRuntime,
                                      _PatternSideReceiver)
        qr = PatternQueryRuntime(query, self.ctx, self.junctions, self.tables,
                                 self.ctx.registry, name)
        if qr.merged_junction is not None:
            # multi-stream sequence: the tagged merged junction (fed by
            # send-order taps on the sources) is the only feed; register it
            # so flush()/shutdown drive it like any other junction
            qr.merged_junction.subscribe(_PatternSideReceiver(qr, MERGED_SID))
            self.junctions[qr.merged_junction.definition.id] = \
                qr.merged_junction
        else:
            for sid in qr.junctions:
                qr.junctions[sid].subscribe(_PatternSideReceiver(qr, sid))
        return qr

    def _wire_output(self, qr, query: Query) -> None:
        out = query.output_stream
        if out.action == OutputAction.INSERT and out.is_fault and out.target_id:
            target = self.fault_junctions.get(out.target_id)
            if target is None:
                raise DefinitionNotExistError(
                    f"stream {out.target_id!r} has no fault stream (add "
                    "@OnError(action='STREAM'))")
            qr.output_junction = target
            return
        if out.action == OutputAction.INSERT and out.target_id:
            if out.target_id in self.tables:
                table = self.tables[out.target_id]
                # unionSet-projection provenance flows into the table: the
                # inserted column carries the set-size projection, so
                # downstream sizeOfSet(T.attr) stays readable (and ordinary
                # LONG columns stay rejected)
                marks = {n for n in getattr(qr.selector, "host_set_slots", {})
                         if n in table.attr_types}
                if marks:
                    table.set_projection_attrs = (
                        set(getattr(table, "set_projection_attrs", ()) or ())
                        | marks)
                qr.output_junction = _TableJunctionAdapter(table)
            elif out.target_id in self.windows:
                from .window import WindowJunctionAdapter
                qr.output_junction = WindowJunctionAdapter(
                    self.windows[out.target_id],
                    out_types=qr.selector.out_types)
            else:
                target = self.junctions.get(out.target_id)
                if target is None:
                    # auto-define the output stream from the select list
                    # (reference: OutputParser infers output stream definitions)
                    sd = qr.output_definition
                    target = StreamJunction(sd, self.ctx, codec=qr.output_codec)
                    self.junctions[sd.id] = target
                qr.output_junction = target
        elif out.action in (OutputAction.DELETE, OutputAction.UPDATE,
                            OutputAction.UPDATE_OR_INSERT):
            from ..io.record_table import (RecordTableOutputExecutor,
                                           RecordTableRuntime)
            from .table import TableOutputExecutor
            table = self.tables.get(out.target_id)
            if table is None:
                raise DefinitionNotExistError(f"table {out.target_id!r} is not defined")
            aliases = [getattr(query.input_stream, "stream_id", None),
                       getattr(query.input_stream, "reference_id", None)]
            executor_cls = (RecordTableOutputExecutor
                            if isinstance(table, RecordTableRuntime)
                            else TableOutputExecutor)
            qr.table_executor = executor_cls(
                table, out, qr.selector.out_types, qr.output_codec,
                self.ctx.registry, out_frame_aliases=aliases)

    # ------------------------------------------------------ churn (splice)
    #
    # attach_query/detach_query are the no-stop-the-world deploy path:
    # membership changes splice into/out of the live SharedStepGroup
    # (core/shared.py) with ONE retrace and sibling queries undisturbed —
    # no drain, no rebuild of anything but the fused jit. Splice-ineligible
    # queries fall back LOUDLY to standalone dispatch (the pre-splice
    # behaviour) and the reason lands in optimizer_report["splice_declined"].

    def _all_junctions(self) -> list:
        js = list(self.junctions.values())
        js += list(self.fault_junctions.values())
        js += [w.output_junction for w in self.windows.values()
               if getattr(w, "output_junction", None) is not None]
        return js

    def attach_query(self, query, *, name: Optional[str] = None,
                     state: Optional[bytes] = None) -> dict:
        """Attach one query to the RUNNING app. `query` is SiddhiQL text
        (single query) or a parsed Query. `state` optionally seeds the new
        query's state tensors via the per-element restore primitive
        (state/persistence.py — same path upgrades migrate state through).
        Returns {"name", "deploy_ms", "fused", ...}; raises
        SiddhiAppCreationError (bad query / tenant quota) without touching
        the live plan."""
        import time as _time
        if isinstance(query, str):
            from .. import compiler
            query = compiler.parse_query(query)
        t0 = _time.perf_counter_ns()
        with self.ctx.controller_lock:
            qname = query.name or name
            if qname is None:
                i = len(self.query_runtimes) + 1
                while f"query{i}" in self.query_runtimes:
                    i += 1
                qname = f"query{i}"
            if qname in self.query_runtimes:
                raise SiddhiAppCreationError(
                    f"query {qname!r} is already attached")
            # transactional wiring: snapshot receiver lists + junction map
            # so a failed attach (bad output target, quota...) unwinds to
            # the exact pre-attach plan
            recv_snap = [(j, list(j.receivers)) for j in
                         self._all_junctions()]
            junc_snap = dict(self.junctions)
            try:
                self._add_query(query, qname)
            except BaseException:
                self.query_runtimes.pop(qname, None)
                if self.tenants is not None:
                    self.tenants.release(qname)
                self.junctions.clear()
                self.junctions.update(junc_snap)
                for j, receivers in recv_snap:
                    j.receivers[:] = receivers
                raise
            qr = self.query_runtimes[qname]
            self.app.execution_elements.append(query)
            self._cost_report = None
            if state is not None:
                self.restore(state, elements={"queries": {qname}})
            splice = self._try_splice_in(qr)
            self._plan_epoch += 1
        deploy_ms = (_time.perf_counter_ns() - t0) / 1e6
        return {"name": qname, "deploy_ms": deploy_ms, **splice}

    def detach_query(self, name: str) -> dict:
        """Detach a query from the RUNNING app: spliced out of its fused
        group (siblings keep running; the departing step body is DCE'd on
        the one retrace) or simply unsubscribed when standalone. Raises
        KeyError for an unknown query."""
        import time as _time
        t0 = _time.perf_counter_ns()
        with self.ctx.controller_lock:
            qr = self.query_runtimes[name]
            if getattr(qr, "_fused_group", None) is not None:
                self._unfuse_query(qr, keep=False)
            for j in self._all_junctions():
                j.receivers[:] = [
                    r for r in j.receivers
                    if r is not qr and getattr(r, "runtime", None) is not qr]
            self.query_runtimes.pop(name, None)
            q = qr.query
            self.app.execution_elements[:] = [
                e for e in self.app.execution_elements if e is not q]
            if self.tenants is not None:
                self.tenants.release(name)
            self._cost_report = None
            self._plan_epoch += 1
        return {"name": name,
                "detach_ms": (_time.perf_counter_ns() - t0) / 1e6}

    def _try_splice_in(self, qr) -> dict:
        """One-retrace splice of a freshly attached (or quota-recovered)
        standalone receiver into a fused group on its junction: an
        existing group with room, else a NEW group formed from the
        trailing run of spliceable standalone receivers. Never raises —
        failure/decline leaves `qr` standalone (the loud fallback) and
        returns why."""
        from ..analysis.optimizer import SPLICE_DECLINE_NO_GROUP
        from .shared import SharedStepGroup, group_cap, runtime_decline
        if self.optimizer_report is None:
            return {"fused": False}  # optimizer off: standalone by design
        junction = getattr(qr, "input_junction", None)
        reason = runtime_decline(qr)
        if reason is None and junction is None:
            reason = SPLICE_DECLINE_NO_GROUP
        group = None
        if reason is None:
            for g in self.shared_groups:
                if g.junction is not junction:
                    continue
                r = g.splice_decline(qr)
                if r is None:
                    group = g
                    break
                reason = r
        if group is not None:
            try:
                ms = group.splice_in(qr)
            except Exception as e:  # noqa: BLE001 — group rolled back
                self._splice_failed(f"splice_in {qr.name} -> "
                                    f"{group.name}: {e}")
                return {"fused": False, "failed": str(e)}
            junction.receivers[:] = [r for r in junction.receivers
                                     if r is not qr]
            self._track_splice("in", ms)
            self._refresh_optimizer_report()
            return {"fused": True, "group": group.name, "retrace_ms": ms}
        # no group with room: try forming a new one from the trailing
        # contiguous run of spliceable standalones (contiguity preserves
        # delivery order exactly, like build_shared_groups' run splice)
        if junction is not None and runtime_decline(qr) is None:
            run = []
            for r in reversed(junction.receivers):
                if (type(r) is QueryRuntime
                        and runtime_decline(r) is None
                        and getattr(r, "_fused_group", None) is None
                        and r._batch_cap == qr._batch_cap
                        and len(run) < group_cap()):
                    run.append(r)
                else:
                    break
            run.reverse()
            if len(run) >= 2:
                import time as _time
                self._splice_seq += 1
                gname = (f"shared:{junction.definition.id}:"
                         f"live{self._splice_seq}")
                t0 = _time.perf_counter_ns()
                try:
                    g = SharedStepGroup(gname, run, junction)
                    g.warmup((g._batch_cap,))
                except Exception as e:  # noqa: BLE001
                    for m in run:
                        m._fused_group = None
                    self._splice_failed(f"form {gname}: {e}")
                    return {"fused": False, "failed": str(e)}
                ms = (_time.perf_counter_ns() - t0) / 1e6
                first = run[0]
                out = []
                for r in junction.receivers:
                    if r is first:
                        out.append(g)
                    elif any(r is m for m in run):
                        continue
                    else:
                        out.append(r)
                junction.receivers[:] = out
                self.shared_groups.append(g)
                self._track_splice("in", ms)
                self._refresh_optimizer_report()
                return {"fused": True, "group": gname, "retrace_ms": ms}
            reason = reason or SPLICE_DECLINE_NO_GROUP
        self._track_splice("declined")
        rep = self.optimizer_report
        rep.setdefault("splice_declined", {})[qr.name] = reason
        return {"fused": False, "declined": reason}

    def _unfuse_query(self, qr, *, keep: bool) -> None:
        """Take `qr` out of its fused group: splice-out when the group
        survives (>2 members), else dissolve the pair back to standalone
        receivers in their original slot. keep=True re-subscribes `qr`
        standalone (the quota-divert path); keep=False drops it (detach).
        A failed splice-out falls back LOUDLY to dissolving the whole
        group — the old full-rebuild path."""
        group = qr._fused_group
        junction = group.junction
        if len(group.members) > 2:
            try:
                ms = group.splice_out(qr)
                self._track_splice("out", ms)
                if keep:
                    junction.subscribe(qr)
                self._refresh_optimizer_report()
                return
            except Exception as e:  # noqa: BLE001 — group rolled back
                self._splice_failed(f"splice_out {qr.name}: {e}")
        members = group.dissolve()
        survivors = [m for m in members if m is not qr or keep]
        out = []
        for r in junction.receivers:
            if r is group:
                out.extend(survivors)
            else:
                out.append(r)
        junction.receivers[:] = out
        self.shared_groups[:] = [g for g in self.shared_groups
                                 if g is not group]
        self._track_splice("out")
        self._refresh_optimizer_report()

    def _refresh_optimizer_report(self) -> None:
        rep = self.optimizer_report
        if rep is None:
            return
        rep["groups"] = len(self.shared_groups)
        rep["queries_fused"] = sum(len(g.members)
                                   for g in self.shared_groups)
        rep["group_members"] = {g.name: [m.name for m in g.members]
                                for g in self.shared_groups}

    def _track_splice(self, kind: str, ms: Optional[float] = None) -> None:
        self.ctx.statistics.track_splice(kind, ms)
        tele = self.ctx.telemetry
        if tele is not None:
            tele.record_splice(kind, ms)

    def _splice_failed(self, reason: str) -> None:
        import logging
        logging.getLogger("siddhi_tpu").warning(
            "splice failed, falling back to standalone dispatch: %s",
            reason)
        self._track_splice("failed")
        rec = self.ctx.recorder
        if rec is not None:
            rec.trigger("splice_failure", reason=reason)

    # -------------------------------------------------- tenant enforcement

    def _enforce_tenant_quotas(self) -> None:
        """Flush-boundary device-time quota enforcement (NEVER inside
        junction dispatch — _deliver iterates receivers directly). An
        over-budget tenant's queries are spliced out of their groups and
        force-trip quota breakers, so the junction diverts their batches
        (dead-letter path, replayable) while siblings run untouched. Once
        the rolling window drains under budget the breakers lift and the
        queries re-splice automatically."""
        tenants = self.tenants
        if tenants is None:
            return
        over = set(tenants.over_budget())
        rec = self.ctx.recorder
        for tid in tenants.ids():
            if tid in over:
                if tenants.note_breach(tid):
                    self.ctx.statistics.track_tenant_breach(tid)
                    dom = tenants.dominant_query(tid) or "?"
                    if rec is not None:
                        rec.trigger(
                            "tenant_quota_breach",
                            reason=f"tenant {tid!r} over device.ms budget "
                                   f"(dominant query {dom!r})")
                quota = tenants.quota(tid)
                from .breaker import CircuitBreaker
                for qname in tenants.queries_of(tid):
                    qr = self.query_runtimes.get(qname)
                    if qr is None:
                        continue
                    br = getattr(qr, "breaker", None)
                    if br is not None and getattr(br, "quota_tenant",
                                                  None) is None:
                        continue  # user-declared breaker: never touched
                    if getattr(qr, "_fused_group", None) is not None:
                        self._unfuse_query(qr, keep=True)
                    if br is None:
                        br = CircuitBreaker(
                            threshold=1, window_s=quota.window_s,
                            cooldown_s=quota.window_s, owner=qname)
                        br.quota_tenant = tid
                        qr.breaker = br
                    if br.state != "open":
                        br.record_failure()  # (re-)trip: divert until lift
            elif tenants.diverting(tid):
                tenants.note_recovery(tid)
                for qname in tenants.queries_of(tid):
                    qr = self.query_runtimes.get(qname)
                    if qr is None:
                        continue
                    br = getattr(qr, "breaker", None)
                    if br is not None and getattr(br, "quota_tenant",
                                                  None) == tid:
                        qr.breaker = None
                        self._try_splice_in(qr)

    # ---------------------------------------------------------------- control

    def start(self, *, connect_sources: bool = True,
              start_persist_scheduler: bool = True) -> None:
        """Start the runtime. The blue-green upgrade path starts the v2
        runtime in SHADOW (`connect_sources=False`,
        `start_persist_scheduler=False`): fully built and able to process,
        but not yet pulling from transports and not yet writing revisions —
        cutover calls connect_sources()/_start_persist_scheduler() after the
        swap commits."""
        self._started = True
        from ..telemetry.profiling import maybe_start_jax_profiler
        # SIDDHI_PROFILE=<dir>: the first runtime to start owns the
        # process-wide jax.profiler capture and closes it on shutdown
        self._owns_jax_trace = maybe_start_jax_profiler()
        if self.aot_warmup:
            self.warmup()
        if self.ctx.async_callbacks and self.ctx.decoder is None:
            from .stream import AsyncDecoder
            self.ctx.decoder = AsyncDecoder()
        for j in self.junctions.values():
            j.start_async()
        for sink in self.sinks:
            sink.connect()
        if connect_sources:
            self.connect_sources()
        if self.triggers:
            now = self.ctx.timestamp_generator.current_time()
            for tr in self.triggers.values():
                tr.start(now)
            self.flush(now)
        if self.auto_flush_ms:
            import threading
            # producers must pair their staged appends under the controller
            # lock once a flusher thread can swap the lists concurrently
            self.ctx.autoflush_active = True
            self._flusher_stop = threading.Event()
            self._flusher_thread = threading.Thread(
                target=self._flusher_loop, daemon=True,
                name=f"siddhi-flusher-{self.app.name}")
            self._flusher_thread.start()
        if start_persist_scheduler:
            self._start_persist_scheduler()
        if self.slo_engine is not None and self._slo_thread is None:
            import threading
            self._slo_stop = threading.Event()
            self._slo_thread = threading.Thread(
                target=self._slo_loop, daemon=True,
                name=f"siddhi-slo-{self.app.name}")
            self._slo_thread.start()

    def _slo_loop(self) -> None:
        """Daemon: one SLO evaluation pass per engine interval (~1 s).
        tick() samples every objective's cumulative reader, re-judges both
        burn windows, and fires the recorder on fresh breaches; a failing
        tick is logged and retried — objectives must not die with one bad
        sample."""
        import logging
        eng = self.slo_engine
        while not self._slo_stop.wait(eng.interval_s):
            if not self._started:
                return
            try:
                eng.tick()
            except Exception:  # noqa: BLE001 — evaluator must not die
                logging.getLogger("siddhi_tpu").exception(
                    "SLO evaluation tick failed (will retry next interval)")

    def diagnostics(self, reason: str = "manual") -> dict:
        """Force a diagnostic bundle now (POST /siddhi-apps/<name>/
        diagnostics). Bypasses the recorder's de-dup/rate-limit gates."""
        rec = self.ctx.recorder
        path = rec.trigger("manual", reason=reason, force=True)
        return {"bundle": path, "recorder": rec.report()}

    def connect_sources(self) -> None:
        """Connect every declared source transport (idempotent — already
        connected sources no-op in their connect paths)."""
        for source in self.sources:
            source.connect_with_retry()

    def _start_persist_scheduler(self) -> None:
        if not (self.persistence_interval_s
                and self.persistence_store is not None) \
                or self._persist_thread is not None:
            return
        import threading
        self._persist_stop = threading.Event()
        self._persist_thread = threading.Thread(
            target=self._persist_loop, daemon=True,
            name=f"siddhi-persist-{self.app.name}")
        self._persist_thread.start()

    def _persist_loop(self) -> None:
        """Daemon: bound data-at-risk to ~persistence_interval_s without the
        caller ever invoking persist() (reference: the operator-driven
        SiddhiManager.persist on a cron; here it is built in). A failed
        persist is logged and retried next tick — the WAL still covers the
        window."""
        import logging
        interval = float(self.persistence_interval_s)
        while not self._persist_stop.wait(interval):
            if not self._started:
                return
            if self._recovering:  # recover() owns the journal right now
                continue
            try:
                self.persist()
            except Exception:  # noqa: BLE001 — scheduler must not die
                logging.getLogger("siddhi_tpu").exception(
                    "periodic persist failed (will retry next interval)")

    def _flusher_loop(self) -> None:
        """Daemon: bound staged-row latency to ~auto_flush_ms without the
        caller polling flush() (the Disruptor's immediate consumption).
        Also drives heartbeats for time-semantic queries in realtime mode
        so absences/time windows fire on wall clock during idle."""
        interval = self.auto_flush_ms / 1000.0

        def _needs_hb() -> bool:
            return any(
                getattr(qr, "has_time_semantics", False)
                for qr in self.query_runtimes.values()) or any(
                w.has_time_semantics for w in self.windows.values())

        epoch = self._plan_epoch
        needs_hb = _needs_hb()
        while not self._flusher_stop.wait(interval / 2):
            if not self._started:
                return
            if self._plan_epoch != epoch:
                # attach/detach changed the plan shape: recompute whether
                # any live query still needs wall-clock heartbeats
                epoch = self._plan_epoch
                needs_hb = _needs_hb()
            try:
                # async junctions drain via their own feeder threads;
                # the flusher covers synchronous staging. The whole tick
                # runs under the controller lock: query steps donate their
                # state buffers, so a tick racing a user-thread delivery
                # into the same runtime would double-donate
                with self.ctx.controller_lock:
                    staged = any(j._staged_rows or j._tap_queue
                                 for j in self.junctions.values())
                    if staged:
                        self.flush()
                    elif needs_hb and not self.ctx.playback:
                        self.heartbeat()
            except Exception:  # noqa: BLE001 — flusher must not die
                import logging
                logging.getLogger("siddhi_tpu").exception(
                    "auto-flush tick failed")

    def warmup(self, buckets=None) -> dict:
        """AOT-compile every query runtime's jitted step for its lane-bucket
        ladder (shape-bucketed queries: min_bucket..batch_size; shape-baked
        ones: the single full capacity), so steady-state traffic — and
        benchmark measurement windows — never absorb first-compile latency.
        Each step executes once per bucket on a throwaway state copy with an
        all-invalid batch; live state is untouched. Returns
        {query_name: fresh_compile_count}; failures are logged, never
        raised (warmup is an optimization, not a correctness step)."""
        import logging
        out: dict[str, int] = {}
        with self.ctx.controller_lock:
            for name, qr in self.query_runtimes.items():
                if getattr(qr, "_fused_group", None) is not None:
                    continue  # its step never runs: the group's fused jit does
                fn = getattr(qr, "warmup", None)
                if fn is None:
                    continue
                try:
                    out[name] = fn(buckets)
                except Exception:  # noqa: BLE001 — advisory only
                    logging.getLogger("siddhi_tpu").exception(
                        "AOT warmup failed for query %r", name)
            for g in self.shared_groups:
                try:
                    out[g.name] = g.warmup(buckets)
                except Exception:  # noqa: BLE001 — advisory only
                    logging.getLogger("siddhi_tpu").exception(
                        "AOT warmup failed for shared group %r", g.name)
        return out

    def shutdown(self, *, flush_durable: bool = True,
                 drain: bool = True) -> None:
        self._started = False
        if self._persist_stop is not None:
            self._persist_stop.set()
            if self._persist_thread is not None:
                self._persist_thread.join(timeout=10)
            self._persist_stop = self._persist_thread = None
        if self._slo_stop is not None:
            self._slo_stop.set()
            if self._slo_thread is not None:
                self._slo_thread.join(timeout=5)
            self._slo_stop = self._slo_thread = None
        if self._flusher_stop is not None:
            self._flusher_stop.set()
            if self._flusher_thread is not None:
                self._flusher_thread.join(timeout=5)
            self._flusher_stop = None
            # producers pair staged appends under the controller lock only
            # while a flusher can swap the lists — post-shutdown send()s
            # must not keep taking it for a flusher that is gone
            self.ctx.autoflush_active = False
        # rows accepted by send() must not vanish silently on stop: drain
        # the pre-staging/staging buffers through the pipeline; whatever a
        # failing drain leaves behind is counted and reported, not dropped
        # on the floor unrecorded
        def _staged() -> int:
            return sum(len(j._staged_rows) + len(j._tap_queue)
                       for j in self.junctions.values())
        n0, drain_failed = _staged(), False
        if drain and n0:
            import logging
            try:
                self.drain()
            except Exception:  # noqa: BLE001 — shutdown must complete
                drain_failed = True
                logging.getLogger("siddhi_tpu").exception(
                    "draining staged rows at shutdown failed")
        remaining = _staged()
        if drain_failed:
            # flush() swaps the staged lists before delivering, so rows that
            # died mid-drain are no longer countable — report the pre-drain
            # depth as the (upper-bound) loss instead of pretending zero
            remaining = max(remaining, n0)
        if remaining:
            import logging
            self.ctx.statistics.track_shutdown_discard(remaining)
            logging.getLogger("siddhi_tpu").warning(
                "shutdown discarded %d staged row(s) (see statistics "
                "recovery.shutdown_discarded)", remaining)
        if drain and self.ctx.event_time is not None:
            # rows the event-time gates still hold are REAL accepted events:
            # deliver them (watermark jumps to max seen) rather than letting
            # shutdown silently eat the tail of every pane
            import logging
            try:
                self.release_watermarks()
            except Exception:  # noqa: BLE001 — shutdown must complete
                logging.getLogger("siddhi_tpu").exception(
                    "releasing event-time watermarks at shutdown failed")
        for j in self.junctions.values():
            j.stop_async()
        if self.ctx.decoder is not None:
            self.ctx.decoder.stop()
            self.ctx.decoder = None
        for a in self.aggregations.values():
            if flush_durable:
                a.flush_durable()  # durable duration tables (restart rebuild)
            a.close_durable()
        for t in self.tables.values():
            if hasattr(t, "shutdown"):
                t.shutdown()
        for tr in self.triggers.values():
            tr.shutdown()
        for source in self.sources:
            source.disconnect()
        for sink in self.sinks:
            sink.disconnect()
        if self.wal is not None:
            self.wal.close()
        if self._owns_jax_trace:
            from ..telemetry.profiling import stop_jax_profiler
            stop_jax_profiler()
            self._owns_jax_trace = False
        if self.ctx.recorder is not None:
            self.ctx.recorder.close()  # detach the log-tail handler

    def profile(self, n_batches: int = 32):
        """Arm a one-shot per-query device/host time split over the next
        `n_batches` query-step invocations (across all queries). Returns the
        ProfileSession; call .wait() after driving traffic, then .report()
        for {query: {batches, host_ms, device_wait_ms, device_fraction}}.

        Each profiled step pays a block_until_ready() on its post-step
        state — the device sync the steady-state pipeline avoids — which is
        why this is a bounded one-shot, not an always-on metric."""
        from ..telemetry.profiling import ProfileSession
        tele = self.ctx.telemetry
        sess = ProfileSession(tele, n_batches)
        tele.profile = sess
        return sess

    # ------------------------------------------------------------------- I/O

    def get_input_handler(self, stream_id: str) -> InputHandler:
        if stream_id not in self.input_handlers:
            junction = self.junctions.get(stream_id)
            if junction is None:
                raise DefinitionNotExistError(f"stream {stream_id!r} is not defined")
            self.input_handlers[stream_id] = InputHandler(junction)
        return self.input_handlers[stream_id]

    def add_callback(self, stream_id: str, callback,
                     columnar: bool = False) -> None:
        """Subscribe to a stream. `columnar=True` delivers ColumnarBlock
        batches (compacted numpy columns, lazy string decode) instead of
        materialized Event lists — the high-throughput form of the
        reference's Event[] callback (StreamCallback.java:38)."""
        from .stream import BatchStreamCallback, FunctionBatchCallback
        if stream_id.startswith("!"):
            junction = self.fault_junctions.get(stream_id[1:])
        else:
            junction = self.junctions.get(stream_id)
        if junction is None:
            raise DefinitionNotExistError(f"stream {stream_id!r} is not defined")
        if columnar and not isinstance(
                callback, (BatchStreamCallback, StreamCallback)):
            callback = FunctionBatchCallback(callback)
        elif not isinstance(callback, (StreamCallback, BatchStreamCallback)):
            callback = FunctionStreamCallback(callback)
        junction.subscribe(callback)

    def add_query_callback(self, query_name: str, callback) -> None:
        qr = self.query_runtimes.get(query_name)
        if qr is None:
            raise DefinitionNotExistError(f"query {query_name!r} is not defined")
        if not isinstance(callback, QueryCallback):
            callback = FunctionQueryCallback(callback)
        qr.add_callback(callback)

    def query(self, on_demand_text: str, now: Optional[int] = None):
        """Execute an on-demand (pull) query against a table (reference:
        SiddhiAppRuntimeImpl.query:309-371). Returns a list of Events."""
        from .. import compiler
        from .ondemand import OnDemandQueryRuntime

        if not hasattr(self, "_ondemand_cache"):
            self._ondemand_cache = {}
        rt = self._ondemand_cache.get(on_demand_text)
        if rt is None:
            odq = compiler.parse_on_demand_query(on_demand_text)
            from ..query_api.execution import OutputAction as _OA
            if odq.action != _OA.RETURN:
                rt = self._build_crud_runtime(odq)
                self._ondemand_cache[on_demand_text] = rt
                self.flush()
                t = (now if now is not None
                     else self.ctx.timestamp_generator.current_time())
                return rt.execute(t)
            store = self.tables.get(odq.input_store_id)
            if store is None:
                store = self.windows.get(odq.input_store_id)
            if store is None and odq.input_store_id in self.aggregations:
                # aggregation store query: bind `per`/`within` into a view
                # (reference: AggregationRuntime.find, within/per clauses)
                import dataclasses as dc
                agg = self.aggregations[odq.input_store_id]
                if odq.per is None:
                    raise SiddhiAppCreationError(
                        f"aggregation {odq.input_store_id!r} queries need "
                        "`per '<duration>'`")
                store = agg.view(odq.per, odq.within_range)
                odq = dc.replace(odq, per=None, within_range=None)
            if store is None:
                raise DefinitionNotExistError(
                    f"store {odq.input_store_id!r} is not defined")
            rt = OnDemandQueryRuntime(odq, store, self.ctx, self.ctx.registry)
            self._ondemand_cache[on_demand_text] = rt
        self.flush()
        t = now if now is not None else self.ctx.timestamp_generator.current_time()
        return rt.execute(t)

    def _build_crud_runtime(self, odq):
        """Write-form on-demand queries (delete/update/update-or-insert/
        select-insert) — reference: OnDemandQueryParser non-find runtimes."""
        from ..io.record_table import RecordCrudRuntime, RecordTableRuntime
        from ..query_api.execution import OutputAction as _OA
        from .ondemand import OnDemandCrudRuntime
        target = self.tables.get(odq.target_id)
        if target is None:
            raise DefinitionNotExistError(
                f"table {odq.target_id!r} is not defined")
        if isinstance(target, RecordTableRuntime):
            source = None
            if odq.action == _OA.INSERT and odq.input_store_id is not None:
                source = self.tables.get(odq.input_store_id)
                if source is None:
                    source = self.windows.get(odq.input_store_id)
                if source is None:
                    raise DefinitionNotExistError(
                        f"store {odq.input_store_id!r} is not defined")
            return RecordCrudRuntime(odq, target, self.ctx,
                                     self.ctx.registry, source_store=source)
        source = None
        if odq.action == _OA.INSERT and odq.input_store_id is not None:
            source = self.tables.get(odq.input_store_id)
            if source is None:  # NOT `or`: an empty table is falsy (__len__)
                source = self.windows.get(odq.input_store_id)
            if source is None and odq.input_store_id in self.aggregations:
                raise SiddhiAppCreationError(
                    "insert-into from aggregations: query the aggregation "
                    "and insert host-side instead")
            if source is None:
                raise DefinitionNotExistError(
                    f"store {odq.input_store_id!r} is not defined")
        return OnDemandCrudRuntime(odq, target, self.ctx, self.ctx.registry,
                                   source_store=source)

    def flush(self, now: Optional[int] = None) -> None:
        """Drive every staged batch through the pipeline (source junctions
        first; device-to-device chaining cascades synchronously)."""
        if self.triggers:
            t = now if now is not None else self.ctx.timestamp_generator.current_time()
            for tr in self.triggers.values():
                tr.poll(t)
        for j in list(self.junctions.values()):
            j.flush(now)
        # tenant device-time quotas enforce at this boundary — never inside
        # junction dispatch, where receiver lists must not be mutated
        self._enforce_tenant_quotas()

    def drain(self) -> None:
        """Flush staged rows AND block until every async callback has fired.
        The barrier for async_callbacks=True mode (with synchronous
        callbacks this is equivalent to flush())."""
        self.flush()
        if self.ctx.decoder is not None:
            self.ctx.decoder.drain()

    def release_watermarks(self, now: Optional[int] = None) -> None:
        """End-of-stream drain for @app:eventTime: force every gate's
        watermark to its max seen event time and deliver the held rows in
        event-time order. Stragglers sent afterwards classify as late
        (replayable), never as out-of-order emissions."""
        for j in self.junctions.values():
            if j._et is not None:
                j.release_event_time(now)
        self.flush(now)

    def heartbeat(self, now: Optional[int] = None) -> None:
        """Advance watermarks: flush + deliver empty timer batches to queries
        with time-driven windows (the reference Scheduler's TIMER events).
        In playback mode a bare heartbeat() bumps the virtual clock by the
        @app:playback increment (idle-time heartbeat,
        TimestampGeneratorImpl.java:92-131)."""
        tg = self.ctx.timestamp_generator
        if now is None and tg.playback and tg.playback_increment_ms:
            t = tg.advance_idle()
        else:
            t = now if now is not None else tg.current_time()
        self.flush(t)
        for w in self.windows.values():
            if w.has_time_semantics:
                w.heartbeat(t)
        for a in self.aggregations.values():
            a._maybe_evict(t)  # retention purge rides the heartbeat clock
        for pr in self.partitions.values():
            if pr.has_time_semantics or pr._purge_idle_ms is not None:
                pr.heartbeat(t)
        seen: set[int] = set()
        for qr in self.query_runtimes.values():
            if not qr.has_time_semantics or getattr(qr, "_partitioned", False):
                continue
            if hasattr(qr, "heartbeat"):  # pattern runtimes drive themselves
                qr.heartbeat(t)
                continue
            j = getattr(qr, "input_junction", None)
            if j is not None and id(j) not in seen:
                seen.add(id(j))
                j.heartbeat(t)
        for j in self.junctions.values():
            # event-time gates ride the heartbeat too (idle.timeout release)
            # even when no consumer has time semantics
            if j._et is not None and id(j) not in seen:
                seen.add(id(j))
                j.heartbeat(t)
        # overflow counters warn from the heartbeat too, not only when the
        # user polls statistics_report()
        self.collect_overflow()

    # ----------------------------------------------------- persist / restore

    @property
    def persistence_store(self):
        return getattr(self, "_persistence_store", None)

    @persistence_store.setter
    def persistence_store(self, store) -> None:
        self._persistence_store = store

    def _snapshot_service(self):
        from ..state.persistence import SnapshotService
        if not hasattr(self, "_snap_service"):
            self._snap_service = SnapshotService(self)
        return self._snap_service

    def snapshot(self) -> bytes:
        """Full state snapshot as bytes (reference:
        SiddhiAppRuntimeImpl.snapshot)."""
        return self._snapshot_service().full_snapshot()

    def restore(self, snapshot: bytes, *, elements=None) -> None:
        """Restore a snapshot. `elements` (section -> element-name set)
        limits the restore to the migratable subset during a state-mapped
        upgrade (state/persistence.py SnapshotService.restore)."""
        self._snapshot_service().restore(snapshot, elements=elements)

    def persist(self) -> str:
        """Snapshot to the configured PersistenceStore; returns the revision
        (reference: SiddhiAppRuntimeImpl.persist:686)."""
        from ..errors import NoPersistenceStoreError
        store = self.persistence_store
        if store is None:
            raise NoPersistenceStoreError(
                "no persistence store configured "
                "(set manager.persistence_store)")
        import time as _time
        ms = int(_time.time() * 1000)
        # strictly increasing: two persists in one millisecond must not
        # collide (delta persistence chains rely on revision uniqueness/order)
        last = getattr(self, "_last_rev_ms", 0)
        ms = max(ms, last + 1)
        self._last_rev_ms = ms
        revision = f"{ms}_{self.app.name}"
        # snapshot→save→rotate is ONE critical section under the controller
        # lock (the reference's world-stopping ThreadBarrier): WAL-journaled
        # sends take the same lock, so every journaled row is either flushed
        # into this snapshot (its record is safely rotated away) or staged
        # after the rotation (its record lands in the new segment) — never
        # journaled-then-lost in between
        with self.ctx.controller_lock:
            store.save(self.app.name, revision, self.snapshot())
            for a in self.aggregations.values():
                a.flush_durable()  # write-through durable duration tables
            if self.wal is not None:
                # rotate AFTER the store accepted the snapshot
                # (save-then-rotate: a crash between the two duplicates the
                # suffix on recover, never loses it)
                self.wal.rotate(revision)
        return revision

    def restore_revision(self, revision: str) -> None:
        from ..errors import CannotRestoreStateError, NoPersistenceStoreError
        store = self.persistence_store
        if store is None:
            raise NoPersistenceStoreError("no persistence store configured")
        blob = store.load(self.app.name, revision)
        if blob is None:
            raise CannotRestoreStateError(f"revision {revision!r} not found")
        self.restore(blob)

    def restore_last_revision(self) -> Optional[str]:
        """Reference: SiddhiAppRuntimeImpl.restoreLastRevision."""
        store = self.persistence_store
        if store is None:
            from ..errors import NoPersistenceStoreError
            raise NoPersistenceStoreError("no persistence store configured")
        rev = store.get_last_revision(self.app.name)
        if rev is not None:
            self.restore_revision(rev)
        return rev

    def recover(self) -> dict:
        """Crash recovery: restore the last persisted revision (when a
        persistence store is configured) then replay the write-ahead journal
        with the events' original timestamps — at-least-once restart
        semantics. Safe on a clean state too (no revision, empty WAL = a
        no-op). Returns {"revision", "wal_replayed"}; counts surface in
        statistics_report()["recovery"]."""
        rev = None
        self._recovering = True  # the periodic persist scheduler stands down
        try:
            if self.persistence_store is not None:
                rev = self.restore_last_revision()
            replayed = 0
            if self.wal is not None:
                replayed = self.wal.replay(self)
            self.flush()
        finally:
            self._recovering = False
        self.ctx.statistics.track_recovery(replayed)
        if self.ctx.recorder is not None:
            # recovery is an anomaly worth evidence: capture the post-replay
            # state (WAL position, replayed count, stats) for later triage
            self.ctx.recorder.trigger(
                "recovery", reason=f"revision={rev} wal_replayed={replayed}")
        return {"revision": rev, "wal_replayed": replayed}

    # ------------------------------------------------------------------ health

    def health(self) -> dict:
        """Readiness view of one app (served by `/ready` in service.py):
        overall state (running | degraded | recovering | stopped — degraded
        = at least one circuit breaker not closed), per-query breaker
        snapshots, and staged-queue depth vs. capacity for every bounded
        junction (with its backpressure-paused flag)."""
        breakers = {}
        degraded = False
        for name, qr in self.query_runtimes.items():
            br = getattr(qr, "breaker", None)
            if br is None:
                continue
            breakers[name] = br.snapshot()
            if br.state != "closed":
                degraded = True
        queues = {}
        for sid, j in self.junctions.items():
            if j.capacity is None:
                continue
            depth = j._staged_depth()
            queues[sid] = {"depth": depth, "capacity": j.capacity,
                           "paused": j._bp_paused}
        if self._recovering:
            state = "recovering"
        elif not self._started:
            state = "stopped"
        elif degraded:
            state = "degraded"
        else:
            state = "running"
        return {"state": state, "breakers": breakers, "queues": queues}

    # -------------------------------------------------------------- statistics

    @property
    def statistics(self) -> Statistics:
        return self.ctx.statistics

    def set_statistics_level(self, level: str) -> None:
        """Runtime-switchable metric level (reference:
        SiddhiAppRuntimeImpl.setStatisticsLevel:868)."""
        self.ctx.statistics.set_level(level)

    def statistics_report(self) -> dict:
        return self.ctx.statistics.report(runtime=self)

    @property
    def cost_report(self) -> dict:
        """Static cost prediction for this app (analysis/cost.py), computed
        lazily under the runtime's effective batch/group capacities and
        cached — statistics_report()['cost'] pairs it with live telemetry."""
        rep = getattr(self, "_cost_report", None)
        if rep is None:
            from ..analysis.cost import compute_cost
            rep = compute_cost(self.app,
                               batch_size=self.ctx.batch_size,
                               group_capacity=self.ctx.group_capacity
                               ).to_dict()
            self._cost_report = rep
        return rep

    def collect_overflow(self) -> None:
        """Sweep every runtime's device state for capacity-overflow counters
        and surface them via Statistics.record_overflow (one-shot warning
        per counter). Syncs a handful of scalars — called from
        statistics_report() and the heartbeat, not the hot path.

        Counters: window-ring overwrites of live rows (SlidingState /
        expression windows), key-table unresolved lanes (group-by, distinct
        pairs, aggregation buckets), pattern pending-table drops, keyed
        session key-capacity drops, join pair-block/candidate-walk drops."""
        import numpy as np

        from ..ops.aggregators import HLLState
        from ..ops.groupby import KeyTable
        from ..ops.ratelimit import WindowedSnapshotState
        from ..ops.windows import SlidingState
        from ..ops.windows_extra import KeyedSessionState
        from .join_runtime import JoinQueryRuntime
        from .pattern_runtime import PatternState

        stats = self.ctx.statistics

        def scan(label: str, obj, acc: dict) -> None:
            # accumulate DEVICE scalars; the single device_get below fetches
            # everything in one round trip (a per-counter np.asarray costs a
            # full tunnel sync EACH — see event.to_host_events)
            def add(key, arr):
                acc.setdefault(key, []).append(arr)

            if isinstance(obj, KeyTable):
                add("key_table_unresolved", obj.misses)
            elif isinstance(obj, SlidingState):
                add("window_ring_overflow", obj.overflow)
            elif isinstance(obj, KeyedSessionState):
                add("session_key_dropped", obj.dropped)
            elif isinstance(obj, PatternState):
                add("pattern_pending_dropped", obj.dropped)
            elif isinstance(obj, WindowedSnapshotState):
                add("snapshot_ring_overflow", obj.overflow)
            elif isinstance(obj, HLLState):
                add("hll_groups_dropped", obj.dropped)
            import dataclasses as _dc
            if isinstance(obj, dict):
                for v in obj.values():
                    scan(label, v, acc)
            elif hasattr(obj, "_fields"):  # NamedTuple: recurse into fields
                for f in obj._fields:
                    scan(label, getattr(obj, f), acc)
            elif isinstance(obj, (tuple, list)):
                for v in obj:
                    scan(label, v, acc)
            elif _dc.is_dataclass(obj) and not isinstance(obj, type):
                for f in _dc.fields(obj):  # e.g. SelectorState
                    scan(label, getattr(obj, f.name), acc)

        sources: list[tuple[str, object]] = []
        sources += [(f"query:{n}", qr.state)
                    for n, qr in self.query_runtimes.items()
                    if hasattr(qr, "state")]
        sources += [(f"window:{n}", w.state) for n, w in self.windows.items()]
        sources += [(f"aggregation:{n}", a.state)
                    for n, a in self.aggregations.items()]
        pending: dict[str, list] = {}
        for label, obj in sources:
            acc: dict = {}
            scan(label, obj, acc)
            for k, arrs in acc.items():
                pending[f"{label}.{k}"] = arrs
        for n, qr in self.query_runtimes.items():
            if isinstance(qr, JoinQueryRuntime) and qr._dropped_dev is not None:
                pending[f"query:{n}.join_pairs_dropped"] = [qr._dropped_dev]
        import jax
        fetched = jax.device_get(pending)  # ONE device->host round trip
        for name, arrs in fetched.items():
            stats.record_overflow(name, int(sum(np.sum(a) for a in arrs)))

    # ---------------------------------------------------------------- debugger

    def debug(self):
        """Attach a debugger (reference: SiddhiAppRuntimeImpl.debug():666 →
        core/debugger/SiddhiDebugger.java:36)."""
        from .debugger import SiddhiDebugger
        if getattr(self.ctx, "debugger", None) is None:
            self.ctx.debugger = SiddhiDebugger(self)
        if not self._started:
            self.start()
        return self.ctx.debugger


class _TableJunctionAdapter:
    """Adapts the query-output junction interface onto a table insert."""

    def __init__(self, table) -> None:
        self.table = table

    def publish_batch(self, batch, now) -> None:
        self.table.insert_batch(batch)
