"""Per-query circuit breakers — overload protection for the execution plane.

Reference analogue: the stream-level OnErrorAction (StreamJunction.java:371-463)
decides what happens to a FAILED event; a breaker decides whether a repeatedly
failing query step should keep receiving events at all. A query whose step
throws `threshold` times within `window` trips OPEN: its input batches are
diverted (fault stream / ErrorStore) instead of executed, so one poisoned
query cannot take sibling queries — or the whole app — down with it. After
`cooldown` the breaker goes HALF_OPEN and admits one probe batch; a probe
success closes the breaker, a probe failure re-opens it.

Configured per query:

    @breaker(threshold='5', window='60 sec', cooldown='30 sec')
    from S select ... insert into Out;

State transitions and divert counts surface in statistics_report()["breakers"]
and in SiddhiAppRuntime.health() (an OPEN breaker marks the app "degraded",
which /ready reports as 503).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: transition history kept per breaker (ops forensics, bounded)
_MAX_TRANSITIONS = 64


class CircuitBreaker:
    """Failure-rate gate for one query runtime. Single-controller discipline:
    allow()/record_* are called under the junction's controller lock, so no
    internal locking is needed and the HALF_OPEN probe is naturally serial."""

    def __init__(self, *, threshold: int = 5, window_s: float = 60.0,
                 cooldown_s: float = 30.0, owner: str = "",
                 clock: Callable[[], float] = time.monotonic) -> None:
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.owner = owner
        #: swap for a virtual clock in tests (all time reads go through it)
        self.clock = clock
        self.state = CLOSED
        self.opens = 0
        self.closes = 0
        #: (state, at) pairs, newest last, bounded
        self.transitions: deque = deque(maxlen=_MAX_TRANSITIONS)
        self._failures: deque = deque()  # failure instants inside the window
        self._opened_at: float = 0.0

    def _transition(self, state: str) -> None:
        self.state = state
        self.transitions.append((state, self.clock()))

    def allow(self) -> bool:
        """May the next batch be dispatched? OPEN past its cooldown admits
        exactly one probe (HALF_OPEN); the probe's record_success/
        record_failure decides what happens next."""
        if self.state == OPEN:
            if self.clock() - self._opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN)
                return True
            return False
        return True

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            self._transition(CLOSED)
            self.closes += 1
            self._failures.clear()

    def record_failure(self) -> bool:
        """Count one step failure. Returns True when THIS failure tripped the
        breaker OPEN (callers use it to count opens exactly once)."""
        now = self.clock()
        if self.state == HALF_OPEN:  # failed probe: straight back to OPEN
            self._opened_at = now
            self._transition(OPEN)
            self.opens += 1
            return True
        self._failures.append(now)
        while self._failures and now - self._failures[0] > self.window_s:
            self._failures.popleft()
        if self.state == CLOSED and len(self._failures) >= self.threshold:
            self._opened_at = now
            self._transition(OPEN)
            self.opens += 1
            self._failures.clear()
            return True
        return False

    def snapshot(self) -> dict:
        """Health/statistics view (JSON-safe)."""
        return {
            "state": self.state,
            "opens": self.opens,
            "closes": self.closes,
            "failures_in_window": len(self._failures),
            "threshold": self.threshold,
        }


def breaker_from_annotations(query, name: str = "",
                             clock: Callable[[], float] = time.monotonic,
                             ) -> Optional[CircuitBreaker]:
    """Build a CircuitBreaker from a query's `@breaker(...)` annotation, or
    None when the query carries none. Elements: threshold (count), window /
    cooldown (time literals like '10 sec')."""
    ann = next((a for a in (query.annotations or ())
                if a.name.lower() == "breaker"), None)
    if ann is None:
        return None
    from ..errors import SiddhiAppCreationError
    from .partition import _parse_annotation_time
    try:
        threshold = int(ann.element("threshold") or 5)
        window = ann.element("window")
        cooldown = ann.element("cooldown")
        return CircuitBreaker(
            threshold=threshold,
            window_s=(_parse_annotation_time(window) / 1000.0
                      if window else 60.0),
            cooldown_s=(_parse_annotation_time(cooldown) / 1000.0
                        if cooldown else 30.0),
            owner=name, clock=clock)
    except ValueError as e:
        raise SiddhiAppCreationError(f"bad @breaker annotation: {e}") from e
