"""Script-defined functions — `define function f[lang] return type { body }`.

Reference: core/function/Script.java (init/eval SPI),
ScriptFunctionExecutor.java:33, ScriptExtensionHolder — script engines (JS
etc.) plug in as extensions keyed by language name.

TPU build: the first-class language is `python` (alias `jax`) — the body is
compiled once into a traced, batch-vectorized callable over `args` (the list
of argument ARRAYS) with `jnp`/`np` in scope, so a script function fuses into
the same XLA program as the rest of the query instead of dropping to a
per-event interpreter the way the reference's JS scripts do. Other languages
register engines under ExtensionKind.SCRIPT."""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from ..ops.expr_compile import ScalarFunction
from ..query_api.definition import FunctionDefinition
from . import dtypes


class ScriptEngine:
    """SPI: compile a FunctionDefinition into a ScalarFunction
    (reference: core/function/Script.java init/eval)."""

    def compile(self, fd: FunctionDefinition) -> ScalarFunction:
        raise NotImplementedError


class PythonScriptEngine(ScriptEngine):
    """Bodies are Python over `args` (argument arrays) with jnp/np in scope.

    Expression form:   define function sq[python] return double { args[0] ** 2 }
    Statement form:    ... { x = args[0] * 2\n return x + 1 }  (must `return`)
    Everything must stay traceable (vectorized jnp ops, no data-dependent
    Python control flow) — it runs inside the query's jitted step."""

    def compile(self, fd: FunctionDefinition) -> ScalarFunction:
        body = fd.body.strip()
        scope = {"jnp": jnp, "np": np, "__builtins__": __builtins__}
        try:
            code = compile(body, f"<function {fd.id}>", "eval")

            def raw(*args):
                return eval(code, scope, {"args": list(args)})  # noqa: S307
        except SyntaxError:
            import textwrap

            # the app text embeds the body at arbitrary indentation: dedent
            # continuation lines by their common prefix before re-indenting
            lines = body.splitlines()
            tail = textwrap.dedent("\n".join(lines[1:])) if len(lines) > 1 else ""
            norm = lines[0].strip() + ("\n" + tail if tail else "")
            src = f"def __script__(args):\n{textwrap.indent(norm, '    ')}"
            try:
                exec(compile(src, f"<function {fd.id}>", "exec"), scope)  # noqa: S102
            except SyntaxError as e:
                raise SiddhiAppCreationError(
                    f"function {fd.id!r}: cannot compile body: {e}") from e
            fn = scope["__script__"]

            def raw(*args):
                return fn(list(args))

        ret_dtype = dtypes.device_dtype(fd.return_type)
        ret_t = fd.return_type

        def make(arg_types):
            def call(*args):
                out = raw(*args)
                if out is None:
                    raise SiddhiAppCreationError(
                        f"function {fd.id!r} returned nothing (missing return?)")
                return jnp.asarray(out).astype(ret_dtype)

            return call, ret_t

        return ScalarFunction(make=make)


def register_all() -> None:
    engine = PythonScriptEngine()
    GLOBAL.register(ExtensionKind.SCRIPT, "", "python", engine)
    GLOBAL.register(ExtensionKind.SCRIPT, "", "jax", engine)


register_all()


def bind_app_functions(app, registry) -> None:
    """Compile every `define function` and register it as a scalar function
    in the app's registry (reference: SiddhiAppParser → ScriptExtensionHolder
    wiring). Call with an app-scoped registry copy."""
    for fd in app.function_definitions.values():
        engine = registry.lookup(ExtensionKind.SCRIPT, "", fd.language)
        if engine is None:
            raise SiddhiAppCreationError(
                f"function {fd.id!r}: no script engine for language "
                f"{fd.language!r} (available: python/jax; register engines "
                "via ExtensionKind.SCRIPT)")
        registry.register(ExtensionKind.FUNCTION, "", fd.id, engine.compile(fd))
