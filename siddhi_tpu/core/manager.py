"""SiddhiManager — top-level factory (reference: core/SiddhiManager.java:50)."""

from __future__ import annotations

from typing import Optional, Union

from .. import compiler
from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind, Registry
from ..query_api import SiddhiApp
from .app_runtime import SiddhiAppRuntime

# built-in extension registration side effects
from ..ops import aggregators as _aggregators  # noqa: F401
from ..ops import builtin_functions as _builtin_functions  # noqa: F401
from ..ops import window_factories as _window_factories  # noqa: F401


def sandbox_app(app: SiddhiApp) -> SiddhiApp:
    """A copy of `app` with every @source/@sink/@store/@cache annotation
    stripped so a runtime built from it is fully in-memory (no transports,
    no external stores). Used by sandbox mode AND the historical-replay
    harness, which must never let a candidate app publish to production
    sinks while replaying recorded traffic."""
    import dataclasses as dc
    drop = {"source", "sink", "store", "cache"}

    def strip(defn):
        anns = tuple(a for a in (defn.annotations or ())
                     if a.name.lower() not in drop)
        return dc.replace(defn, annotations=anns)

    return dc.replace(
        app,
        stream_definitions={k: strip(v) for k, v
                            in app.stream_definitions.items()},
        table_definitions={k: strip(v) for k, v
                           in app.table_definitions.items()},
        aggregation_definitions={k: strip(v) for k, v
                                 in app.aggregation_definitions.items()},
    )


class SiddhiManager:
    def __init__(self) -> None:
        self.registry = GLOBAL.copy()
        self.runtimes: dict[str, SiddhiAppRuntime] = {}
        self._env_overrides: dict[str, str] = {}
        #: shared store for all apps (reference:
        #: SiddhiManager.setPersistenceStore)
        self.persistence_store = None
        #: shared error store (reference: SiddhiManager.setErrorStore)
        self.error_store = None
        #: deployment config (reference: SiddhiManager.setConfigManager)
        self.config_manager = None
        #: internal: the jaxpr lint pass builds sandbox runtimes through a
        #: private manager and must not re-enter the lint gate
        self._lint_enabled = True
        #: apps deferred by SIDDHI_BUDGET_MODE=queue admission control:
        #: [(SiddhiApp, create kwargs)], FIFO; drain with admit_pending()
        self.pending_apps: list[tuple[SiddhiApp, dict]] = []

    @staticmethod
    def _parse(app: Union[str, SiddhiApp]) -> SiddhiApp:
        if isinstance(app, str):
            text = compiler.update_variables(app) if "${" in app else app
            app = compiler.parse(text)
        return app

    def create_siddhi_app_runtime(
        self, app: Union[str, SiddhiApp], *,
        batch_size: int = 0, group_capacity: int = 0,
        mesh=None, partition_capacity: int = 0,
        async_callbacks: bool = False,
        auto_flush_ms=None, aot_warmup: bool = False,
        wal_dir=None, persistence_interval_s=None,
        optimize=None,
    ) -> Optional[SiddhiAppRuntime]:
        app = self._parse(app)
        lint_report = self._lint_gate(app)
        kwargs = dict(batch_size=batch_size, group_capacity=group_capacity,
                      mesh=mesh, partition_capacity=partition_capacity,
                      async_callbacks=async_callbacks,
                      auto_flush_ms=auto_flush_ms, aot_warmup=aot_warmup,
                      wal_dir=wal_dir,
                      persistence_interval_s=persistence_interval_s,
                      optimize=optimize)
        if self._budget_gate(app, batch_size=batch_size,
                             group_capacity=group_capacity):
            # queue mode: defer — no device state has been allocated
            self.pending_apps.append((app, kwargs))
            return None
        if self._lint_enabled:
            # @app:shards (n overridable via SIDDHI_SHARDS) builds a sharded
            # execution plane — N replicas behind a partition-key router —
            # in place of a single runtime. Internal analysis managers
            # (_lint_enabled=False: sandbox/jaxpr builds) never construct
            # planes: replicas are plain runtimes with the annotation
            # stripped, so recursion terminates there too.
            from ..analysis.sharding import shard_config
            cfg = shard_config(app, strict=True)
            if cfg is not None:
                from ..parallel.shard_plane import ShardPlane
                plane = ShardPlane(
                    app, self.registry, config=cfg,
                    batch_size=batch_size, group_capacity=group_capacity,
                    error_store=self.error_store,
                    config_manager=self.config_manager,
                    mesh=mesh, partition_capacity=partition_capacity,
                    async_callbacks=async_callbacks,
                    auto_flush_ms=auto_flush_ms, aot_warmup=aot_warmup,
                    wal_dir=wal_dir,
                    persistence_interval_s=persistence_interval_s,
                    optimize=optimize)
                if self.persistence_store is not None:
                    plane.persistence_store = self.persistence_store
                plane.lint_report = lint_report
                self.runtimes[app.name] = plane
                return plane
        rt = SiddhiAppRuntime(app, self.registry, batch_size=batch_size,
                              group_capacity=group_capacity,
                              error_store=self.error_store,
                              config_manager=self.config_manager,
                              mesh=mesh, partition_capacity=partition_capacity,
                              async_callbacks=async_callbacks,
                              auto_flush_ms=auto_flush_ms,
                              aot_warmup=aot_warmup,
                              wal_dir=wal_dir,
                              persistence_interval_s=persistence_interval_s,
                              optimize=optimize)
        if self.persistence_store is not None:
            rt.persistence_store = self.persistence_store
        rt.lint_report = lint_report
        self.runtimes[app.name] = rt
        return rt

    def _budget_gate(self, app: SiddhiApp, *, batch_size: int,
                     group_capacity: int) -> bool:
        """Admission control (SL501): price the app with the static cost
        model BEFORE any device state is allocated. With a budget configured
        (@app:budget / SIDDHI_STATE_BUDGET / SIDDHI_COMPILE_BUDGET), an
        over-budget app is refused (SIDDHI_BUDGET_MODE=error, default) or
        deferred to `pending_apps` (returns True; SIDDHI_BUDGET_MODE=queue).
        An env-level state budget is manager-wide: already-admitted apps'
        predictions count against it. The gate itself never crashes app
        creation — a cost-model failure admits the app unpriced."""
        import os

        from ..analysis.cost import app_budget, compute_cost, format_size

        if not self._lint_enabled:
            return False  # internal analysis manager (sandbox/jaxpr builds)
        budget = app_budget(app)
        if budget is None:
            return False
        try:
            rep = compute_cost(app, batch_size=batch_size,
                               group_capacity=group_capacity)
        except Exception:
            import logging
            logging.getLogger("siddhi_tpu.lint").debug(
                "cost model crashed; app %r admitted unpriced",
                app.name, exc_info=True)
            return False
        over: list[str] = []
        if budget.state_bytes is not None:
            demand = rep.state_bytes
            fleet = 0
            if os.environ.get("SIDDHI_STATE_BUDGET", "").strip():
                for other in self.runtimes.values():
                    try:
                        fleet += int(other.cost_report.get(
                            "predicted_state_bytes", 0))
                    except Exception:
                        pass
            if demand + fleet > budget.state_bytes:
                held = (f" ({format_size(fleet)} already held by "
                        f"{len(self.runtimes)} admitted app(s))"
                        if fleet else "")
                over.append(
                    f"predicted device state {format_size(demand)}{held} "
                    f"exceeds the budget {format_size(budget.state_bytes)} "
                    f"({budget.source})")
        if budget.compiles is not None and rep.compile_ladder > budget.compiles:
            over.append(
                f"predicted compile ladder {rep.compile_ladder} exceeds the "
                f"compile budget {budget.compiles} ({budget.source})")
        if not over:
            return False
        if budget.mode == "queue":
            import logging
            logging.getLogger("siddhi_tpu.lint").warning(
                "SL501: app %r deferred (SIDDHI_BUDGET_MODE=queue): %s",
                app.name, "; ".join(over))
            return True
        raise SiddhiAppCreationError(
            f"SL501: app {app.name!r} refused by admission control: "
            + "; ".join(over)
            + ". Shrink capacities, raise the budget, or set "
            "SIDDHI_BUDGET_MODE=queue to defer (docs/COST.md).")

    def attach_query(self, app_name: str, query, *,
                     name: Optional[str] = None,
                     state: Optional[bytes] = None) -> dict:
        """Attach one query to a RUNNING app (one-retrace splice; see
        SiddhiAppRuntime.attach_query). The splice is priced incrementally
        (analysis/cost.py price_splice) and SL501 is enforced PER SPLICE:
        an over-budget attach raises before any device state exists —
        splices never queue (there is no deferred half-deployed query).
        Raises KeyError for an unknown app."""
        rt = self.runtimes[app_name]
        if getattr(rt, "is_shard_plane", False):
            raise SiddhiAppCreationError(
                f"cannot splice into sharded app {app_name!r}: redeploy "
                "the plane (docs/SHARDING.md)")
        if isinstance(query, str):
            text = (compiler.update_variables(query) if "${" in query
                    else query)
            query = compiler.parse_query(text)
        self._splice_budget_gate(rt, query)
        return rt.attach_query(query, name=name, state=state)

    def detach_query(self, app_name: str, query_name: str) -> dict:
        """Detach a query from a RUNNING app (splice-out, siblings keep
        running), then retry the pending-app queue: the freed budget is
        visible immediately because the runtime's cost report re-prices
        against the post-splice plan. Raises KeyError for an unknown app
        or query."""
        rt = self.runtimes[app_name]
        out = rt.detach_query(query_name)
        admitted = self.admit_pending()
        if admitted:
            out["admitted_pending"] = [a.app.name for a in admitted]
        return out

    def _splice_budget_gate(self, rt, query) -> None:
        """Per-splice SL501: price the app WITH the query attached (delta
        + post totals) against the budget, counting the rest of the fleet
        exactly like _budget_gate. Never queues — an over-budget splice
        raises. A cost-model crash admits the splice unpriced."""
        import os

        from ..analysis.cost import app_budget, format_size, price_splice

        if not self._lint_enabled:
            return
        budget = app_budget(rt.app)
        if budget is None:
            return
        try:
            delta = price_splice(rt.app, query,
                                 batch_size=rt.ctx.batch_size,
                                 group_capacity=rt.ctx.group_capacity)
        except Exception:
            import logging
            logging.getLogger("siddhi_tpu.lint").debug(
                "cost model crashed; splice into %r admitted unpriced",
                rt.app.name, exc_info=True)
            return
        over: list[str] = []
        if budget.state_bytes is not None:
            demand = delta["post_state_bytes"]
            fleet = 0
            if os.environ.get("SIDDHI_STATE_BUDGET", "").strip():
                for other in self.runtimes.values():
                    if other is rt:
                        continue
                    try:
                        fleet += int(other.cost_report.get(
                            "predicted_state_bytes", 0))
                    except Exception:
                        pass
            if demand + fleet > budget.state_bytes:
                over.append(
                    f"post-splice device state {format_size(demand)} "
                    f"(splice adds "
                    f"{format_size(max(delta['delta_state_bytes'], 0))}) "
                    f"exceeds the budget {format_size(budget.state_bytes)} "
                    f"({budget.source})")
        if (budget.compiles is not None
                and delta["post_compiles"] > budget.compiles):
            over.append(
                f"post-splice compile ladder {delta['post_compiles']} "
                f"exceeds the compile budget {budget.compiles} "
                f"({budget.source})")
        if over:
            raise SiddhiAppCreationError(
                f"SL501: splice into app {rt.app.name!r} refused by "
                "admission control: " + "; ".join(over)
                + ". Detach queries or raise the budget (docs/COST.md).")

    def admit_pending(self) -> list[SiddhiAppRuntime]:
        """Retry every queued app FIFO (after budget headroom freed up —
        e.g. a runtime shut down, a query was DETACHED (the fleet sum
        re-prices against each runtime's post-splice plan), or the budget
        was raised). Apps that still exceed the budget stay queued;
        admitted ones are returned. detach_query() calls this
        automatically."""
        admitted: list[SiddhiAppRuntime] = []
        still_pending: list[tuple[SiddhiApp, dict]] = []
        pending, self.pending_apps = self.pending_apps, []
        for app, kwargs in pending:
            rt = self.create_siddhi_app_runtime(app, **kwargs)
            if rt is None:
                # create re-queued it onto self.pending_apps; keep order
                still_pending.extend(self.pending_apps)
                self.pending_apps = []
            else:
                admitted.append(rt)
        self.pending_apps = still_pending
        return admitted

    def _lint_gate(self, app: SiddhiApp):
        """Run the static linter per SIDDHI_LINT (error|warn|off, default
        warn): `error` refuses apps with ERROR findings before any device
        state is planned; `warn` logs and attaches the report; `off` skips.
        The linter itself never raises — a crash in analysis is logged and
        treated as `off` for this app."""
        from ..analysis import analyze, lint_mode

        mode = lint_mode()
        if mode == "off" or not self._lint_enabled:
            return None
        try:
            report = analyze(app)
        except Exception:
            import logging
            logging.getLogger("siddhi_tpu.lint").debug(
                "lint pass crashed; app %r proceeds unlinted",
                app.name, exc_info=True)
            return None
        if report.has_errors and mode == "error":
            raise SiddhiAppCreationError(
                f"SIDDHI_LINT=error: app {app.name!r} has "
                f"{len(report.errors)} lint error(s):\n" +
                "\n".join(d.format() for d in report.sorted()))
        if report.diagnostics:
            import logging
            log = logging.getLogger("siddhi_tpu.lint")
            for d in report.sorted():
                log.log({"error": 40, "warn": 30}.get(
                    d.severity.value, 20), "%s: %s", app.name, d.format())
        return report

    def validate(self, app: Union[str, "SiddhiApp"], *,
                 jaxpr: bool = False):
        """Lint the app and return the LintReport WITHOUT creating a
        runtime. With jaxpr=True also traces each query's compiled step
        for host-sync/dtype hazards (slower: builds a sandbox plan)."""
        from ..analysis import analyze

        return analyze(self._parse(app), jaxpr=jaxpr)

    def validate_siddhi_app(self, app: Union[str, "SiddhiApp"]) -> None:
        """Parse AND plan the app, then discard it — surfacing every
        creation-time error without starting anything (reference:
        SiddhiManager.validateSiddhiApp / managment/ValidateTestCase)."""
        rt = SiddhiAppRuntime(self._parse(app), self.registry,
                              error_store=self.error_store,
                              config_manager=self.config_manager)
        # validation must be read-only: never rewrite durable stores
        rt.shutdown(flush_durable=False)

    def create_sandbox_siddhi_app_runtime(
        self, app: Union[str, "SiddhiApp"], **kw,
    ) -> SiddhiAppRuntime:
        """Build the app with every @source/@sink/@store annotation STRIPPED
        so it runs fully in-memory — the reference's sandbox mode
        (SiddhiManager.createSandboxSiddhiAppRuntime /
        managment/SandboxTestCase): feed via InputHandler, observe via
        callbacks, no external transports or stores."""
        return self.create_siddhi_app_runtime(
            sandbox_app(self._parse(app)), **kw)

    def upgrade(self, new_app: Union[str, "SiddhiApp"], *,
                force: bool = False) -> dict:
        """Blue-green hot-swap of a RUNNING app to `new_app` (same name):
        diff the plan graphs, shadow-start v2, migrate state, replay the WAL
        tail, atomically cut sources/junctions/REST routing over, resume —
        or roll everything back to v1 on any failure. See core/upgrade.py.
        Returns the upgrade summary dict."""
        from .upgrade import upgrade_app
        new_app = self._parse(new_app)
        old = self.runtimes.get(new_app.name)
        if old is None:
            raise SiddhiAppCreationError(
                f"cannot upgrade {new_app.name!r}: no running app by that "
                "name (deploy it instead)")
        if getattr(old, "is_shard_plane", False):
            raise SiddhiAppCreationError(
                f"cannot upgrade sharded app {new_app.name!r} in place: "
                "the blue-green upgrade path swaps ONE runtime, not a "
                "shard fleet — redeploy the plane, or move replicas one "
                "at a time with rebalance()/move_shard() "
                "(docs/SHARDING.md)")
        return upgrade_app(self, old, new_app, force=force)

    def replay(self, app: Union[str, "SiddhiApp"], wal_dir: str, *,
               app_name: Optional[str] = None,
               speed: Optional[float] = None) -> dict:
        """Deterministic accelerated-clock replay of recorded WAL segments
        against a candidate app (backtesting / what-if). See
        core/upgrade.py replay_wal. Returns the replay summary (events,
        per-stream output counts, output digest — bit-identical across runs
        of the same segments)."""
        from .upgrade import replay_wal
        return replay_wal(self, self._parse(app), wal_dir,
                          app_name=app_name, speed=speed)

    def shuffled_replay(self, app: Union[str, "SiddhiApp"],
                        wal_dir: Optional[str] = None, *,
                        app_name: Optional[str] = None, seeds: int = 16,
                        arrivals: Optional[list] = None) -> dict:
        """@app:eventTime determinism oracle: replay one event set (from a
        WAL or an explicit ``(stream, ts, row)`` list) in event-time order
        plus `seeds` lateness-bounded arrival permutations, asserting
        bit-identical output digests and zero late diversions. See
        core/upgrade.py shuffled_replay and docs/EVENT_TIME.md."""
        from .upgrade import shuffled_replay
        return shuffled_replay(self, self._parse(app), wal_dir,
                               app_name=app_name, seeds=seeds,
                               arrivals=arrivals)

    def set_persistence_store(self, store) -> None:
        """Reference: SiddhiManager.setPersistenceStore — shared by all apps."""
        self.persistence_store = store
        for rt in self.runtimes.values():
            rt.persistence_store = store

    def set_error_store(self, store) -> None:
        """Reference: SiddhiManager.setErrorStore — shared by all apps."""
        self.error_store = store
        for rt in self.runtimes.values():
            if getattr(rt, "is_shard_plane", False):
                for srt in rt.shards:
                    if srt is not None:
                        srt.ctx.error_store = store
            else:
                rt.ctx.error_store = store

    def set_config_manager(self, config_manager) -> None:
        """Reference: SiddhiManager.setConfigManager — deployment config for
        extension ConfigReaders (applies to apps created afterwards)."""
        self.config_manager = config_manager

    def persist(self) -> dict:
        """Persist every running app (reference: SiddhiManager.persist:291)."""
        return {name: rt.persist() for name, rt in self.runtimes.items()}

    def restore_last_state(self) -> None:
        for rt in self.runtimes.values():
            rt.restore_last_revision()

    def recover(self) -> dict:
        """Crash-recover every app: restore the last revision + replay each
        app's write-ahead journal (SiddhiAppRuntime.recover)."""
        return {name: rt.recover() for name, rt in self.runtimes.items()}

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self.runtimes.get(name)

    def set_extension(self, name: str, impl, kind: ExtensionKind = None) -> None:
        """Register a per-manager extension as `namespace:name` (reference:
        SiddhiManager.setExtension). `kind` defaults by probing impl type."""
        if kind is None:
            from ..io.record_table import RecordStore
            from ..ops.aggregators import AggregatorFactory
            from ..ops.expr_compile import ScalarFunction
            from ..ops.window_factories import WindowFactory
            if isinstance(impl, AggregatorFactory):
                kind = ExtensionKind.AGGREGATOR
            elif isinstance(impl, ScalarFunction):
                kind = ExtensionKind.FUNCTION
            elif isinstance(impl, WindowFactory):
                kind = ExtensionKind.WINDOW
            elif (isinstance(impl, RecordStore)
                  or (isinstance(impl, type)
                      and issubclass(impl, RecordStore))):
                kind = ExtensionKind.STORE
            else:
                raise SiddhiAppCreationError(
                    f"cannot infer extension kind for {impl!r}; pass kind=")
        ns, _, nm = name.rpartition(":")
        self.registry.register(kind, ns, nm, impl)

    def shutdown(self) -> None:
        for rt in self.runtimes.values():
            rt.shutdown()
        self.runtimes.clear()
