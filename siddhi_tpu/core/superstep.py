"""Device-resident supersteps: K ingress batches per device dispatch.

The async ingress feeder (core/ingress.py) normally delivers one full ring
chunk per controller-lock acquisition: one pjit dispatch per query (or
fused group) per micro-batch, plus the host fan-out. At CPU/TPU dispatch
cost ~0.1-6 ms that per-batch hop dominates the stateful laggards long
before the kernels do (BENCH_r08: groupby 555k ev/s device vs 52.7M for
the stateless filter kernel).

A superstep amortizes the hop: the feeder stages K consecutive full chunks
into one `[K, B]` host block, uploads it with a single device_put, and the
WHOLE eligible query chain — every runtime reachable from the ingress
junction through scannable-through junctions — runs as one `lax.scan` over
the K leading axis with the per-query state tuple as the donated carry.
One dispatch per K batches instead of (nodes x K).

Outputs stay per-batch observable:

  * inside the scan each emitting node's published form
    (`_select_event_type`) is collected per iteration;
  * after the scan, one on-device compaction per emitting slot — per-slot
    valid counts + a single `stable_partition_order` gather over the
    flattened `[K*W]` lanes — packs every valid row, in (iteration, lane)
    order, into a dense prefix;
  * ONE device_get fetches counts + dense buffers, and a host replay loop
    re-publishes slice k to the node's output junction exactly where the
    K=1 path would have (`publish_batch` → `_deliver`), so sinks,
    callbacks on terminal streams, ineligible downstream queries, rate
    limiters (scanned in-state) and telemetry all see per-batch semantics.
    Row content is bit-identical to K=1: compaction preserves lane order
    and `to_host_events`/window masks never read invalid lanes.

Telemetry: the feeder mints one BatchTrace per inner batch from the
per-slot staging t0s; the replay pushes each trace, replays the chain
junction spans nested exactly as `_deliver` would, and attributes each
query an equal share of the measured scan wall time — traces stay per
inner batch and stage spans stay additive (docs/OBSERVABILITY.md).

Eligibility is decided once (lazily, at the first staged superstep) by a
walk from the ingress junction and revalidated cheaply per dispatch;
ineligible plans decline LOUDLY (one log line + statistics_report entry +
the static SL506 lint) and fall back to the K=1 path forever. The knob is
`@app:superstep(k=)` / env SIDDHI_SUPERSTEP_K (core/app_runtime.py).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.search import stable_partition_order
from ..query_api.execution import OutputAction
from .event import EventBatch

# ----------------------------------------------------------- decline taxonomy
#: surfaced verbatim in the feeder log line, statistics_report()
#: ["superstep"], and mirrored by the static SL506 lint (analysis/rules.py)
DECLINE_RECEIVER = "receiver is not a scannable query/join/shared-group"
DECLINE_BREAKER = "query has a circuit breaker"
DECLINE_FAULT = "fault-stream query"
DECLINE_OBJECT = "OBJECT-typed attributes have no scannable layout"
DECLINE_TABLE = "table dependency or input fallback"
DECLINE_CALLBACK = "query callbacks attached"
DECLINE_HOST_SLOT = "host uuid()/unionSet() selector slots"
DECLINE_ACTION = "non-INSERT output action (table executor)"
DECLINE_PARTITION = "partitioned query"
DECLINE_JOIN_BUILD = "join build side is a table/named-window/aggregation"
DECLINE_JOIN_TRIGGER = "join side does not trigger output"
DECLINE_JUNCTION = "junction has taps/event-time gate/redirect/error handler/WAL"
DECLINE_FAN_IN = "fan-in: junction fed by multiple scanned producers"
DECLINE_PLAYBACK = "playback clock advances per delivery"
DECLINE_EMPTY = "no receivers on the async stream"


class _Decline(Exception):
    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


class _Node:
    """One scanned step: a plain QueryRuntime, a SharedStepGroup, or one
    triggering join side. `parent` is the node index whose published output
    feeds this node (-1 = the ingress chunk itself)."""

    __slots__ = ("kind", "qr", "name", "parent", "children", "cap",
                 "pad_always", "bucket_ok", "etype", "out_junction",
                 "members", "from_left")

    def __init__(self, kind: str, qr, name: str, parent: int, cap: int,
                 bucket_ok: bool, etype, out_junction,
                 pad_always: bool = False, members=None,
                 from_left: bool = False) -> None:
        self.kind = kind
        self.qr = qr
        self.name = name
        self.parent = parent
        self.children: list[int] = []
        self.cap = cap
        self.pad_always = pad_always
        self.bucket_ok = bucket_ok
        self.etype = etype
        self.out_junction = out_junction
        self.members = members or []
        self.from_left = from_left


# ------------------------------------------------------------ eligibility


def _query_decline(qr) -> Optional[str]:
    """Why this QueryRuntime cannot be scanned (None = eligible). A strict
    superset of shared.runtime_decline minus custom aggregates: the
    compaction cadence (`_post_step_maintenance`) is replayed per inner
    batch after state writeback, so distinctCount tables keep their
    compaction schedule."""
    from ..query_api.definition import AttributeType
    if getattr(qr, "_partitioned", False):
        return DECLINE_PARTITION
    if qr.breaker is not None:
        return DECLINE_BREAKER
    if qr.query.input_stream.is_fault:
        return DECLINE_FAULT
    if any(a.type == AttributeType.OBJECT
           for a in qr.input_junction.definition.attributes):
        return DECLINE_OBJECT
    if qr.dep_tables or qr._in_fallbacks:
        return DECLINE_TABLE
    if qr.callbacks:
        return DECLINE_CALLBACK
    if qr.selector.host_uuid_slots or \
            getattr(qr.selector, "host_set_slots", None):
        return DECLINE_HOST_SLOT
    if qr.query.output_stream.action != OutputAction.INSERT or \
            qr.table_executor is not None:
        return DECLINE_ACTION
    return None


def _join_decline(r) -> Optional[str]:
    """Why this _JoinSideReceiver cannot be scanned. Only stream-stream
    joins whose scanned side triggers output are eligible: the build side's
    state rides in the carried 5-tuple, while table/named-window/
    aggregation builds live outside it and can be mutated host-side between
    inner batches on the K=1 path."""
    from ..query_api.definition import AttributeType
    qr = r.runtime
    side = qr.left if r.from_left else qr.right
    build = qr.right if r.from_left else qr.left
    from ..query_api.execution import EventTrigger
    triggers = (qr.trigger == EventTrigger.ALL
                or (qr.trigger == EventTrigger.LEFT and r.from_left)
                or (qr.trigger == EventTrigger.RIGHT and not r.from_left))
    if not triggers:
        return DECLINE_JOIN_TRIGGER
    if build.is_table or build.is_named_window or build.is_aggregation:
        return DECLINE_JOIN_BUILD
    if getattr(qr, "breaker", None) is not None:
        return DECLINE_BREAKER
    if qr.callbacks:
        return DECLINE_CALLBACK
    if qr.selector.host_uuid_slots or \
            getattr(qr.selector, "host_set_slots", None):
        return DECLINE_HOST_SLOT
    if qr.query.output_stream.action != OutputAction.INSERT or \
            qr.table_executor is not None:
        return DECLINE_ACTION
    if side.junction is not None and any(
            a.type == AttributeType.OBJECT
            for a in side.junction.definition.attributes):
        return DECLINE_OBJECT
    return None


def _junction_decline(j) -> Optional[str]:
    if j.taps or j._et is not None or j._redirect is not None \
            or j.wal is not None or j.on_error is not None \
            or j.on_error_action is not None:
        return DECLINE_JUNCTION
    return None


class SuperstepRunner:
    """One runner per async ingress junction, built lazily by the feeder at
    the first staged superstep. `dispatch(slots)` returns False when this
    superstep must fall back to per-batch delivery (debugger attached, plan
    invalidated by a topology change); the feeder then delivers the staged
    chunks through the ordinary K=1 path."""

    def __init__(self, pipeline, k: int) -> None:
        self.pipeline = pipeline
        self.j = pipeline.j
        self.ctx = pipeline.ctx
        self.k = int(k)
        self.name = f"superstep:{self.j.definition.id}"
        self.B = self.j.batch_size
        self.nodes: list[_Node] = []
        self.roots: list[int] = []
        self._steps: list = []          # per node: fn | [member fns]
        self._build_plan()
        # receiver-list snapshots for cheap per-dispatch revalidation: a
        # subscribe/unsubscribe anywhere in the scanned region rebuilds
        self._junctions = [self.j] + [n.out_junction for n in self.nodes
                                      if n.children]
        self._snaps = [tuple(id(r) for r in j.receivers)
                       for j in self._junctions]
        self._n_queries = sum(len(n.members) if n.kind == "group" else 1
                              for n in self.nodes)
        self._emit_flags = self._current_emit_flags()
        self._emit_slots: list = []     # (node_idx, member_idx|None)
        self._fn = self._make_jit(self._emit_flags)
        self._tele_cells: dict = {}
        self._warmed = False

    # ------------------------------------------------------------ plan build

    def _build_plan(self) -> None:
        from .query_runtime import QueryRuntime
        ctx = self.ctx
        if ctx.playback:
            raise _Decline(DECLINE_PLAYBACK)
        if not self.j.receivers:
            raise _Decline(DECLINE_EMPTY)
        why = _junction_decline(self.j)
        if why:
            raise _Decline(why)
        claimed = {id(self.j)}
        self._add_receivers(self.j, -1, claimed, require=True)
        if not self.nodes:
            raise _Decline(DECLINE_EMPTY)
        self.roots = [i for i, n in enumerate(self.nodes) if n.parent < 0]

    def _add_receivers(self, j, parent: int, claimed: set,
                       require: bool) -> bool:
        """Try to scan every receiver of `j`. With require=True (the
        ingress junction) any ineligible receiver declines the whole plan;
        with require=False (a chain junction) the caller keeps the parent
        terminal instead. Fan-in onto an already-claimed junction always
        declines: replayed host deliveries would reorder against in-scan
        consumption."""
        from .join_runtime import _JoinSideReceiver
        from .query_runtime import QueryRuntime
        from .shared import SharedStepGroup
        mark = len(self.nodes)
        added: list[int] = []
        try:
            for r in list(j.receivers):
                if type(r) is QueryRuntime:
                    why = _query_decline(r)
                    if why:
                        raise _Decline(f"{r.name}: {why}")
                    node = _Node("query", r, r.name, parent, r._batch_cap,
                                 r._bucket_ok,
                                 r.query.output_stream.event_type,
                                 r.output_junction)
                    self._steps.append(r._make_step(track_compiles=False))
                elif isinstance(r, SharedStepGroup):
                    for m in r.members:
                        why = _query_decline(m)
                        if why:
                            raise _Decline(f"{m.name}: {why}")
                    node = _Node("group", r, r.name, parent, r._batch_cap,
                                 r._bucket_ok, None, None, members=r.members)
                    self._steps.append(list(r._steps))
                elif isinstance(r, _JoinSideReceiver):
                    why = _join_decline(r)
                    if why:
                        raise _Decline(f"{r.runtime.name}: {why}")
                    qr = r.runtime
                    side = qr.left if r.from_left else qr.right
                    node = _Node("join", qr, qr.name, parent,
                                 side.junction.batch_size, False,
                                 qr.query.output_stream.event_type,
                                 qr.output_junction, pad_always=True,
                                 from_left=r.from_left)
                    self._steps.append(qr._make_step(from_left=r.from_left))
                else:
                    raise _Decline(
                        f"{type(r).__name__}: {DECLINE_RECEIVER}")
                self.nodes.append(node)
                idx = len(self.nodes) - 1
                added.append(idx)
                if parent >= 0:
                    self.nodes[parent].children.append(idx)
            # recurse: scan through each added node's output junction when
            # every one of ITS receivers is eligible too
            for idx in added:
                node = self.nodes[idx]
                if node.kind == "group":
                    continue  # member outputs deliver terminally
                oj = node.out_junction
                if oj is None or not oj.receivers:
                    continue
                if id(oj) in claimed:
                    raise _Decline(DECLINE_FAN_IN)
                if _junction_decline(oj):
                    continue  # terminal: replay delivers through _deliver
                claimed.add(id(oj))
                if not self._add_receivers(oj, idx, claimed, require=False):
                    claimed.discard(id(oj))
            return True
        except _Decline as d:
            if require or d.reason == DECLINE_FAN_IN:
                # fan-in always declines the WHOLE plan: treating the
                # second producer as terminal would deliver its batches
                # after the scan consumed the first producer's K batches —
                # reordered relative to the K=1 interleaving
                raise
            # roll back this junction's children; the parent goes terminal
            del self._steps[mark:]
            del self.nodes[mark:]
            if parent >= 0:
                self.nodes[parent].children = [
                    c for c in self.nodes[parent].children if c < mark]
            return False

    # ------------------------------------------------------------- emit flags

    def _current_emit_flags(self) -> tuple:
        """Per node: is the terminal output observable? Mirrors
        shared.SharedStepGroup._current_emit_flags — scanned-through nodes
        (children consume the output in-scan) never deliver terminally.
        Group entries are per-member tuples."""
        from .query_runtime import _sink_dark
        flags = []
        for n in self.nodes:
            if n.kind == "group":
                flags.append(n.qr._current_emit_flags())
            elif n.children:
                flags.append(False)
            else:
                j = n.out_junction
                flags.append(j is not None and not _sink_dark(j))
        return tuple(flags)

    # -------------------------------------------------------------- the scan

    def _make_jit(self, emit_flags: tuple):
        from .query_runtime import QueryRuntime
        nodes = self.nodes
        steps = self._steps
        stats = self.ctx.statistics
        name = self.name
        B = self.B
        emit_slots: list = []
        for i, n in enumerate(nodes):
            if n.kind == "group":
                emit_slots.extend((i, mi) for mi, f in enumerate(emit_flags[i])
                                  if f)
            elif emit_flags[i]:
                emit_slots.append((i, None))
        self._emit_slots = emit_slots
        chain_nodes = [i for i, n in enumerate(nodes) if n.children]
        self._chain_nodes = chain_nodes

        def pad_in(inp, node):
            if inp.capacity < node.cap and (node.pad_always
                                            or not node.bucket_ok):
                return inp.pad_to(node.cap)
            return inp

        def superstep(states, ts_k, cols_k, now_k):
            # one compile per runner (full chunks only: shapes never vary)
            stats.track_compile(name, ts_k.shape[1])

            def body(carry, x):
                sts, drops = list(carry[0]), list(carry[1])
                ts, cols, now = x
                ingress = EventBatch(
                    ts=ts, cols=cols,
                    valid=jnp.ones((B,), jnp.bool_),
                    types=jnp.zeros((B,), jnp.int8))
                fwds: dict = {}
                emits: dict = {}
                counts: dict = {}
                for i, node in enumerate(nodes):
                    inp = ingress if node.parent < 0 else fwds[node.parent]
                    inp = pad_in(inp, node)
                    if node.kind == "group":
                        new_sts = []
                        for mi, (st, stp, m) in enumerate(
                                zip(sts[i], steps[i], node.members)):
                            s2, out = stp(st, inp, now, None)
                            new_sts.append(s2)
                            if emit_flags[i][mi]:
                                f = QueryRuntime._select_event_type(
                                    out, m.query.output_stream.event_type)
                                emits[(i, mi)] = (f.ts, f.cols, f.valid)
                        sts[i] = tuple(new_sts)
                        continue
                    if node.kind == "join":
                        s2, out, dropped = steps[i](sts[i], inp, now, None)
                        drops[i] = drops[i] + dropped
                    else:
                        s2, out = steps[i](sts[i], inp, now,
                                           node.qr._table_states())
                    sts[i] = s2
                    if node.children or emit_flags[i]:
                        fwd = QueryRuntime._select_event_type(out, node.etype)
                        if node.children:
                            fwds[i] = fwd
                            counts[i] = jnp.sum(fwd.valid.astype(jnp.int32))
                        else:
                            emits[(i, None)] = (fwd.ts, fwd.cols, fwd.valid)
                ys = (tuple(emits[s] for s in emit_slots),
                      tuple(counts[i] for i in chain_nodes))
                return (tuple(sts), tuple(drops)), ys

            drops0 = tuple(jnp.int32(0) for _ in nodes)
            (states2, drops2), (ys_emit, ys_counts) = jax.lax.scan(
                body, (states, drops0), (ts_k, cols_k, now_k))
            # on-device compaction: one stable partition per emitting slot
            # packs every valid row — in (iteration, lane) order — into a
            # dense prefix of the flattened [K*W] buffer, so slice k of the
            # SINGLE fetched array is exactly inner batch k's output
            compacted = []
            for ts_y, cols_y, valid_y in ys_emit:
                cnt = jnp.sum(valid_y.astype(jnp.int32), axis=1)
                perm = stable_partition_order(valid_y.reshape(-1))
                compacted.append(
                    (cnt, ts_y.reshape(-1)[perm],
                     {a: v.reshape(-1)[perm] for a, v in cols_y.items()}))
            return states2, tuple(compacted), ys_counts, drops2

        return jax.jit(superstep, donate_argnums=(0,))

    def warm(self) -> None:
        """AOT-compile the superstep (query_runtime.aot_warm) so the first
        dispatch never pays the trace+compile inside the controller lock."""
        if self._warmed:
            return
        from .query_runtime import aot_warm
        K, B = self.k, self.B
        ts_k = np.zeros((K, B), np.int64)
        cols_k = {a: np.zeros((K, B), dt)
                  for a, dt in zip(self.pipeline.attrs,
                                   self.pipeline.np_dtypes)}
        now_k = np.zeros((K,), np.int64)
        aot_warm(self._fn, self._states(), ts_k, cols_k, now_k)
        self._warmed = True

    def _states(self) -> tuple:
        return tuple(tuple(m.state for m in n.members)
                     if n.kind == "group" else n.qr.state
                     for n in self.nodes)

    # -------------------------------------------------------------- dispatch

    def revalidate(self) -> bool:
        """Cheap per-dispatch guard: the scanned topology (receiver lists,
        callbacks, debugger) must still match the built plan. False = the
        caller must fall back (and rebuild on the next superstep)."""
        if getattr(self.ctx, "debugger", None) is not None:
            return False
        for j, snap in zip(self._junctions, self._snaps):
            if tuple(id(r) for r in j.receivers) != snap:
                return False
        for n in self.nodes:
            qrs = n.members if n.kind == "group" else [n.qr]
            for qr in qrs:
                if qr.callbacks or qr.selector.host_uuid_slots:
                    return False
        return True

    def dispatch(self, slots: list) -> bool:
        """Run one superstep over `slots` = [(ts_buf, col_bufs, t0_ns), ...]
        (feeder thread, controller lock NOT held). Returns False when the
        caller must deliver the slots through the K=1 path instead."""
        if not self.revalidate():
            return False
        flags = self._current_emit_flags()
        if flags != self._emit_flags:
            # a terminal sink lit up or went dark: one retrace, mirrored
            # from shared.SharedStepGroup.on_batch
            self._emit_flags = flags
            self._fn = self._make_jit(flags)
            self._warmed = False
        pipe = self.pipeline
        ctx = self.ctx
        j = self.j
        K = len(slots)
        tele = getattr(ctx, "telemetry", None)
        tracing = tele is not None and tele.on
        sid = j.definition.id

        # ---- one host stack + one device_put for the whole superstep ----
        t0 = time.perf_counter_ns()
        ts_k = jnp.asarray(np.stack([s[0] for s in slots]))
        cols_k = {a: jnp.asarray(np.stack([s[1][ai] for s in slots]))
                  for ai, a in enumerate(pipe.attrs)}
        h2d = time.perf_counter_ns() - t0
        pipe._h2d_ns += h2d
        pipe._h2d_count += K
        traces = None
        if tracing:
            traces = []
            for ts_buf, _cols, slot_t0 in slots:
                tr = tele.mint(sid, self.B, t0=slot_t0)
                tr.h2d_ns = h2d // K
                tr.superstep = K
                traces.append(tr)
                tele.record_lag(sid, int(ts_buf[-1]))

        with ctx.controller_lock:
            # staged (sync-path) rows flush first: arrival order, exactly
            # as _deliver_locked / publish_batch would
            for cj in self._junctions:
                if cj._staged_rows or cj._tap_queue:
                    cj.flush()
            now = ctx.timestamp_generator.current_time()
            now_k = jnp.full((K,), now, jnp.int64)
            d0 = time.perf_counter_ns()
            states2, compacted, chain_counts, drops = self._fn(
                self._states(), ts_k, cols_k, now_k)
            # ONE fetch per superstep: counts + dense compacted outputs
            host = jax.device_get(compacted)
            chain_host = jax.device_get(chain_counts) if chain_counts else ()
            scan_ns = time.perf_counter_ns() - d0
            pipe._ss_scan_ns += scan_ns
            # write every state back BEFORE any distribution: terminal
            # callbacks can re-enter the ingress junction synchronously
            for n, s in zip(self.nodes, states2):
                if n.kind == "group":
                    for m, ms in zip(n.members, s):
                        m.state = ms
                else:
                    n.qr.state = s
            try:
                self._replay(slots, host, chain_host, drops, traces, now,
                             d0, scan_ns)
            except Exception as e:
                # the scan already COMMITTED (states written back): the
                # slots must not be re-delivered through the K=1 path, or
                # every window/aggregate would double-count them. Mark the
                # error as committed so the feeder disables supersteps
                # without replaying, and keep the feeder thread alive.
                e.superstep_committed = True  # type: ignore[attr-defined]
                raise
            dev = time.perf_counter_ns() - d0
            pipe._ss_replay_ns += dev - scan_ns
            pipe._device_ns += dev
            pipe._batches += K
        return True

    # ---------------------------------------------------------------- replay

    def _replay(self, slots, host, chain_host, drops, traces, now: int,
                d0: int, scan_ns: int) -> None:
        """Per-inner-batch host fan-out: replay counters, traces, terminal
        publishes, and per-query maintenance in the exact nesting order of
        K single-batch deliveries."""
        ctx = self.ctx
        stats = ctx.statistics
        tele = getattr(ctx, "telemetry", None)
        tracing = traces is not None
        K = len(slots)
        sid = self.j.definition.id
        # equal-share attribution, like SharedStepGroup: each query reports
        # scan_wall / (K * queries) so per-trace device spans stay additive
        share = scan_ns // max(K * self._n_queries, 1)
        offsets = [np.zeros(K + 1, np.int64) for _ in host]
        for si, (cnt, _ts, _cols) in enumerate(host):
            offsets[si][1:] = np.cumsum(cnt)
        slot_of = {key: si for si, key in enumerate(self._emit_slots)}
        chain_of = {ni: ci for ci, ni in enumerate(self._chain_nodes)}
        flags = self._emit_flags

        def deliver(node, key, k):
            si = slot_of[key]
            cnt, dts, dcols = host[si]
            c = int(cnt[k])
            off = int(offsets[si][k])
            oj = node.out_junction
            # _pad_cap buckets up to the junction batch size, but a step's
            # emit width can exceed it (e.g. a lengthBatch flush emits
            # window-capacity rows): fall back to the slot's device width,
            # which is exactly the width the K=1 step would have delivered
            pcap = oj._pad_cap(c)
            if pcap < c:
                pcap = dts.size // K
            ts_arr = np.zeros(pcap, np.int64)
            cols = {}
            if c:
                ts_arr[:c] = dts[off:off + c]
                ts_arr[c:] = ts_arr[c - 1]  # monotone pad
            for a, v in dcols.items():
                col = np.zeros(pcap, v.dtype)
                if c:
                    col[:c] = v[off:off + c]
                cols[a] = col
            oj.publish_batch(EventBatch.from_numpy(ts_arr, cols, c), now)

        def replay_node(i: int, k: int) -> None:
            node = self.nodes[i]
            if node.kind == "group":
                g = node.qr
                for mi, m in enumerate(node.members):
                    if flags[i][mi]:
                        deliver_member(node, i, mi, k)
                    if stats.detail:
                        stats.track_latency(m.name, share)
                    m._post_step_maintenance()
                if tele is not None and tele.on:
                    cells = self._tele_cells.get(i)
                    if cells is None:
                        cells = self._tele_cells[i] = [
                            tele.query_cell(m.name) for m in node.members]
                    tele.record_query_block(
                        cells, [m.name for m in node.members], share)
                stats.track_latency(g.name, share * len(node.members))
                g._batches_seen += 1
                return
            if flags[i] and not node.children:
                deliver(node, (i, None), k)
            if node.children:
                oj = node.out_junction
                tr2 = None
                if tracing:
                    tr2 = tele.mint(oj.definition.id)
                    tr2.deliver_t0 = time.perf_counter_ns()
                    tele.push_active(tr2)
                ci = chain_of[i]
                n_in = int(chain_host[ci][k]) if stats.enabled else 0
                stats.track_in(oj.definition.id, n_in)
                stats.track_batch(oj.definition.id)
                try:
                    for c in node.children:
                        replay_node(c, k)
                finally:
                    if tr2 is not None:
                        tele.pop_active(tr2)
            if tele is not None and tele.on:
                tele.record_query(node.name, share)
            stats.track_latency(node.name, share)
            if node.kind == "query":
                node.qr._post_step_maintenance()
            else:  # join: replay the device-side drop accounting — the
                # scan already summed this superstep's drops, so the total
                # lands once (k=0) and the warning cadence advances per k
                qr = node.qr
                if k == 0:
                    d = drops[i]
                    qr._dropped_dev = (d if qr._dropped_dev is None
                                       else qr._dropped_dev + d)
                qr._drop_checks += 1
                if not qr._drop_warned and qr._drop_checks % 64 == 0:
                    if int(qr._dropped_dev) > 0:
                        import warnings
                        warnings.warn(
                            f"join {qr.name!r}: "
                            f"{int(qr._dropped_dev)} matched pairs exceeded "
                            "the per-step pair block or the per-probe "
                            "candidate walk and were dropped — raise "
                            "config.join_pair_cap_factor / "
                            "config.join_max_matches", stacklevel=2)
                        qr._drop_warned = True

        def deliver_member(node, i, mi, k):
            m = node.members[mi]
            si = slot_of[(i, mi)]
            cnt, dts, dcols = host[si]
            c = int(cnt[k])
            off = int(offsets[si][k])
            oj = m.output_junction
            if oj is None:
                return
            pcap = oj._pad_cap(c)
            if pcap < c:  # emit wider than the junction bucket: slot width
                pcap = dts.size // K
            ts_arr = np.zeros(pcap, np.int64)
            cols = {}
            if c:
                ts_arr[:c] = dts[off:off + c]
                ts_arr[c:] = ts_arr[c - 1]
            for a, v in dcols.items():
                col = np.zeros(pcap, v.dtype)
                if c:
                    col[:c] = v[off:off + c]
                cols[a] = col
            oj.publish_batch(EventBatch.from_numpy(ts_arr, cols, c), now)

        for k in range(K):
            tr = traces[k] if tracing else None
            if tr is not None:
                tr.deliver_t0 = d0
                tele.push_active(tr)
            try:
                stats.track_in(sid, self.B if stats.enabled else 0)
                stats.track_batch(sid)
                for r in self.roots:
                    replay_node(r, k)
            finally:
                if tr is not None:
                    tele.pop_active(tr)


def build_runner(pipeline, k: int):
    """Feeder entry point: (runner, None) or (None, decline reason)."""
    try:
        runner = SuperstepRunner(pipeline, k)
    except _Decline as d:
        return None, d.reason
    try:
        runner.warm()
    except Exception as e:  # pragma: no cover — lowering failure
        return None, f"superstep compile failed: {e}"
    return runner, None
