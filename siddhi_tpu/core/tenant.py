"""Per-tenant quotas and device-time metering for multi-tenant apps.

An app declares tenants with app-level annotations::

    @app:tenant(id='acme', device.ms='40', queries='8', window='60')

and maps each query to one with a query-level ``@tenant('acme')``. Two
budgets exist:

- ``queries``  — hard ceiling on concurrently attached queries. Checked
  synchronously at build/attach time (SiddhiAppCreationError), so an
  over-quota attach_query never allocates device state.
- ``device.ms`` — rolling-window budget of *metered device time* (the
  per-query latency attribution every dispatch path already computes;
  fused groups report an equal share per member). Enforced asynchronously
  by the runtime's flush/heartbeat boundary: every query of an over-budget
  tenant is spliced OUT of its fused group (siblings untouched) and given
  a force-tripped quota CircuitBreaker, so the junction diverts its
  batches to the dead-letter path until the window drains. Recovery is
  automatic — once the tenant is back under budget the quota breakers are
  removed and the queries re-splice.

Blast radius is therefore per tenant: a noisy tenant's queries are the
only receivers diverted, and because splice-out is a one-retrace
operation the siblings never stop.

Like CircuitBreaker, the registry has NO internal locking: recording and
enforcement both run under the app's controller discipline (delivery and
flush hold ctx.controller_lock).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ..errors import SiddhiAppCreationError

__all__ = ["TenantQuota", "TenantRegistry", "tenants_from_app"]


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's declared budgets (None = unlimited)."""

    id: str
    max_queries: Optional[int] = None
    device_ms: Optional[float] = None
    window_s: float = 60.0


@dataclass
class _TenantLedger:
    """Rolling-window device-time entries: (monotonic_s, ns, query)."""

    quota: TenantQuota
    entries: deque = field(default_factory=deque)
    total_ns: int = 0  # sum over entries (kept incrementally)
    breaches: int = 0
    diverting: bool = False  # quota breakers currently attached


class TenantRegistry:
    """Query→tenant ownership plus rolling device-time accounting."""

    def __init__(self, quotas: dict[str, TenantQuota],
                 clock=time.monotonic) -> None:
        self._clock = clock
        self._ledgers: dict[str, _TenantLedger] = {
            tid: _TenantLedger(q) for tid, q in quotas.items()}
        self._owner: dict[str, str] = {}  # query name -> tenant id
        self._tele = None
        self._ms_cells: dict[str, object] = {}
        self._q_cells: dict[str, object] = {}

    # ------------------------------------------------------------ ownership

    def ids(self) -> list[str]:
        return list(self._ledgers)

    def quota(self, tid: str) -> TenantQuota:
        return self._ledgers[tid].quota

    def tenant_of(self, query: str) -> Optional[str]:
        return self._owner.get(query)

    def queries_of(self, tid: str) -> list[str]:
        return [q for q, t in self._owner.items() if t == tid]

    def query_count(self, tid: str) -> int:
        return sum(1 for t in self._owner.values() if t == tid)

    def assign(self, query: str, tid: str) -> None:
        """Bind `query` to tenant `tid`; raises SiddhiAppCreationError on
        an unknown tenant or a full `queries=` quota — the caller must
        check BEFORE allocating runtime state."""
        led = self._ledgers.get(tid)
        if led is None:
            raise SiddhiAppCreationError(
                f"query {query!r} names undeclared tenant {tid!r} "
                f"(declare @app:tenant(id='{tid}', ...))")
        q = led.quota
        if (q.max_queries is not None
                and self.query_count(tid) >= q.max_queries):
            raise SiddhiAppCreationError(
                f"SL502: tenant {tid!r} at query quota "
                f"({q.max_queries}): cannot attach {query!r}")
        self._owner[query] = tid
        self._set_query_gauge(tid)

    def release(self, query: str) -> None:
        tid = self._owner.pop(query, None)
        if tid is not None:
            self._set_query_gauge(tid)

    # ------------------------------------------------------------- metering

    def record(self, query: str, elapsed_ns: int) -> None:
        """Attribute one dispatch's wall time to the owning tenant (no-op
        for unowned queries). Always on — NOT gated on statistics detail
        or telemetry enablement, because quota enforcement reads it."""
        tid = self._owner.get(query)
        if tid is None:
            return
        led = self._ledgers[tid]
        led.entries.append((self._clock(), int(elapsed_ns), query))
        led.total_ns += int(elapsed_ns)
        cell = self._ms_cells.get(tid)
        if cell is not None:
            cell.inc(elapsed_ns / 1e6)

    def record_block(self, queries, share_ns: int) -> None:
        """Fused-group attribution: an equal share per member (the same
        split SharedStepGroup reports to statistics/telemetry)."""
        for q in queries:
            self.record(q, share_ns)

    def _prune(self, led: _TenantLedger, now_s: float) -> None:
        horizon = now_s - led.quota.window_s
        ent = led.entries
        while ent and ent[0][0] < horizon:
            led.total_ns -= ent.popleft()[1]

    def spent_ms(self, tid: str) -> float:
        """Device ms attributed to `tid` within its rolling window."""
        led = self._ledgers[tid]
        self._prune(led, self._clock())
        return led.total_ns / 1e6

    def over_budget(self) -> list[str]:
        """Tenants currently past their device.ms window budget."""
        out = []
        for tid, led in self._ledgers.items():
            if led.quota.device_ms is None:
                continue
            if self.spent_ms(tid) > led.quota.device_ms:
                out.append(tid)
        return out

    def dominant_query(self, tid: str) -> Optional[str]:
        """The query consuming the most device time in the window — the
        doctor names it in tenant_quota_breach findings."""
        led = self._ledgers[tid]
        self._prune(led, self._clock())
        by_q: dict[str, int] = {}
        for _, ns, q in led.entries:
            by_q[q] = by_q.get(q, 0) + ns
        if not by_q:
            return None
        return max(by_q, key=by_q.get)

    # ---------------------------------------------------------- enforcement

    def note_breach(self, tid: str) -> bool:
        """Mark `tid` breached; True when this is a NEW breach (tenant was
        not already diverting) — the edge the FlightRecorder triggers on."""
        led = self._ledgers[tid]
        fresh = not led.diverting
        if fresh:
            led.breaches += 1
        led.diverting = True
        return fresh

    def note_recovery(self, tid: str) -> None:
        self._ledgers[tid].diverting = False

    def diverting(self, tid: str) -> bool:
        return self._ledgers[tid].diverting

    # ------------------------------------------------------------ reporting

    def bind_telemetry(self, tele) -> None:
        """Cache per-tenant Prometheus cells (always-on families declared
        by AppTelemetry): device-ms counter + query-count gauge."""
        self._tele = tele
        reg = tele.registry
        ms_fam = reg.counter("siddhi_tenant_device_ms_total",
                             "Metered device milliseconds per tenant",
                             ("tenant",))
        q_fam = reg.gauge("siddhi_tenant_queries",
                          "Attached queries per tenant", ("tenant",))
        for tid in self._ledgers:
            self._ms_cells[tid] = ms_fam.labels(tid)
            self._q_cells[tid] = q_fam.labels(tid)
            self._set_query_gauge(tid)

    def _set_query_gauge(self, tid: str) -> None:
        cell = self._q_cells.get(tid)
        if cell is not None:
            cell.set(self.query_count(tid))

    def report(self, stats=None) -> dict:
        """statistics_report()['tenants'] section."""
        out = {}
        for tid, led in self._ledgers.items():
            q = led.quota
            queries = self.queries_of(tid)
            entry = {
                "queries": sorted(queries),
                "query_count": len(queries),
                "max_queries": q.max_queries,
                "device_ms_window": round(self.spent_ms(tid), 3),
                "device_ms_budget": q.device_ms,
                "window_s": q.window_s,
                "breaches": led.breaches,
                "diverting": led.diverting,
            }
            if stats is not None:
                entry["diverted_rows"] = sum(
                    stats.breaker_diverted.get(name, 0)
                    for name in queries)
            dom = self.dominant_query(tid)
            if dom is not None:
                entry["dominant_query"] = dom
            out[tid] = entry
        return out


# ------------------------------------------------------------------ parsing


def _parse_float(ann, key: str, tid: str) -> Optional[float]:
    raw = ann.element(key)
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        raise SiddhiAppCreationError(
            f"@app:tenant(id={tid!r}): {key}={raw!r} is not a number")


def tenants_from_app(app, clock=time.monotonic) -> Optional[TenantRegistry]:
    """Build the registry from `@app:tenant(...)` annotations (None when
    the app declares no tenants). Query ownership comes from query-level
    `@tenant('id')` annotations and is validated against `queries=`
    quotas here, before any runtime state exists."""
    quotas: dict[str, TenantQuota] = {}
    for ann in app.annotations:
        if ann.name.lower() != "app:tenant":
            continue
        tid = ann.element("id") or ann.element()
        if not tid:
            raise SiddhiAppCreationError(
                "@app:tenant requires id= (or a bare tenant id)")
        if tid in quotas:
            raise SiddhiAppCreationError(
                f"duplicate @app:tenant(id={tid!r})")
        mq = ann.element("queries")
        try:
            max_queries = int(mq) if mq is not None else None
        except ValueError:
            raise SiddhiAppCreationError(
                f"@app:tenant(id={tid!r}): queries={mq!r} is not an int")
        quotas[tid] = TenantQuota(
            id=tid, max_queries=max_queries,
            device_ms=_parse_float(ann, "device.ms", tid),
            window_s=_parse_float(ann, "window", tid) or 60.0)
    if not quotas:
        return None
    return TenantRegistry(quotas, clock=clock)


def query_tenant(query) -> Optional[str]:
    """The tenant id a query claims via `@tenant('id')` (None = unowned)."""
    for ann in getattr(query, "annotations", ()) or ():
        if ann.name.lower() == "tenant":
            return ann.element("id") or ann.element()
    return None
