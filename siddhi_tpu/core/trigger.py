"""Triggers — `define trigger T at ('start' | every <interval> | '<cron>')`.

Reference: core/trigger/ — PeriodicTrigger.java:36,74 (ScheduledExecutorService),
CronTrigger.java:46,109 (quartz), StartTrigger. A trigger defines a stream named
after itself with one attribute `triggered_time long` and injects events into
its junction at fire times.

TPU design: the engine is synchronous single-controller (no background timer
threads racing the jitted pipeline), so trigger firing is **watermark-driven**:
`poll(now)` computes every due fire time <= now and stages one event per fire
into the trigger's junction. The app runtime polls triggers on every flush() /
heartbeat(), which is also how time windows receive their timer batches — one
clock, one ordering. `start` triggers fire once inside SiddhiAppRuntime.start().
Cron expressions use quartz's 6/7-field layout (sec min hour dom mon dow
[year]), evaluated by the pure-Python matcher below.
"""

from __future__ import annotations

import calendar
from datetime import datetime, timedelta
from typing import Optional

from ..errors import SiddhiAppCreationError
from ..query_api.definition import Attribute, AttributeType, StreamDefinition, TriggerDefinition


# --------------------------------------------------------------------------- #
# quartz-style cron (sec min hour dom mon dow [year]); minute-level wildcards
# like the reference's common "0 * * * * ?" patterns
# --------------------------------------------------------------------------- #


def _parse_field(spec: str, lo: int, hi: int, names: Optional[dict] = None) -> Optional[frozenset]:
    """One cron field → allowed-value set, or None for 'any' (* or ?)."""
    spec = spec.strip()
    if spec in ("*", "?"):
        return None
    allowed: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if part in ("*", ""):
                part = f"{lo}-{hi}"
        if names:
            for nm, v in names.items():
                part = part.upper().replace(nm, str(v))
        if "-" in part:
            a, b = int(part.split("-", 1)[0]), int(part.split("-", 1)[1])
            if a <= b:
                rng = list(range(a, b + 1, step))
            else:  # quartz wrap-around range, e.g. hours 22-2 or SAT-SUN
                rng = list(range(a, hi + 1, step)) + list(range(lo, b + 1, step))
        else:
            start = int(part)
            rng = range(start, hi + 1, step) if step > 1 else (start,)
        for v in rng:
            if not (lo <= v <= hi):
                raise SiddhiAppCreationError(
                    f"cron field value {v} outside [{lo},{hi}]")
            allowed.add(v)
    if not allowed:
        raise SiddhiAppCreationError(f"cron field {spec!r} matches no values")
    return frozenset(allowed)


_MONTHS = {m.upper(): i for i, m in enumerate(calendar.month_abbr) if m}
_DOWS = {"SUN": 1, "MON": 2, "TUE": 3, "WED": 4, "THU": 5, "FRI": 6, "SAT": 7}


class CronSchedule:
    """Quartz layout: sec min hour day-of-month month day-of-week [year].
    Reference: CronTrigger.java:46 delegates to quartz; this is a direct
    next-fire evaluator over the same field semantics."""

    def __init__(self, expr: str) -> None:
        fields = expr.split()
        if len(fields) not in (6, 7):
            raise SiddhiAppCreationError(
                f"cron expression needs 6 or 7 fields (quartz), got {expr!r}")
        self.sec = _parse_field(fields[0], 0, 59)
        self.minute = _parse_field(fields[1], 0, 59)
        self.hour = _parse_field(fields[2], 0, 23)
        self.dom = _parse_field(fields[3], 1, 31)
        self.mon = _parse_field(fields[4], 1, 12, _MONTHS)
        self.dow = _parse_field(fields[5], 1, 7, _DOWS)  # 1 = SUN (quartz)
        self.year = _parse_field(fields[6], 1970, 2199) if len(fields) == 7 else None
        if self.dom is not None and self.dow is not None:
            # quartz rejects restricting both; accepting would silently AND
            # them (classic cron ORs) — surprising either way
            raise SiddhiAppCreationError(
                "cron: specify day-of-month or day-of-week, not both "
                f"(use '?' for one): {expr!r}")

    @staticmethod
    def _next_in(allowed: Optional[frozenset], v: int, lo: int, hi: int):
        """Smallest allowed value >= v, or (lo-of-allowed, carry=True)."""
        if allowed is None:
            return v, False
        geq = [a for a in allowed if a >= v]
        if geq:
            return min(geq), False
        return min(allowed), True

    def next_fire_ms(self, after_ms: int) -> Optional[int]:
        """First fire time strictly after `after_ms` (epoch millis). Field-carry
        evaluation: jumps straight to the next allowed second/minute/hour/day
        instead of scanning second-by-second."""
        dt = datetime.fromtimestamp(after_ms / 1000.0).replace(microsecond=0)
        dt += timedelta(seconds=1)
        limit = dt + timedelta(days=366 * 4)
        while dt < limit:
            if ((self.mon is not None and dt.month not in self.mon)
                    or (self.dom is not None and dt.day not in self.dom)
                    or (self.dow is not None
                        and (dt.isoweekday() % 7) + 1 not in self.dow)
                    or (self.year is not None and dt.year not in self.year)):
                dt = (dt + timedelta(days=1)).replace(hour=0, minute=0, second=0)
                continue
            h, carry = self._next_in(self.hour, dt.hour, 0, 23)
            if carry:
                dt = (dt + timedelta(days=1)).replace(hour=0, minute=0, second=0)
                continue
            if h != dt.hour:
                dt = dt.replace(hour=h, minute=0, second=0)
            m, carry = self._next_in(self.minute, dt.minute, 0, 59)
            if carry:
                dt = (dt.replace(minute=0, second=0) + timedelta(hours=1))
                continue
            if m != dt.minute:
                dt = dt.replace(minute=m, second=0)
            s, carry = self._next_in(self.sec, dt.second, 0, 59)
            if carry:
                dt = (dt.replace(second=0) + timedelta(minutes=1))
                continue
            return int(dt.replace(second=s).timestamp() * 1000)
        return None


# --------------------------------------------------------------------------- #
# trigger runtime
# --------------------------------------------------------------------------- #

TRIGGER_ATTR = "triggered_time"


def trigger_stream_definition(td: TriggerDefinition) -> StreamDefinition:
    """A trigger IS a stream of (triggered_time long) (reference:
    DefinitionParserHelper — trigger streams)."""
    return StreamDefinition(
        id=td.id,
        attributes=(Attribute(TRIGGER_ATTR, AttributeType.LONG),),
        annotations=td.annotations)


class TriggerRuntime:
    """Watermark-driven fire computation for one trigger."""

    def __init__(self, definition: TriggerDefinition, junction, ctx) -> None:
        self.definition = definition
        self.junction = junction
        self.ctx = ctx
        if definition.at_every_ms is not None and definition.at_every_ms <= 0:
            raise SiddhiAppCreationError(
                f"trigger {definition.id!r}: interval must be positive")
        self.cron: Optional[CronSchedule] = (
            CronSchedule(definition.at_cron) if definition.at_cron else None)
        #: next due fire (epoch ms); None until started / for start-only triggers
        self.next_fire_ms: Optional[int] = None
        self._started = False

    def start(self, now_ms: int) -> None:
        self._started = True
        td = self.definition
        if td.at_start:
            self._fire(now_ms)
        if td.at_every_ms is not None:
            self.next_fire_ms = now_ms + td.at_every_ms
        elif self.cron is not None:
            self.next_fire_ms = self.cron.next_fire_ms(now_ms)

    def poll(self, now_ms: int, max_fires: int = 10_000) -> int:
        """Fire every due time <= now; returns number of fires staged."""
        if not self._started or self.next_fire_ms is None:
            return 0
        fired = 0
        td = self.definition
        while self.next_fire_ms is not None and self.next_fire_ms <= now_ms:
            self._fire(self.next_fire_ms)
            fired += 1
            if td.at_every_ms is not None:
                self.next_fire_ms += td.at_every_ms
            else:
                self.next_fire_ms = self.cron.next_fire_ms(self.next_fire_ms)
            if fired >= max_fires:  # clock jumped far forward; don't spin
                break
        return fired

    def _fire(self, ts_ms: int) -> None:
        self.junction.send_row(ts_ms, (ts_ms,))

    def shutdown(self) -> None:
        self._started = False
        self.next_fire_ms = None
