"""Per-app shared services (reference: core/config/SiddhiAppContext.java:53).

The TPU build's context is much smaller: no thread pools or locks — execution
is single-controller and synchronous per micro-batch; state is functional. What
remains: the timestamp generator (wall clock vs playback virtual time,
reference core/util/timestamp/TimestampGeneratorImpl.java:31), the extension
registry snapshot, batching knobs, and statistics.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..extension.registry import Registry
from . import dtypes


class TimestampGenerator:
    """Wall-clock by default; in playback mode (@app:playback) time is driven
    by event timestamps (reference TimestampGeneratorImpl.java:78-131)."""

    def __init__(self, playback: bool = False,
                 playback_increment_ms: int = 0) -> None:
        self.playback = playback
        self.playback_increment_ms = playback_increment_ms
        self._last_event_ts: Optional[int] = None

    def current_time(self) -> int:
        if self.playback:
            if self._last_event_ts is None:
                return 0
            return self._last_event_ts + self.playback_increment_ms
        return int(time.time() * 1000)

    def observe_event_time(self, ts: int) -> None:
        if self._last_event_ts is None or ts > self._last_event_ts:
            self._last_event_ts = ts


@dataclass
class Statistics:
    """Per-app counters (reference: core/util/statistics/ — codahale registry;
    here simple host counters; per-query latency tracked in QueryRuntime)."""

    enabled: bool = False
    level: str = "OFF"  # OFF | BASIC | DETAIL
    events_in: dict = field(default_factory=dict)  # stream -> count
    events_out: dict = field(default_factory=dict)
    batches: dict = field(default_factory=dict)
    query_latency_ns: dict = field(default_factory=dict)  # query -> (total, count)

    def track_in(self, stream_id: str, n: int) -> None:
        if self.enabled:
            self.events_in[stream_id] = self.events_in.get(stream_id, 0) + n

    def track_batch(self, stream_id: str) -> None:
        if self.enabled:
            self.batches[stream_id] = self.batches.get(stream_id, 0) + 1

    def track_latency(self, query: str, ns: int) -> None:
        if self.enabled:
            t, c = self.query_latency_ns.get(query, (0, 0))
            self.query_latency_ns[query] = (t + ns, c + 1)

    def report(self) -> dict:
        out = {"events_in": dict(self.events_in), "batches": dict(self.batches)}
        out["query_latency_ms"] = {
            q: (t / c / 1e6 if c else 0.0)
            for q, (t, c) in self.query_latency_ns.items()}
        return out


@dataclass
class SiddhiAppContext:
    name: str
    registry: Registry
    timestamp_generator: TimestampGenerator
    batch_size: int = 0  # 0 = dtypes.config.default_batch_size
    group_capacity: int = 0
    statistics: Statistics = field(default_factory=Statistics)
    playback: bool = False
    #: root runtime back-reference (set by SiddhiAppRuntime)
    runtime: object = None
    #: app-global string interning table shared by every codec (stream, table,
    #: window, query output) so dictionary codes are consistent app-wide
    global_strings: object = None

    @property
    def effective_batch_size(self) -> int:
        return self.batch_size or dtypes.config.default_batch_size

    @property
    def effective_group_capacity(self) -> int:
        return self.group_capacity or dtypes.config.default_group_capacity
