"""Per-app shared services (reference: core/config/SiddhiAppContext.java:53).

The TPU build's context is much smaller: no thread pools or locks — execution
is single-controller and synchronous per micro-batch; state is functional. What
remains: the timestamp generator (wall clock vs playback virtual time,
reference core/util/timestamp/TimestampGeneratorImpl.java:31), the extension
registry snapshot, batching knobs, and statistics.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..extension.registry import Registry
from ..util.locks import named_lock, named_rlock
from . import dtypes

log = logging.getLogger("siddhi_tpu.stats")


class TimestampGenerator:
    """Wall-clock by default; in playback mode (@app:playback) time is driven
    by event timestamps (reference TimestampGeneratorImpl.java:78-131).

    @app:playback(idle.time='100 millisecond', increment='2 sec'): when the
    stream goes idle, `advance_idle()` bumps the virtual clock by `increment`
    (the reference runs this on a scheduled thread every idle.time; here the
    single-controller calls it from SiddhiAppRuntime.heartbeat)."""

    def __init__(self, playback: bool = False,
                 playback_increment_ms: int = 0,
                 idle_time_ms: Optional[int] = None) -> None:
        self.playback = playback
        self.playback_increment_ms = playback_increment_ms
        self.idle_time_ms = idle_time_ms
        self._observe_lock = named_lock("app.timestamp")
        self._last_event_ts: Optional[int] = None

    def current_time(self) -> int:
        if self.playback:
            if self._last_event_ts is None:
                return 0
            return self._last_event_ts
        return int(time.time() * 1000)

    def observe_event_time(self, ts: int) -> None:
        # multiple producer threads race this check-then-set; the watermark
        # must never regress (time-window expiry ordering depends on it)
        with self._observe_lock:
            if self._last_event_ts is None or ts > self._last_event_ts:
                self._last_event_ts = ts

    def advance_idle(self) -> int:
        """Playback idle bump: virtual clock += increment. Returns new time."""
        if self.playback and self._last_event_ts is not None:
            self._last_event_ts += self.playback_increment_ms
        return self.current_time()


@dataclass
class Statistics:
    """Per-app metrics (reference: core/util/statistics/ —
    SiddhiStatisticsManager.java:35-55 codahale registry, ThroughputTracker,
    LatencyTracker markIn/markOut, MemoryUsageTracker with deep object sizing,
    BufferedEventsTracker; levels OFF/BASIC/DETAIL, metrics/Level.java).

    BASIC: per-stream throughput + batch counts. DETAIL adds per-query latency
    and on-demand device-state memory (pytree nbytes replaces the reference's
    ObjectSizeCalculator walk) + staged-buffer depth (the Disruptor backlog
    analogue). Runtime-switchable via SiddhiAppRuntime.set_statistics_level
    (reference: SiddhiAppRuntimeImpl.setStatisticsLevel:868)."""

    enabled: bool = False
    level: str = "OFF"  # OFF | BASIC | DETAIL
    events_in: dict = field(default_factory=dict)  # stream -> count
    events_out: dict = field(default_factory=dict)
    batches: dict = field(default_factory=dict)
    query_latency_ns: dict = field(default_factory=dict)  # query -> (total, count)
    #: per-query XLA compile counter (query -> count) and the batch lane
    #: widths that triggered each trace (query -> [width, ...]). Tracked
    #: REGARDLESS of level: a recompile storm (unbounded shapes hitting a
    #: jitted step) stalls the pipeline for seconds per compile — it must be
    #: a visible metric, not a silent hang. Incremented at TRACE time from
    #: inside each runtime's step closure, so the count is exact per
    #: (query, shape-signature) executable.
    compiles: dict = field(default_factory=dict)
    compile_widths: dict = field(default_factory=dict)
    #: per-query step wall-time histogram: query -> {bucket_us: count} with
    #: power-of-two microsecond buckets (key = inclusive upper bound in us).
    #: DETAIL only — one bit_length per step.
    step_hist: dict = field(default_factory=dict)
    started_at: float = field(default_factory=time.time)
    #: capacity-overflow counters ("<runtime>.<structure>" -> lifetime rows
    #: dropped/overwritten/unresolved). Tracked regardless of level — silent
    #: capacity loss is a correctness signal, not a metric (SURVEY §7
    #: "overflow-to-host escape hatches"). Each counter warns once.
    overflow: dict = field(default_factory=dict)
    _overflow_warned: set = field(default_factory=set)
    #: fault-tolerance counters — tracked regardless of level, like overflow:
    #: a retried/dead-lettered/dropped event is a correctness signal operators
    #: must see without opting into metrics. sink_* keyed by stream id.
    sink_retries: dict = field(default_factory=dict)
    sink_dead_letters: dict = field(default_factory=dict)  # events stored
    sink_dropped: dict = field(default_factory=dict)  # events dropped (LOG)
    source_retries: dict = field(default_factory=dict)  # reconnect attempts
    recoveries: int = 0  # recover() completions
    wal_replayed: int = 0  # lifetime events re-sent by recover()
    shutdown_discarded: int = 0  # staged rows lost at shutdown()
    #: blue-green upgrade / historical-replay counters (core/upgrade.py) —
    #: tracked regardless of level: a swap or rollback is an operational
    #: event operators must see. cutover_pause_ms is the LAST swap's
    #: source-paused wall time (the headline "how long were we dark").
    upgrades: int = 0  # committed hot-swaps
    upgrade_rollbacks: int = 0  # failed swaps rolled back to v1
    upgrade_cutover_pause_ms: float = 0.0
    upgrade_wal_replayed: int = 0  # journal-tail events replayed into v2
    replay_runs: int = 0  # replay_wal() completions
    replay_events: int = 0  # lifetime events driven by replay_wal()
    #: overload-protection counters — tracked regardless of level, like the
    #: sink_* family: a dropped/diverted/paused event is a correctness signal.
    #: ingress_dropped is keyed stream -> {policy: rows} where policy is one
    #: of drop.new | drop.old | fault | block.timeout | source.pending.
    ingress_dropped: dict = field(default_factory=dict)
    bp_pauses: dict = field(default_factory=dict)  # stream -> pause() calls
    bp_resumes: dict = field(default_factory=dict)  # stream -> resume() calls
    queue_hwm: dict = field(default_factory=dict)  # stream -> max staged depth
    #: circuit-breaker counters, keyed by query name (state itself lives on
    #: the runtime's CircuitBreaker; report(runtime) merges both views)
    breaker_opens: dict = field(default_factory=dict)
    breaker_failures: dict = field(default_factory=dict)
    breaker_diverted: dict = field(default_factory=dict)  # rows diverted
    #: @app:eventTime rows diverted behind the watermark (kind="late"),
    #: keyed by stream — tracked regardless of level, like sink_*: a
    #: diverted row is a correctness signal, not a metric
    late_events: dict = field(default_factory=dict)
    #: one-retrace splice counters (core/shared.py splice_in/splice_out),
    #: keyed by kind: in | out | declined | failed — tracked regardless of
    #: level: a failed/declined splice is an operational event. The ms
    #: figure is the LAST successful splice's retrace+compile wall time.
    splices: dict = field(default_factory=dict)
    splice_retrace_ms: float = 0.0
    #: tenant device-time quota breaches, keyed by tenant id (core/tenant.py)
    tenant_breaches: dict = field(default_factory=dict)

    @property
    def detail(self) -> bool:
        return self.enabled and self.level == "DETAIL"

    def set_level(self, level: str) -> None:
        level = level.upper()
        if level not in ("OFF", "BASIC", "DETAIL"):
            raise ValueError(f"bad statistics level {level!r}")
        self.level = level
        self.enabled = level != "OFF"

    def track_in(self, stream_id: str, n: int) -> None:
        if self.enabled:
            self.events_in[stream_id] = self.events_in.get(stream_id, 0) + n

    def track_batch(self, stream_id: str) -> None:
        if self.enabled:
            self.batches[stream_id] = self.batches.get(stream_id, 0) + 1

    def track_latency(self, query: str, ns: int) -> None:
        if self.detail:
            t, c = self.query_latency_ns.get(query, (0, 0))
            self.query_latency_ns[query] = (t + ns, c + 1)
            bucket = 1 << max(ns // 1000, 1).bit_length()  # us, power of two
            h = self.step_hist.setdefault(query, {})
            h[bucket] = h.get(bucket, 0) + 1

    def track_compile(self, query: str, width: int) -> None:
        """One jitted-step TRACE (== one XLA compile) for `query` on a batch
        of `width` lanes. Called from inside the traced function body, so it
        fires exactly once per cached executable."""
        self.compiles[query] = self.compiles.get(query, 0) + 1
        self.compile_widths.setdefault(query, []).append(int(width))

    def track_sink_retry(self, stream_id: str) -> None:
        self.sink_retries[stream_id] = self.sink_retries.get(stream_id, 0) + 1

    def track_source_retry(self, stream_id: str) -> None:
        self.source_retries[stream_id] = \
            self.source_retries.get(stream_id, 0) + 1

    def track_dead_letter(self, stream_id: str, n: int) -> None:
        self.sink_dead_letters[stream_id] = \
            self.sink_dead_letters.get(stream_id, 0) + n

    def track_sink_drop(self, stream_id: str, n: int) -> None:
        self.sink_dropped[stream_id] = \
            self.sink_dropped.get(stream_id, 0) + n

    def track_ingress_drop(self, stream_id: str, policy: str, n: int) -> None:
        """Rows shed/diverted by a bounded junction (or a paused source's
        pending buffer) under `policy`. Exact by construction: every admission
        decision increments exactly one policy counter."""
        per = self.ingress_dropped.setdefault(stream_id, {})
        per[policy] = per.get(policy, 0) + n

    def track_pause(self, stream_id: str) -> None:
        self.bp_pauses[stream_id] = self.bp_pauses.get(stream_id, 0) + 1

    def track_resume(self, stream_id: str) -> None:
        self.bp_resumes[stream_id] = self.bp_resumes.get(stream_id, 0) + 1

    def track_queue_depth(self, stream_id: str, depth: int) -> None:
        if depth > self.queue_hwm.get(stream_id, 0):
            self.queue_hwm[stream_id] = depth

    def track_breaker_failure(self, query: str) -> None:
        self.breaker_failures[query] = self.breaker_failures.get(query, 0) + 1

    def track_breaker_open(self, query: str) -> None:
        self.breaker_opens[query] = self.breaker_opens.get(query, 0) + 1

    def track_breaker_divert(self, query: str, n: int) -> None:
        self.breaker_diverted[query] = self.breaker_diverted.get(query, 0) + n

    def track_splice(self, kind: str, retrace_ms: float = None) -> None:
        """kind: in | out | declined | failed. retrace_ms records the
        successful splice's trace+compile wall time (deploy latency)."""
        self.splices[kind] = self.splices.get(kind, 0) + 1
        if retrace_ms is not None:
            self.splice_retrace_ms = float(retrace_ms)

    def track_tenant_breach(self, tenant: str) -> None:
        self.tenant_breaches[tenant] = self.tenant_breaches.get(tenant, 0) + 1

    def track_late(self, stream_id: str, n: int) -> None:
        """Rows diverted to the ErrorStore as kind="late" (event time behind
        the watermark). Exact by construction: every gated row either
        delivers, buffers, or increments this once."""
        self.late_events[stream_id] = self.late_events.get(stream_id, 0) + n

    def track_recovery(self, replayed: int) -> None:
        self.recoveries += 1
        self.wal_replayed += replayed

    def track_shutdown_discard(self, n: int) -> None:
        self.shutdown_discarded += n

    def track_upgrade(self, cutover_pause_ms: float, replayed: int,
                      rollback: bool = False) -> None:
        if rollback:
            self.upgrade_rollbacks += 1
            return
        self.upgrades += 1
        self.upgrade_cutover_pause_ms = float(cutover_pause_ms)
        self.upgrade_wal_replayed += replayed

    def track_replay(self, events: int) -> None:
        self.replay_runs += 1
        self.replay_events += events

    def record_overflow(self, name: str, n: int) -> None:
        """Register a lifetime overflow counter reading; warns ONCE per
        counter the first time it goes positive (an @OnError-style signal —
        results past this point may be missing rows)."""
        if n <= 0:
            self.overflow.pop(name, None)
            return
        self.overflow[name] = n
        if name not in self._overflow_warned:
            self._overflow_warned.add(name)
            import warnings
            warnings.warn(
                f"{name}: {n} rows exceeded a fixed device capacity and "
                "were dropped/overwritten — results may be missing rows; "
                "raise the relevant capacity (see Statistics.report()"
                "['overflow'])", stacklevel=3)

    def reset(self) -> None:
        self.events_in.clear()
        self.events_out.clear()
        self.batches.clear()
        self.query_latency_ns.clear()
        self.compiles.clear()
        self.compile_widths.clear()
        self.step_hist.clear()
        self.overflow.clear()
        self.sink_retries.clear()
        self.sink_dead_letters.clear()
        self.sink_dropped.clear()
        self.source_retries.clear()
        self.ingress_dropped.clear()
        self.bp_pauses.clear()
        self.bp_resumes.clear()
        self.queue_hwm.clear()
        self.breaker_opens.clear()
        self.breaker_failures.clear()
        self.breaker_diverted.clear()
        self.late_events.clear()
        self.splices.clear()
        self.splice_retrace_ms = 0.0
        self.tenant_breaches.clear()
        self.recoveries = 0
        self.wal_replayed = 0
        self.shutdown_discarded = 0
        self.upgrades = 0
        self.upgrade_rollbacks = 0
        self.upgrade_cutover_pause_ms = 0.0
        self.upgrade_wal_replayed = 0
        self.replay_runs = 0
        self.replay_events = 0
        self.started_at = time.time()

    def report(self, runtime=None) -> dict:
        elapsed = max(time.time() - self.started_at, 1e-9)
        if runtime is not None:
            runtime.collect_overflow()
        out = {
            "level": self.level,
            "uptime_seconds": elapsed,
            "events_in": dict(self.events_in),
            "batches": dict(self.batches),
            "throughput_eps": {s: n / elapsed for s, n in self.events_in.items()},
            "overflow": dict(self.overflow),
            # always reported: a growing count under a steady workload is
            # the recompile-storm signature (see track_compile)
            "compiles": dict(self.compiles),
            "compile_widths": {q: list(w)
                               for q, w in self.compile_widths.items()},
            # fault-tolerance counters (always, like overflow: silent loss
            # is a correctness signal, not a metric)
            "sink_retries": dict(self.sink_retries),
            "sink_dead_letters": dict(self.sink_dead_letters),
            "sink_dropped": dict(self.sink_dropped),
            "source_retries": dict(self.source_retries),
            # overload protection (always, same rationale): drops by policy,
            # backpressure pause/resume counts, staged-depth high-watermarks
            "ingress_dropped": {s: dict(d)
                                for s, d in self.ingress_dropped.items()},
            "backpressure": {
                "pauses": dict(self.bp_pauses),
                "resumes": dict(self.bp_resumes),
                "queue_hwm": dict(self.queue_hwm),
            },
            "recovery": {
                "recoveries": self.recoveries,
                "wal_replayed": self.wal_replayed,
                "shutdown_discarded": self.shutdown_discarded,
            },
            "upgrade": {
                "upgrades": self.upgrades,
                "rollbacks": self.upgrade_rollbacks,
                "cutover_pause_ms": self.upgrade_cutover_pause_ms,
                "wal_tail_replayed": self.upgrade_wal_replayed,
            },
            "replay": {
                "runs": self.replay_runs,
                "events": self.replay_events,
            },
            # one-retrace membership churn (core/shared.py splice_in/out):
            # always reported — a failed or declined splice means a deploy
            # fell back to standalone dispatch, an operational event
            "splices": {
                "counts": dict(self.splices),
                "last_retrace_ms": self.splice_retrace_ms,
                "tenant_breaches": dict(self.tenant_breaches),
            },
            # always-on, like overflow: a serialized ingress pipeline is a
            # performance regression operators must see in production.
            # Populated below from the live pipelines (ring depth HWM,
            # worker utilization, h2d overlap ratio, per-stage wall time).
            "ingress_pipeline": {},
        }
        if runtime is not None:
            for sid, j in runtime.junctions.items():
                p = getattr(j, "_pipeline", None)
                if p is not None:
                    out["ingress_pipeline"][sid] = p.stats_snapshot()
        if runtime is not None:
            wal = getattr(runtime, "wal", None)
            if wal is not None:
                out["recovery"]["wal_appended"] = wal.appended_events
                out["recovery"]["wal_records"] = wal.appended_records
            es = getattr(runtime.ctx, "error_store", None)
            if es is not None and hasattr(es, "dropped_count"):
                out["error_store"] = {
                    "entries": len(es.load(runtime.app.name)),
                    "dropped_error_entries":
                        es.dropped_count(runtime.app.name),
                }
            wms = {}
            for sid, j in runtime.junctions.items():
                et = getattr(j, "_et", None)
                if et is not None:
                    wms[sid] = et.snapshot()
            if wms:
                # event-time gates (core/event_time.py): watermark position,
                # reorder-buffer depth, and the exactly-once accounting
                # (admitted == released + late + buffered)
                out["watermarks"] = wms
            if self.late_events:
                out["late_events"] = dict(self.late_events)
            breakers = {}
            for name, qr in runtime.query_runtimes.items():
                br = getattr(qr, "breaker", None)
                if br is None:
                    continue
                breakers[name] = {
                    **br.snapshot(),
                    "failures": self.breaker_failures.get(name, 0),
                    "diverted_rows": self.breaker_diverted.get(name, 0),
                }
            if breakers:
                out["breakers"] = breakers
            tenants = getattr(runtime, "tenants", None)
            if tenants is not None:
                # per-tenant quota accounting (core/tenant.py): rolling
                # device-ms spend vs budget, breach counts, diverted rows
                out["tenants"] = tenants.report(self)
            tele = getattr(runtime.ctx, "telemetry", None)
            if tele is not None:
                # always-on (independent of statistics level): the batch
                # tracer's per-stage/per-query percentiles and the worst-N
                # slow-batch exemplars — same histograms /metrics exports
                out["latency"] = tele.latency_snapshot()
                out["slow_batches"] = tele.slow_batches()
            eng = getattr(runtime, "slo_engine", None)
            if eng is not None:
                # declared objectives + both burn windows + breach state
                # (telemetry/slo.py; same data GET /slo serves)
                out["slo"] = eng.report()
            rec = getattr(runtime.ctx, "recorder", None)
            if rec is not None:
                out["recorder"] = rec.report()
            opt = getattr(runtime, "optimizer_report", None)
            if opt is not None:
                # multi-query shared execution (core/shared.py): fused-group
                # inventory from creation time, plus the live compile-savings
                # number — each group compile replaces len(members) per-query
                # compiles of the same shape
                groups = getattr(runtime, "shared_groups", ())
                out["optimizer"] = {
                    **opt,
                    "compiles_avoided": sum(
                        self.compiles.get(g.name, 0) * (len(g.members) - 1)
                        for g in groups),
                }
            else:
                out["optimizer"] = {"enabled": False}
            try:
                # static cost prediction vs live telemetry (analysis/cost.py
                # + measure_runtime_state_bytes): the calibration pair that
                # tools/cost_calibrate.py gates on in CI
                from ..analysis.cost import measure_runtime_state_bytes
                pred = runtime.cost_report
                live = measure_runtime_state_bytes(runtime)
                live_bytes = sum(live.values())
                live_compiles = sum(self.compiles.values())
                out["cost"] = {
                    "predicted_state_bytes": pred["predicted_state_bytes"],
                    "live_state_bytes": live_bytes,
                    "state_ratio": (live_bytes /
                                    pred["predicted_state_bytes"]
                                    if pred["predicted_state_bytes"] else
                                    None),
                    "predicted_compiles": pred["predicted_compiles"],
                    "live_compiles": live_compiles,
                    "exact": pred["exact"],
                    "dominant": pred.get("dominant"),
                    "budget": pred.get("budget"),
                    "live_elements": live,
                }
            except Exception:  # advisory — never break a stats report
                log.debug("cost section crashed", exc_info=True)
            lint = getattr(runtime, "lint_report", None)
            if lint is not None:
                # what the SIDDHI_LINT gate saw at creation: rule counts +
                # severity totals (full diagnostics via the lint CLI/REST)
                out["lint"] = {
                    "valid": not lint.has_errors,
                    "errors": len(lint.errors),
                    "warnings": len(lint.warnings),
                    "rules": lint.rule_counts(),
                }
        from ..util import locks as _locks
        if _locks.checks_enabled():
            # lockdep findings (util/locks.py): acquisition-order cycles +
            # held-across-blocking hazards, only under SIDDHI_LOCK_CHECKS=1
            out["lockdep"] = _locks.lockdep_report()
        if self.detail:
            out["query_latency_ms"] = {
                q: (t / c / 1e6 if c else 0.0)
                for q, (t, c) in self.query_latency_ns.items()}
            out["step_time_hist_us"] = {
                q: dict(sorted(h.items())) for q, h in self.step_hist.items()}
            if runtime is not None:
                out["state_memory_bytes"] = {
                    name: _pytree_nbytes(qr.state)
                    for name, qr in runtime.query_runtimes.items()}
                out["buffered_events"] = {
                    sid: len(j._staged_rows) + len(j._tap_queue)
                    for sid, j in runtime.junctions.items()}
        return out


def _pytree_nbytes(tree) -> int:
    """Deep device-state size — replaces the reference's
    ObjectSizeCalculator (core/util/statistics/memory/)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += getattr(leaf, "nbytes", 0) or 0
    return total


@dataclass
class SiddhiAppContext:
    name: str
    registry: Registry
    timestamp_generator: TimestampGenerator
    batch_size: int = 0  # 0 = dtypes.config.default_batch_size
    group_capacity: int = 0
    #: jax.sharding.Mesh for SPMD partition execution (None = host routing)
    mesh: object = None
    partition_capacity: int = 0  # key slots for mesh partitions; 0 = default
    statistics: Statistics = field(default_factory=Statistics)
    playback: bool = False
    #: root runtime back-reference (set by SiddhiAppRuntime)
    runtime: object = None
    #: app-global string interning table shared by every codec (stream, table,
    #: window, query output) so dictionary codes are consistent app-wide
    global_strings: object = None
    #: single-controller gate: async feeder threads and user-thread
    #: flush/heartbeat/query serialize device work through this RLock (the
    #: role of the reference's ThreadBarrier + per-query locks)
    controller_lock: object = field(
        default_factory=lambda: named_rlock("app.controller"))
    #: async stream-callback decode (create_siddhi_app_runtime(...,
    #: async_callbacks=True)): device→host readback + Event decode run on a
    #: dedicated worker so the controller thread never blocks on the
    #: device→host round trip (~100 ms through a tunneled TPU). Opt-in
    #: because it changes visible semantics: flush() may return before
    #: callbacks ran — runtime.drain() is the barrier.
    async_callbacks: bool = False
    decoder: object = None
    #: telemetry.AppTelemetry — always-on metrics registry + batch tracer
    #: (set by SiddhiAppRuntime before any junction is built)
    telemetry: object = None
    #: telemetry.FlightRecorder — always-on evidence ring + anomaly-triggered
    #: diagnostic bundles (set by SiddhiAppRuntime after build)
    recorder: object = None
    #: event_time.EventTimeConfig parsed from @app:eventTime (None = arrival
    #: time); read by query runtimes (window lateness) and ingress gates
    event_time: object = None
    #: device-resident supersteps (@app:superstep(k=) / SIDDHI_SUPERSTEP_K):
    #: the async ingress feeder stages this many ring slots into one [K, B]
    #: chunk and runs the query chain as a single lax.scan dispatch
    #: (core/superstep.py). 1 = off; ineligible plans fall back loudly.
    superstep_k: int = 1
    #: tenant.TenantRegistry when the app declares @app:tenant quotas —
    #: the ALWAYS-ON device-time meter both dispatch paths feed (unlike
    #: track_latency it is not gated on statistics detail, because quota
    #: enforcement reads it)
    tenant_meter: object = None

    @property
    def effective_batch_size(self) -> int:
        return self.batch_size or dtypes.config.default_batch_size

    @property
    def effective_group_capacity(self) -> int:
        return self.group_capacity or dtypes.config.default_group_capacity

    @property
    def effective_partition_capacity(self) -> int:
        return self.partition_capacity or dtypes.config.default_partition_capacity
