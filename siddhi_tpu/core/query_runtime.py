"""Query planner & runtime — AST query → one jitted step function.

Reference counterpart: core/util/parser/QueryParser.java:70 builds a chain of
Processor objects walked per event (ProcessStreamReceiver → FilterProcessor →
WindowProcessor → QuerySelector → OutputRateLimiter → OutputCallback,
call stack SURVEY §3.2). The TPU build collapses that chain into ONE pure
function per query:

    step(state, batch, now) -> (state', out_batch)

traced once and jit-compiled; filters become masks, the window emits a typed
chunk, the selector runs grouped scans — all fused by XLA into a handful of
kernels per micro-batch. State is a pytree (window rings + group tables),
donated on each call so device buffers are reused in place.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..errors import SiddhiAppCreationError
from ..extension.registry import ExtensionKind, Registry
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..ops.selector import CompiledSelector
from ..ops.window_factories import WindowFactory
from ..ops.windows import PassThroughWindow, WindowOp
from ..query_api.definition import AttributeType, StreamDefinition, Attribute
from ..query_api.execution import (
    OutputAction,
    OutputEventType,
    Query,
    SingleInputStream,
)
from ..query_api.expression import Constant, Expression, Variable
from . import dtypes
from .context import SiddhiAppContext
from .event import Event, EventBatch, EventType, StreamCodec
from .stream import Receiver, StreamJunction


class QueryCallback:
    """Reference: core/query/output/callback/QueryCallback.java:37 — receives
    (timestamp, inEvents, removeEvents) per emission chunk."""

    def receive(self, timestamp: int, in_events, remove_events) -> None:
        raise NotImplementedError


class FunctionQueryCallback(QueryCallback):
    def __init__(self, fn):
        self.fn = fn

    def receive(self, timestamp: int, in_events, remove_events) -> None:
        self.fn(timestamp, in_events, remove_events)


def eval_constant(expr: Expression):
    """Evaluate a compile-time-constant window/extension parameter (sizes,
    periods). Variables pass through as AST nodes — some windows take
    attribute references (externalTime's tsAttr, sort keys)."""
    if isinstance(expr, Constant):
        return expr.value
    if isinstance(expr, Variable):
        return expr
    raise SiddhiAppCreationError(f"expected a constant parameter, got {expr!r}")


@dataclass
class QueryPlanInputs:
    definition: StreamDefinition
    codec: StreamCodec
    frame_ref: str


def aot_warm(jit_fn, *args) -> None:
    """Populate `jit_fn`'s dispatch cache for `args`' shape signature
    WITHOUT executing it — jax (>= 0.4.31) shares `lower().compile()`
    executables with the normal call path, so the next real call is a pure
    cache hit. Warmup therefore has no step side effects, cannot touch live
    state, and never runs host callbacks (executing a step during warmup
    can deadlock jax's CPU pure_callback path on small hosts)."""
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
        args)
    jit_fn.lower(*abstract).compile()


def _selects_aggregates(selector, registry) -> bool:
    """True if any select item contains an aggregator call — the same
    detection CompiledSelector performs, needed BEFORE the window is built
    (full-window snapshots change the window's expired-lane emission)."""
    from ..extension.registry import ExtensionKind
    from ..ops.aggregators import AggregatorFactory
    from ..query_api.expression import AttributeFunction, Expression

    def walk(e) -> bool:
        if isinstance(e, AttributeFunction):
            f = registry.lookup(ExtensionKind.AGGREGATOR, e.namespace, e.name)
            if isinstance(f, AggregatorFactory):
                return True
        for a in ("left", "right", "expression"):
            sub = getattr(e, a, None)
            if isinstance(sub, Expression) and walk(sub):
                return True
        return any(isinstance(p, Expression) and walk(p)
                   for p in getattr(e, "parameters", ()) or ())

    return any(walk(a.expression) for a in selector.attributes)


class QueryRuntime(Receiver):
    """Runtime for a single-input-stream query (joins/patterns have their own
    runtimes). Subscribes to the input junction; publishes to the output
    junction and/or query callbacks."""

    def __init__(
        self,
        query: Query,
        ctx: SiddhiAppContext,
        input_junction: StreamJunction,
        registry: Registry,
        name: Optional[str] = None,
        tables: Optional[dict] = None,
    ) -> None:
        assert isinstance(query.input_stream, SingleInputStream)
        self.query = query
        self.ctx = ctx
        self.name = name or query.name or f"query_{id(self)}"
        self.registry = registry
        self.input_junction = input_junction
        # per-query circuit breaker (@breaker(threshold=..., window=...,
        # cooldown=...)) — the input junction consults it around every
        # on_batch dispatch (core/breaker.py); None = failures propagate
        # per @OnError exactly as before
        from .breaker import breaker_from_annotations
        self.breaker = breaker_from_annotations(query, name=self.name)
        self.callbacks: list[QueryCallback] = []
        self.output_junction: Optional[StreamJunction] = None
        self.table_executor = None  # set by app runtime for table CRUD outputs
        self.tables = tables or {}
        # tables referenced by `in Table` conditions: their states become step
        # arguments (contents must not be baked into the trace as constants)
        self.dep_tables = sorted(
            tid for tid in _collect_in_sources(query) if tid in self.tables)
        # tables whose `in` conditions carry an index-eligible equality:
        # only these pay the (lazy) sorted-index rebuild per mutated batch
        self._index_tables = _collect_eq_probe_tables(query, self.tables)
        #: cached @store tables probed by `T.attr == <stream expr>` `in`
        #: conditions: once the store outgrows the cache, on_batch pre-warms
        #: the cache with the batch's probe values (store read-through,
        #: reference AbstractQueryableRecordTable.java:207-238). Populated
        #: after the resolver exists (see below).
        self._in_fallbacks: dict = {}

        in_stream = query.input_stream
        definition = input_junction.definition
        self.frame_ref = in_stream.reference_id
        self.codec = input_junction.codec

        # --- type resolver over the input frame ---
        attr_types = {a.name: a.type for a in definition.attributes
                      if a.type != AttributeType.OBJECT}
        frames = {self.frame_ref: attr_types}
        if self.frame_ref != definition.id:
            frames[definition.id] = attr_types
        codecs = {self.frame_ref: self.codec, definition.id: self.codec}
        # `in Table` conditions reference table attributes (T.attr): add the
        # dep tables' frames so their inner conditions resolve
        for tid in self.dep_tables:
            frames[tid] = dict(self.tables[tid].attr_types)
            codecs[tid] = self.tables[tid].codec
        # unionSet-projection provenance (Attribute.set_projection markers on
        # upstream auto-defined outputs; table markers set at wiring time):
        # the only columns sizeOfSet() accepts downstream
        sp = {a.name for a in definition.attributes
              if getattr(a, "set_projection", False)}
        set_projections = {}
        if sp:
            set_projections[self.frame_ref] = sp
            set_projections[definition.id] = sp
        for tid in self.dep_tables:
            tsp = getattr(self.tables[tid], "set_projection_attrs", None)
            if tsp:
                set_projections[tid] = set(tsp)
        self.resolver = TypeResolver(frames, self.frame_ref, codecs,
                                     set_projections)

        # --- filters ---
        self.filters = [compile_expression(f, self.resolver, registry)
                        for f in in_stream.handlers.filters]
        for f in self.filters:
            if f.type != AttributeType.BOOL:
                raise SiddhiAppCreationError("filter must be boolean")

        self._in_fallbacks, in_nofallback = _collect_in_fallbacks(
            query, self.tables, self.resolver, registry)
        for tid in self._in_fallbacks:
            self.tables[tid]._probe_fallback_ready = True
        for tid in in_nofallback:
            self.tables[tid]._probe_nofallback = True

        # --- stream functions (reference: StreamFunctionProcessor SPI) ---
        # each appends computed columns to the frame; later handlers and the
        # selector see the extended schema. attr_types is the same dict the
        # resolver reads, so extending it here extends name resolution too.
        def _compile_stream_fns(handlers):
            from ..ops.stream_functions import StreamFunctionFactory
            out = []
            for h in handlers:
                factory = registry.require(
                    ExtensionKind.STREAM_FUNCTION, h.namespace, h.name)
                assert isinstance(factory, StreamFunctionFactory)
                arg_ex = [compile_expression(p, self.resolver, registry)
                          for p in h.parameters]
                spec = factory.make(tuple(a.type for a in arg_ex))
                for n, t in spec.new_attrs:
                    attr_types[n] = t
                out.append((spec, arg_ex))
            return out

        self.pre_window_fns = _compile_stream_fns(
            in_stream.handlers.pre_window_functions)
        self.post_window_fns = _compile_stream_fns(
            in_stream.handlers.post_window_functions)
        self.post_filters = [compile_expression(f, self.resolver, registry)
                             for f in in_stream.handlers.post_window_filters]

        # --- window (layout includes stream-function columns) ---
        batch_cap = input_junction.batch_size
        from ..ops.windows import make_layout
        layout = make_layout({a.name: a.type for a in definition.attributes
                              if a.type != AttributeType.OBJECT})
        for spec, _ in self.pre_window_fns:
            for n, t in spec.new_attrs:
                layout[n] = dtypes.device_dtype(t)
                layout.attr_types[n] = t
        # expired-lane emission (reference: outputExpectsExpiredEvents wiring,
        # QueryParser): batch windows only materialize EXPIRED lanes when the
        # query output wants them (`insert all/expired events`) — a CURRENT
        # insert halves the emission chunk the selector sorts. Sliding windows
        # ignore this flag: their expired lanes drive aggregator removal.
        expired_on = query.output_stream.event_type != OutputEventType.CURRENT
        # full-window snapshot (non-aggregated, ungrouped `output snapshot`):
        # the limiter pops its FIFO ring on EXPIRED lanes, so batch windows
        # must materialize them even for CURRENT-only output. The SAME flag
        # later selects the limiter, so the two decisions cannot diverge.
        from ..query_api.execution import OutputRateType
        self._selects_aggs = _selects_aggregates(query.selector, registry)
        # grouped non-aggregated queries snapshot full window contents too
        # (reference GroupByPerSnapshotOutputRateLimiter emits per-group
        # event lists — concatenated, that is every window row)
        self._snapshot_full_window = (
            query.output_rate is not None
            and query.output_rate.type == OutputRateType.SNAPSHOT
            and not self._selects_aggs)
        if self._snapshot_full_window:
            expired_on = True
        wh = in_stream.handlers.window
        if wh is not None:
            factory = registry.require(ExtensionKind.WINDOW, wh.namespace, wh.name)
            assert isinstance(factory, WindowFactory)
            params = [eval_constant(p) for p in wh.parameters]
            registry.validate_params(ExtensionKind.WINDOW, wh.namespace,
                                     wh.name, params, what="window")
            self.window: WindowOp = factory.make(layout, batch_cap, params, expired_on)
            et = getattr(ctx, "event_time", None)
            if (et is not None and et.lateness_ms
                    and getattr(self.window, "ts_attr", None) is not None):
                # @app:eventTime + externalTime(Batch): watermark-driven
                # emission — the device watermark trails max-seen by the
                # allowed lateness so panes stay open for rows the ingress
                # gate still buffers. Set BEFORE first trace (static attr).
                self.window.lateness_ms = int(et.lateness_ms)
        else:
            self.window = PassThroughWindow(layout, batch_cap)
        # ExpressionWindow shares SlidingState + FIFO suffix semantics, so
        # the removal-capable extrema path (and the grouped-min rejection)
        # applies to it identically
        self.is_sliding_window = wh is not None and type(self.window).__name__ in (
            "SlidingWindow", "ExpressionWindow", "GeneralExpressionWindow")

        # --- selector ---
        select_all = [(a.name, a.type) for a in definition.attributes
                      if a.type != AttributeType.OBJECT]
        for spec, _ in (*self.pre_window_fns, *self.post_window_fns):
            for n, t in spec.new_attrs:
                if n not in dict(select_all):
                    select_all.append((n, t))
        self.selector = CompiledSelector(
            query.selector, self.resolver, registry,
            ctx.effective_group_capacity, self.frame_ref,
            select_all_attrs=select_all,
            sliding_window=self.is_sliding_window)
        if self.selector.extrema_plan:
            # the range-query extrema path reads WINDOW contents; shapes
            # where window membership diverges from what the aggregator may
            # see are rejected rather than silently diverging
            if self.post_filters:
                raise SiddhiAppCreationError(
                    "min()/max() over a sliding window cannot combine with a "
                    "post-window filter (filtered rows remain in the window); "
                    "filter before the window instead")
            if getattr(self.window, "is_delay", False):
                raise SiddhiAppCreationError(
                    "min()/max() over #window.delay is not supported "
                    "(delay re-emits expired lanes as arrivals)")

        # --- output stream definition ---
        # forwarded raw-unionSet slots carry the set-size projection with a
        # provenance marker so ONLY they satisfy downstream sizeOfSet()
        self.output_attributes = tuple(
            Attribute(name, t,
                      set_projection=name in self.selector.host_set_slots)
            for name, t in self.selector.out_types.items())
        self.output_definition = StreamDefinition(
            id=query.output_stream.target_id or f"{self.name}_out",
            attributes=self.output_attributes)
        self.output_codec = self._build_output_codec()

        # --- output rate limiter ---
        from ..ops.ratelimit import make_rate_limiter
        out_layout = {n: dtypes.device_dtype(t)
                      for n, t in self.selector.out_types.items()
                      if t != AttributeType.OBJECT}  # host-only slots
        from ..ops.windows import (LengthBatchWindow, SlidingWindow,
                                   TimeBatchWindow, WindowOp as _WindowOp)
        fifo = isinstance(self.window,
                          (SlidingWindow, LengthBatchWindow, TimeBatchWindow))
        # non-FIFO windows with a findable surface (sort/session/frequent/
        # cron/hopping): snapshots read the ring's live set directly
        findable = type(self.window).contents is not _WindowOp.contents \
            and not isinstance(self.window, PassThroughWindow)
        self.rate_limiter = make_rate_limiter(
            query.output_rate, out_layout, self.window.chunk_width,
            grouped=bool(query.selector.group_by),
            group_capacity=ctx.effective_group_capacity,
            fifo_window=fifo and self._snapshot_full_window,
            has_aggregates=self._selects_aggs,
            window_capacity=getattr(self.window, "C", 0),
            contents_window=findable and self._snapshot_full_window)
        from ..ops.ratelimit import (ContentsSnapshotLimiter,
                                     GroupedSnapshotLimiter)
        if isinstance(self.rate_limiter, GroupedSnapshotLimiter):
            # the limiter retains one row per group: have the selector ride
            # each lane's group slot on a pseudo-column (set before tracing)
            self.selector.expose_group_slot = True
        if isinstance(self.rate_limiter, ContentsSnapshotLimiter):
            if self.post_window_fns or self.post_filters:
                raise SiddhiAppCreationError(
                    "`output snapshot` over a non-FIFO window cannot combine "
                    "with post-window functions/filters (snapshots re-project "
                    "the raw window contents); apply them before the window")
            if query.selector.order_by or query.selector.limit is not None \
                    or query.selector.offset is not None:
                raise SiddhiAppCreationError(
                    "`output snapshot` over a non-FIFO window cannot combine "
                    "with order by / limit / offset (snapshots re-emit the "
                    "whole live window set)")

        # --- shape-bucketed dispatch eligibility ---
        # the junction pads partial batches to power-of-two lane buckets;
        # a query whose whole step derives lane counts from the batch
        # (shape-polymorphic window, no ring-vs-chunk extrema coupling)
        # consumes them directly, compiling once per ladder rung. Everything
        # else pads back to the planned capacity in on_batch (one compile).
        self._batch_cap = input_junction.batch_size
        self._bucket_ok = (self.window.shape_polymorphic
                          and not self.selector.extrema_plan)

        # --- the jitted step ---
        self._step = jax.jit(self._make_step(), donate_argnums=(0,))
        self.state = self._init_state()
        #: set by core/shared.py when this query's step body is traced into
        #: a SharedStepGroup's fused jit: the junction then delivers to the
        #: group (this runtime's own _step stays cold), but state/callbacks/
        #: output wiring remain per-query, so persistence and upgrade see
        #: exactly the unfused layout
        self._fused_group = None
        self._has_custom_aggs = any(
            spec.custom_scan is not None for _, spec, _ in self.selector.agg_specs)
        self._batches_seen = 0
        self._capacity_warned = False
        self._capacity_pressure = False
        self._snapshot_warned = False
        self._last_compacted_live: dict[int, int] = {}
        #: time-driven windows need heartbeats to flush expirations
        from ..ops.windows import window_has_time_semantics
        self.has_time_semantics = (
            window_has_time_semantics(self.window)
            or self.rate_limiter.has_time_semantics)

    # ----------------------------------------------------------------- plan

    def _build_output_codec(self) -> StreamCodec:
        """String codes are app-global (ctx.global_strings), so output string
        columns decode directly regardless of which source attr produced them."""
        return StreamCodec(self.output_definition, self.ctx.global_strings)

    def _init_state(self):
        return (self.window.init_state(), self.selector.init_state(),
                self.rate_limiter.init_state())

    def _make_step(self, track_compiles: bool = True):
        import dataclasses as dc

        filters = self.filters
        post_filters = self.post_filters
        pre_fns = self.pre_window_fns
        post_fns = self.post_window_fns
        window = self.window
        selector = self.selector
        frame_ref = self.frame_ref
        dep_tables = self.dep_tables
        probes = {tid: self.tables[tid].contains_probe for tid in dep_tables}
        for tid in dep_tables:
            if hasattr(self.tables[tid], "_used_in_probe"):
                self.tables[tid]._used_in_probe = True  # cache-miss monitor

        limiter = self.rate_limiter
        stats = self.ctx.statistics
        qname = self.name

        def apply_fns(fns, batch, scope):
            for spec, arg_ex in fns:
                args = [a(scope) for a in arg_ex]
                new_cols = spec.apply(*args)
                declared = dict(spec.new_attrs)
                cast_cols = {
                    n: jnp.asarray(c).astype(dtypes.device_dtype(declared[n]))
                    for n, c in new_cols.items()}
                batch = dc.replace(batch, cols={**batch.cols, **cast_cols})
                scope.add_frame(frame_ref, batch.cols, batch.ts, batch.valid,
                                default=True)
            return batch

        def step(state, batch: EventBatch, now, table_states=None):
            # trace-time side effect: fires once per compiled executable —
            # the per-query compile counter (recompile-storm observability).
            # Fused members suppress it: the SharedStepGroup counts ONE
            # compile for the whole group under its own name.
            if track_compiles:
                stats.track_compile(qname, batch.capacity)
            wstate, sstate, rstate = state

            scope = Scope()
            scope.add_frame(frame_ref, batch.cols, batch.ts, batch.valid, default=True)
            scope.extras["now"] = now
            if table_states:
                for tid, (tstate, tidx) in table_states.items():
                    scope.extras[f"table:{tid}"] = tstate
                    scope.extras[f"tableidx:{tid}"] = tidx
                    scope.extras[f"in:{tid}"] = probes[tid]
            mask = batch.valid
            for f in filters:
                mask = mask & f(scope)
            batch = batch.where_valid(mask)
            scope.add_frame(frame_ref, batch.cols, batch.ts, batch.valid,
                            default=True)
            batch = apply_fns(pre_fns, batch, scope)

            wstate_pre = wstate
            wstate, chunk = window.step(wstate, batch, now)

            cscope = Scope()
            cscope.add_frame(frame_ref, chunk.cols, chunk.ts, chunk.valid, default=True)
            cscope.extras = dict(scope.extras)
            chunk = apply_fns(post_fns, chunk, cscope)
            for f in post_filters:
                chunk = chunk.where_valid(
                    f(cscope) | (chunk.types != EventType.CURRENT))
            if selector.extrema_plan:
                # removal-capable sliding min/max: range queries over the
                # window's arrival-order sequence (ops/extrema.py)
                from ..ops.extrema import (grouped_sliding_extrema_lanes,
                                           sliding_extrema_lanes)
                from ..ops.windows import _unpack_rows
                ring_cols, ring_ts = _unpack_rows(wstate_pre.ring,
                                                  window.layout)
                rscope = Scope()
                rscope.add_frame(
                    frame_ref, ring_cols, ring_ts,
                    jnp.ones(ring_ts.shape, bool), default=True)
                rscope.extras = dict(scope.extras)
                ghash = selector.extrema_group_hash
                for slot, eop, args in selector.extrema_plan:
                    if ghash is not None:
                        cscope.extras[f"extrema:{slot}"] = \
                            grouped_sliding_extrema_lanes(
                                eop, args[0](rscope), ghash(rscope),
                                wstate_pre.expired, wstate_pre.appended,
                                chunk, args[0](cscope), ghash(cscope))
                    else:
                        cscope.extras[f"extrema:{slot}"] = \
                            sliding_extrema_lanes(
                                eop, args[0](rscope), wstate_pre.expired,
                                wstate_pre.appended, chunk, args[0](cscope))
            sstate, out = selector.step(sstate, chunk, cscope)
            if getattr(limiter, "needs_window_contents", False):
                # non-FIFO snapshot: per-arrival output is suppressed; ticks
                # re-project the window's live contents. POST-step state so
                # time-driven evictions (session close on this watermark)
                # apply; the limiter then drops rows whose arrival ts is
                # PAST the fired boundary, so the batch revealing a crossing
                # cannot leak its later arrivals into that snapshot
                w_cols, w_ts, w_live = window.contents(wstate, now)
                s2 = Scope()
                s2.add_frame(frame_ref, w_cols, w_ts, w_live, default=True)
                s2.extras["now"] = now
                proj = {
                    name: jnp.broadcast_to(
                        jnp.asarray(ce(s2)), w_ts.shape)
                    for name, ce in selector.out_exprs}
                if selector.having is not None:
                    h2 = Scope()
                    h2.add_frame(frame_ref, w_cols, w_ts, w_live)
                    h2.add_frame("__out__", proj, w_ts, w_live, default=True)
                    h2.extras["now"] = now
                    w_live = w_live & selector.having(h2)
                cb = EventBatch(  # ts = ARRIVAL instants (boundary filter)
                    ts=w_ts, cols=proj, valid=w_live,
                    types=jnp.zeros(w_ts.shape, jnp.int8))
                rstate, out = limiter.step_contents(rstate, cb, now)
            else:
                rstate, out = limiter.step(rstate, out, now)

            return (wstate, sstate, rstate), out

        return step

    # -------------------------------------------------------------- runtime

    def _selector_state(self):
        """The selector's slice of this runtime's state tuple (joins keep it
        at a different index — see JoinQueryRuntime)."""
        return self.state[1]

    def _maybe_in_fallback(self, batch: EventBatch, now: int) -> None:
        """Pre-warm overflowed `in`-probed caches with this batch's probe
        values (host store read-through before the jitted step) — see
        RecordTableRuntime.ensure_cached_for_keys."""
        scope = None
        for tid, specs in self._in_fallbacks.items():
            table = self.tables[tid]
            pol = getattr(table, "cache_policy", None)
            if pol is None or not pol.overflowed:
                continue
            if scope is None:
                scope = Scope()
                scope.add_frame(self.frame_ref, batch.cols, batch.ts,
                                batch.valid, default=True)
                scope.extras["now"] = jnp.int64(now)
            for t_attr, sc, stype in specs:
                try:
                    vals_dev = sc(scope)
                except Exception:  # expr needs step-computed columns: skip
                    continue
                import numpy as np
                valid, vals = jax.device_get((batch.valid, vals_dev))
                sel = np.asarray(vals)[np.nonzero(valid)[0]]
                if stype == AttributeType.STRING:
                    keys = table.codec.string_tables[t_attr].decode_array(
                        sel.tolist())
                elif stype == AttributeType.BOOL:
                    keys = sel.astype(bool).tolist()
                else:
                    keys = sel.tolist()
                table.ensure_cached_for_keys((t_attr,),
                                             {(k,) for k in keys})

    def _table_states(self) -> dict:
        return {tid: (self.tables[tid].state,
                      self.tables[tid].probe_indexes()
                      if tid in self._index_tables else {})
                for tid in self.dep_tables}

    def warmup(self, buckets=None) -> int:
        """AOT-compile the jitted step for each lane bucket (ahead of time,
        WITHOUT executing — see aot_warm), so first-batch compile time never
        pollutes steady-state latency/throughput. Returns the number of
        fresh compiles this triggered."""
        if buckets is None:
            buckets = (dtypes.bucket_ladder(self._batch_cap)
                       if self._bucket_ok and dtypes.config.shape_buckets
                       and self.ctx.mesh is None else (self._batch_cap,))
        n0 = self.ctx.statistics.compiles.get(self.name, 0)
        now = jnp.int64(self.ctx.timestamp_generator.current_time())
        for cap in buckets:
            batch = EventBatch.empty(self.input_junction.definition, cap)
            aot_warm(self._step, self.state, batch, now,
                     self._table_states())
        return self.ctx.statistics.compiles.get(self.name, 0) - n0

    def on_batch(self, batch: EventBatch, now: int) -> None:
        t0 = time.perf_counter_ns()
        if batch.capacity < self._batch_cap and not self._bucket_ok:
            # shape-baked step: restore the traced capacity (bucketed or
            # upstream-chunked batches widen; new lanes are invalid)
            batch = batch.pad_to(self._batch_cap)
        debugger = getattr(self.ctx, "debugger", None)
        if debugger is not None:
            from .debugger import QueryTerminal
            if debugger.wants(self.name, QueryTerminal.IN):
                debugger.check_break_point(
                    self.name, QueryTerminal.IN,
                    batch.to_host_events(self.codec))
        if self._in_fallbacks:
            self._maybe_in_fallback(batch, now)
        self.state, out = self._step(self.state, batch, jnp.int64(now),
                                     self._table_states())
        self._distribute(out, now)
        elapsed = time.perf_counter_ns() - t0
        self.ctx.statistics.track_latency(self.name, elapsed)
        meter = getattr(self.ctx, "tenant_meter", None)
        if meter is not None:
            meter.record(self.name, elapsed)
        tele = getattr(self.ctx, "telemetry", None)
        if tele is not None:
            if tele.on:
                tele.record_query(self.name, elapsed)
            sess = tele.profile
            if sess is not None and sess.active:
                # one-shot profile(): block on the post-step state to split
                # host wall time from device execution still in flight
                import jax
                w0 = time.perf_counter_ns()
                jax.block_until_ready(self.state)
                wait = time.perf_counter_ns() - w0
                sess.record(self.name, elapsed + wait, wait)
        self._post_step_maintenance()

    def _post_step_maintenance(self) -> None:
        """Per-batch housekeeping after the jitted step: custom-aggregate
        compaction cadence + snapshot-overflow warning. Shared between
        on_batch and SharedStepGroup dispatch (core/shared.py)."""
        self._batches_seen += 1
        # adaptive cadence: cheap (one scalar sync) but sparse normally;
        # tight once a table runs hot so compaction outruns overflow.
        # Warnings are one-shot, but the checks (and their compactions)
        # keep running for the app's lifetime.
        interval = 4 if self._capacity_pressure else 256
        if (self._has_custom_aggs
                and (self._batches_seen in (1, 16, 64)
                     or self._batches_seen % interval == 0)):
            self._check_custom_agg_capacity()
        if (not self._snapshot_warned and self._batches_seen % 256 == 0
                and hasattr(self.state[2], "overflow")):
            if int(self.state[2].overflow) > 0:
                import warnings
                warnings.warn(
                    f"query {self.name!r}: {int(self.state[2].overflow)} "
                    "output lanes exceeded snapshot_group_capacity and are "
                    "missing from periodic snapshots — raise "
                    "config.snapshot_group_capacity", stacklevel=2)
                self._snapshot_warned = True

    def _check_custom_agg_capacity(self) -> None:
        """distinctCount's (group,value) pair table is append-only inside
        the jitted step (zeroed pairs keep their slot, unlike the reference's
        HashMap entry removal). At 85% occupancy the monitor COMPACTS it —
        rebuilding with only live pairs (ops/aggregators.py
        compact_distinct_state) — and only warns if live pairs alone still
        exceed capacity."""
        import dataclasses as dc
        import warnings

        from ..ops.aggregators import compact_distinct_state
        from ..ops.groupby import GroupState, KeyTable
        pressure = False
        for gi, g in enumerate(self.state[1].groups):
            if not (isinstance(g, tuple) and g):
                continue
            if isinstance(g[0], KeyTable):
                kt = g[0]
                cap = kt.keys.shape[0] // 2  # hash array is 2x id capacity
                count = int(kt.count)
                pressure = pressure or count > int(0.5 * cap)
                # compact early enough that the table cannot fill (and
                # start dropping pairs) between checks — but only when
                # enough NEW pairs arrived since the last rebuild that dead
                # ones can plausibly be reclaimed (a steady 0.6*cap LIVE
                # set must not trigger an O(cap) rebuild every check)
                grown = count - self._last_compacted_live.get(gi, 0)
                if (count > int(0.85 * cap)
                        or (count > int(0.5 * cap)
                            and grown > int(0.2 * cap))):
                    sstate = self.state[1]
                    epoch = int(sstate.epoch)
                    new_g = compact_distinct_state(g, epoch)
                    groups = list(sstate.groups)
                    groups[gi] = new_g
                    self.state = (self.state[0],
                                  dc.replace(sstate, groups=groups),
                                  self.state[2])
                    kt = new_g[0]
                    self._last_compacted_live[gi] = int(kt.count)
                    if (int(kt.count) > int(0.85 * cap)
                            and not self._capacity_warned):
                        warnings.warn(
                            f"query {self.name!r}: distinctCount pair table "
                            f"still at {int(kt.count)}/{cap} LIVE "
                            "(group,value) pairs after compaction; counts "
                            "will corrupt past capacity — raise "
                            "group_capacity", stacklevel=2)
                        self._capacity_warned = True
                elif int(kt.misses) > 0 and not self._capacity_warned:
                    warnings.warn(
                        f"query {self.name!r}: {int(kt.misses)} key lookups "
                        "could not be placed and their events were dropped "
                        "from the aggregate — raise group_capacity",
                        stacklevel=2)
                    self._capacity_warned = True
            elif isinstance(g[0], GroupState) and len(g) == 2:
                # string-code fast path: pair table indexed by interning code
                cap = g[0].values.shape[0]
                n_codes = len(self.ctx.global_strings)
                if n_codes > int(0.85 * cap) and not self._capacity_warned:
                    warnings.warn(
                        f"query {self.name!r}: distinctCount code table at "
                        f"{n_codes}/{cap} interned strings; codes past "
                        "capacity are dropped from the count — raise "
                        "group_capacity", stacklevel=2)
                    self._capacity_warned = True
        self._capacity_pressure = pressure

    def _distribute(self, out: EventBatch, now: int) -> None:
        action = self.query.output_stream.action
        etype = self.query.output_stream.event_type

        debugger = getattr(self.ctx, "debugger", None)
        if (debugger is None and not self.callbacks
                and action == OutputAction.INSERT
                and self.output_junction is not None
                and _sink_dark(self.output_junction)):
            # nothing observes this emission: skip the _select_event_type
            # device ops and the controller-lock publish round trip. For
            # fan-out apps (N queries, few subscribed outputs) this is the
            # dominant per-query per-batch cost.
            return
        if debugger is not None:
            from .debugger import QueryTerminal
            if debugger.wants(self.name, QueryTerminal.OUT):
                debugger.check_break_point(
                    self.name, QueryTerminal.OUT,
                    out.to_host_events(self.output_codec))

        uuid_slots = self.selector.host_uuid_slots
        forwards = (self.output_junction is not None
                    or self.table_executor is not None)
        if uuid_slots and forwards:
            # fresh uuid4 per emitted lane per UUID() slot (reference
            # UUIDFunctionExecutor), interned into the string table's
            # BOUNDED transient ring so every consumer — downstream
            # queries, tables, sinks — sees real values with O(1) host
            # memory (codes recycle after ~1M newer uuids; docs/PARITY.md)
            out = self._intern_uuid_columns(out)

        if self.callbacks:
            # callbacks see exactly what the query emits (reference:
            # outputExpectsExpiredEvents): CURRENT-only queries get no
            # removeEvents regardless of window kind
            events = out.to_host_events(self.output_codec)
            set_slots = getattr(self.selector, "host_set_slots", None)
            if set_slots and events:
                # raw unionSet: materialize the live value set host-side
                # (reference UnionSetAttributeAggregatorExecutor.java:71 —
                # every emission carries the SAME accumulating set object;
                # here each batch's events share one materialized set)
                names = [a.name for a in self.output_attributes]
                subs = [(names.index(n),
                         self.selector.union_set_values(
                             self._selector_state(), n,
                             self.ctx.global_strings))
                        for n in set_slots]
                for k, e in enumerate(events):
                    data = list(e.data)
                    for i, s in subs:
                        data[i] = s
                    events[k] = Event(e.timestamp, tuple(data),
                                      is_expired=e.is_expired)
            if uuid_slots and not forwards and events:
                # callback-only output: substitute decoded events directly —
                # no interning, no string-table growth
                import uuid as _uuid
                names = [a.name for a in self.output_attributes]
                idxs = [names.index(s) for s in uuid_slots]
                for k, e in enumerate(events):
                    data = list(e.data)
                    for i in idxs:
                        data[i] = str(_uuid.uuid4())
                    # Event is frozen (GC-untrack safety): rebuild
                    events[k] = Event(e.timestamp, tuple(data),
                                      is_expired=e.is_expired)
            in_events = [e for e in events if not e.is_expired] or None
            remove_events = ([e for e in events if e.is_expired] or None
                             if etype != OutputEventType.CURRENT else None)
            if etype == OutputEventType.EXPIRED:
                in_events = None
            if in_events or remove_events:
                for cb in self.callbacks:
                    cb.receive(now, in_events, remove_events)

        if action == OutputAction.INSERT and self.output_junction is not None:
            fwd = self._select_event_type(out, etype)
            self.output_junction.publish_batch(fwd, now)
        elif action in (OutputAction.DELETE, OutputAction.UPDATE,
                        OutputAction.UPDATE_OR_INSERT) and self.table_executor is not None:
            fwd = self._select_event_type(out, etype)
            self.table_executor.apply(fwd)

    def _intern_uuid_columns(self, out: EventBatch) -> EventBatch:
        import dataclasses as dc
        import uuid as _uuid

        import numpy as np
        valid = np.asarray(out.valid)
        idx = np.nonzero(valid)[0]
        cols = dict(out.cols)
        for slot in self.selector.host_uuid_slots:
            tbl = self.output_codec.string_tables[slot]
            codes = np.zeros(out.capacity, np.int32)
            for i in idx:
                codes[i] = tbl.encode_transient(str(_uuid.uuid4()))
            cols[slot] = jnp.asarray(codes)
        return dc.replace(out, cols=cols)

    @staticmethod
    def _select_event_type(out: EventBatch, etype: OutputEventType) -> EventBatch:
        import dataclasses as dc
        if etype == OutputEventType.CURRENT:
            keep = out.types == EventType.CURRENT
        elif etype == OutputEventType.EXPIRED:
            keep = out.types == EventType.EXPIRED
        else:
            keep = (out.types == EventType.CURRENT) | (out.types == EventType.EXPIRED)
        # forwarded events enter the next stream as fresh CURRENT arrivals
        return dc.replace(out, valid=out.valid & keep,
                          types=jnp.zeros_like(out.types))

    def add_callback(self, cb: QueryCallback) -> None:
        self.callbacks.append(cb)


def _sink_dark(j) -> bool:
    """True when publishing to junction `j` is observably a no-op: no
    receivers, taps, WAL, blue-green redirect, or staged rows, and
    statistics (explicit opt-in, exact in/out counts) are off. Re-checked
    per batch, so attaching a callback or subscriber later re-lights the
    sink immediately. Always-on telemetry does NOT keep a sink lit: its
    spans measure delivery work, and a skipped no-op delivery has none —
    dark streams simply stop appearing in per-stream batch series
    (docs/OPTIMIZER.md)."""
    if not isinstance(j, StreamJunction):
        # window/table junction adapters always consume their input
        return False
    if j.receivers or j.taps or j._staged_rows:
        return False
    if j.wal is not None or j._redirect is not None:
        return False
    return not j.ctx.statistics.enabled


def _collect_eq_probe_tables(query: Query, tables: dict) -> set:
    """Tables probed by a single-equality `in` condition on an indexable
    attribute — the only ones whose sorted indexes the step will read."""
    from ..query_api.expression import Compare, CompareOp, In

    found: set = set()

    def walk(node):
        if node is None or not isinstance(node, Expression):
            return
        if isinstance(node, In):
            e = node.expression
            t = tables.get(node.source_id)
            if (t is not None and isinstance(e, Compare)
                    and e.op == CompareOp.EQUAL
                    and hasattr(t, "indexable_eq_attrs")):
                for side in (e.left, e.right):
                    if (isinstance(side, Variable)
                            and side.stream_id == node.source_id
                            and side.attribute in t.indexable_eq_attrs()):
                        found.add(node.source_id)
            walk(e)
            return
        for attr in ("left", "right", "expression"):
            sub = getattr(node, attr, None)
            if isinstance(sub, Expression):
                walk(sub)
        for p in getattr(node, "parameters", ()) or ():
            walk(p)

    for f in query.input_stream.handlers.filters:
        walk(f)
    for f in query.input_stream.handlers.post_window_filters:
        walk(f)
    walk(query.selector.having)
    return found


def _collect_in_fallbacks(query: Query, tables: dict, resolver, registry):
    """Per cached-@store table id: [(table_attr, compiled_stream_expr, type)]
    for every `T.attr == <stream expr>` `in` condition — the store-fallback
    key plans (reference: AbstractQueryableRecordTable.java:207-238).
    Returns (fallbacks, nofallback_table_ids): the second set lists cached
    tables probed by at least one `in` condition NO fallback covers (their
    overflow warning must stay the hard miss warning)."""
    from ..io.record_table import RecordTableRuntime
    from ..query_api.expression import Compare, CompareOp, In

    found: dict = {}
    nofallback: set = set()

    def consider(node: In):
        t = tables.get(node.source_id)
        if not (isinstance(t, RecordTableRuntime) and t.cache_policy is not None):
            return
        e = node.expression
        if isinstance(e, Compare) and e.op == CompareOp.EQUAL:
            for tside, sside in ((e.left, e.right), (e.right, e.left)):
                if not (isinstance(tside, Variable)
                        and tside.stream_id == node.source_id):
                    continue
                if _references_table_frame(sside, node.source_id):
                    continue
                try:
                    sc = compile_expression(sside, resolver, registry)
                except SiddhiAppCreationError:
                    continue
                found.setdefault(node.source_id, []).append(
                    (tside.attribute, sc, sc.type))
                return
        nofallback.add(node.source_id)

    def walk(node):
        if node is None or not isinstance(node, Expression):
            return
        if isinstance(node, In):
            consider(node)
            walk(node.expression)
            return
        for attr in ("left", "right", "expression"):
            sub = getattr(node, attr, None)
            if isinstance(sub, Expression):
                walk(sub)
        for p in getattr(node, "parameters", ()) or ():
            walk(p)

    for f in query.input_stream.handlers.filters:
        walk(f)
    for f in query.input_stream.handlers.post_window_filters:
        walk(f)
    for a in query.selector.attributes:
        walk(a.expression)
    walk(query.selector.having)
    return found, nofallback


def _references_table_frame(e, frame: str) -> bool:
    if isinstance(e, Variable):
        return e.stream_id == frame
    for attr in ("left", "right", "expression"):
        sub = getattr(e, attr, None)
        if isinstance(sub, Expression) and _references_table_frame(sub, frame):
            return True
    return any(_references_table_frame(p, frame)
               for p in getattr(e, "parameters", ()) or ()
               if isinstance(p, Expression))


def _collect_in_sources(query: Query) -> set[str]:
    """Table ids referenced by `in Table` conditions anywhere in the query."""
    from ..query_api.expression import In

    found: set[str] = set()

    def walk(node):
        if node is None or not isinstance(node, Expression):
            return
        if isinstance(node, In):
            found.add(node.source_id)
            walk(node.expression)
            return
        for attr in ("left", "right", "expression"):
            sub = getattr(node, attr, None)
            if isinstance(sub, Expression):
                walk(sub)
        for p in getattr(node, "parameters", ()) or ():
            walk(p)

    ins = query.input_stream
    for f in getattr(ins.handlers, "filters", ()):
        walk(f)
    for f in getattr(ins.handlers, "post_window_filters", ()):
        walk(f)
    for a in query.selector.attributes:
        walk(a.expression)
    walk(query.selector.having)
    return found
