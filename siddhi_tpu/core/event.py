"""Columnar event model — the TPU data plane.

Reference design (core/event/): events are heap objects (`StreamEvent.java:38`
with three `Object[]` segments) chained into linked lists and walked one at a
time. That shape cannot feed a systolic array. The TPU-native replacement is a
**struct-of-arrays micro-batch**:

    EventBatch
      ts     : int64[B]            arrival/event timestamps (ms)
      cols   : {attr: dtype[B]}    one fixed-dtype array per attribute
      valid  : bool[B]             lane validity (filters mask, never compact
                                   on device — compaction happens host-side)
      types  : int8[B]             CURRENT/EXPIRED/TIMER/RESET, matching
                                   ComplexEvent.Type semantics

Batches are padded to fixed capacities so every query step compiles once and
reuses the executable (XLA static shapes). `Event` remains as the host-side
user-facing single event (reference: core/event/Event.java).
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..query_api.definition import AttributeType, StreamDefinition
from . import dtypes
from .dtypes import NULL_CODE


class EventType(enum.IntEnum):
    """Reference: core/event/ComplexEvent.java Type enum."""

    CURRENT = 0
    EXPIRED = 1
    TIMER = 2
    RESET = 3


@dataclass(frozen=True, slots=True)
class Event:
    """Host-side single event (reference: core/event/Event.java). Slotted +
    frozen: decode materializes millions of these; __slots__ drops the
    per-instance dict and lets the native builder (columnar.c build_events)
    fill fields through slot descriptors, and immutability makes the
    builder's cyclic-GC untrack provably safe (no cycle can ever be formed
    through an Event after construction)."""

    timestamp: int
    data: tuple
    is_expired: bool = False

    def __iter__(self):
        return iter(self.data)


class StringTable:
    """Host-side string interner for one stream attribute. Device arrays carry
    int32 codes; the table maps code <-> string. Code 0 is null.

    TPU rationale: string group-by keys in the reference are Java string-concat
    HashMap keys (GroupByKeyGenerator.java:37); dictionary encoding turns them
    into device integer ops.
    """

    #: transient codes live at the top of the code space (see
    #: encode_transient)
    TRANSIENT_BASE = 1 << 30

    def __init__(self) -> None:
        self._to_code: dict[str, int] = {}
        self._to_str: list[Optional[str]] = [None]  # code 0 = null
        self._transient: list[Optional[str]] = []
        self._transient_code: dict[str, int] = {}
        self._transient_next = 0
        #: generation per ring slot: decode of a code whose slot has been
        #: recycled raises LOUDLY instead of silently returning a newer
        #: uuid (VERDICT r3 weak #5). The generation is folded into the
        #: code itself (code = BASE + gen*cap + pos), so the check costs
        #: one list read; generations wrap after 2^30/cap reuses of a slot
        #: (~1024 at the default 1M capacity) — documented bound.
        self._transient_gens: list[int] = []
        self._transient_cap: Optional[int] = None
        #: native pointer-identity intern memo (capsule); lazily created by
        #: encode_array, dropped whenever permanent codes are reassigned
        self._id_memo = None

    def encode(self, s: Optional[str]) -> int:
        if s is None:
            return NULL_CODE
        code = self._to_code.get(s)
        if code is None:
            # a LIVE transient string (a uuid coming back from a client)
            # must round-trip to its transient code, or device equality
            # against stored uuid columns would never match
            code = self._transient_code.get(s)
        if code is None:
            code = len(self._to_str)
            self._to_code[s] = code
            self._to_str.append(s)
        return code

    def encode_transient(self, s: str, capacity: int = 1 << 20) -> int:
        """Intern a NEVER-REPEATING string (UUID() output) into a bounded
        recycling ring instead of the append-only table — unbounded interning
        of per-event uniques is a host memory leak. Codes recycle after
        `capacity` newer entries; a consumer that retained a code that long
        (e.g. a huge window over a uuid column) gets a LOUD
        StaleTransientCodeError at decode (the slot generation is folded
        into the code), not a silently-wrong newer uuid."""
        if self._transient_cap is None:
            self._transient_cap = capacity
        cap = self._transient_cap
        pos = self._transient_next
        if len(self._transient) <= pos:
            self._transient.append(s)
            self._transient_gens.append(0)
            gen = 0
        else:
            old = self._transient[pos]
            if old is not None:
                self._transient_code.pop(old, None)
            self._transient[pos] = s
            gen = (self._transient_gens[pos] + 1) % max(
                (1 << 30) // cap, 1)
            self._transient_gens[pos] = gen
        code = self.TRANSIENT_BASE + gen * cap + pos
        self._transient_code[s] = code
        self._transient_next = (pos + 1) % cap
        return code

    def decode(self, code: int) -> Optional[str]:
        if code >= self.TRANSIENT_BASE:
            idx = code - self.TRANSIENT_BASE
            cap = self._transient_cap or (1 << 20)
            pos, gen = idx % cap, idx // cap
            if not 0 <= pos < len(self._transient):
                return None
            if gen != self._transient_gens[pos]:
                from ..errors import StaleTransientCodeError
                raise StaleTransientCodeError(
                    f"transient uuid code {code} was recycled: the slot has "
                    f"seen {self._transient_gens[pos] - gen} newer uuids "
                    f"past the ~{cap}-entry ring — raise the transient "
                    "capacity or avoid retaining uuid codes this long")
            return self._transient[pos]
        return self._to_str[code] if 0 <= code < len(self._to_str) else None

    def encode_many(self, values: Sequence[Optional[str]]) -> np.ndarray:
        return np.fromiter((self.encode(v) for v in values), dtype=np.int32, count=len(values))

    def encode_array(self, values: np.ndarray) -> np.ndarray:
        """Vectorized interning for a whole column (send_columns path):
        native C loop when built, else a local-ref dict loop — both ~5x the
        per-row encode() dispatch. (np.unique was measured and rejected:
        sorting object arrays does Python-level compares.)"""
        values = np.asarray(values, dtype=object)
        n = len(values)
        out = np.empty(n, dtype=np.int32)
        from .. import native as native_mod
        if native_mod.native is not None:
            if self._id_memo is None and \
                    hasattr(native_mod.native, "idmemo_new"):
                # pointer-identity fast path for producers that pool their
                # string objects (see columnar.c); dropped on restore()
                # because restore reassigns permanent codes
                self._id_memo = native_mod.native.idmemo_new()
            native_mod.native.intern_column(values, out, self._to_code,
                                            self._to_str,
                                            self._transient_code,
                                            self._id_memo)
            return out
        to_code, to_str = self._to_code, self._to_str
        transient = self._transient_code
        for i, s in enumerate(values):
            if s is None:
                out[i] = NULL_CODE
                continue
            c = to_code.get(s)
            if c is None:
                c = transient.get(s)
            if c is None:
                c = len(to_str)
                to_code[s] = c
                to_str.append(s)
            out[i] = c
        return out

    def decode_array(self, codes) -> list:
        """Vectorized decode: one list-index per row through a local ref,
        falling back to decode() only for transient (UUID-ring) codes."""
        to_str = self._to_str
        n = len(to_str)
        return [to_str[c] if 0 <= c < n else self.decode(c) for c in codes]

    def __len__(self) -> int:
        return len(self._to_str)

    # snapshot support
    def snapshot(self):
        # transient ring included: persisted state (tables/windows) may hold
        # transient codes (UUID columns) that must decode after restore
        return {"strings": list(self._to_str),
                "transient": list(self._transient),
                "transient_next": self._transient_next,
                "transient_gens": list(self._transient_gens),
                "transient_cap": self._transient_cap}

    def restore(self, snap) -> None:
        if isinstance(snap, list):  # pre-transient snapshot format
            snap = {"strings": snap, "transient": [], "transient_next": 0}
        strings = snap["strings"]
        # mutate in place: native encode plans hold references to these
        self._id_memo = None  # permanent codes reassigned below
        self._to_str[:] = list(strings)
        self._to_code.clear()
        self._to_code.update(
            {s: i for i, s in enumerate(strings) if s is not None})
        self._transient[:] = list(snap["transient"])
        self._transient_next = snap["transient_next"]
        self._transient_gens[:] = list(
            snap.get("transient_gens", [0] * len(self._transient)))
        self._transient_cap = snap.get("transient_cap", self._transient_cap)
        cap = self._transient_cap or (1 << 20)
        self._transient_code.clear()
        self._transient_code.update(
            {s: self.TRANSIENT_BASE + self._transient_gens[i] * cap + i
             for i, s in enumerate(self._transient) if s is not None})


class StreamCodec:
    """Per-stream encoder/decoder between host tuples and columnar arrays.

    Owns one StringTable per STRING attribute and the column dtype layout; this
    is the TPU analogue of the reference's StreamEventConverter family
    (core/event/stream/converter/) which maps external Events onto the internal
    StreamEvent layout chosen by MetaStreamEvent.
    """

    def __init__(self, definition: StreamDefinition,
                 shared_strings: Optional[StringTable] = None) -> None:
        """`shared_strings`: app-global interning table. Sharing one table
        across every stream/table/window codec keeps codes consistent when
        events flow between entities (insert into table, joins, chained
        streams) — string identity is app-wide, like JVM string equality in
        the reference."""
        self.definition = definition
        self.string_tables: dict[str, StringTable] = {
            a.name: (shared_strings if shared_strings is not None else StringTable())
            for a in definition.attributes
            if a.type == AttributeType.STRING
        }
        self.np_dtypes = {
            a.name: np.dtype(jnp.dtype(dtypes.device_dtype(a.type)).name)
            for a in definition.attributes
            if a.type != AttributeType.OBJECT
        }
        self.object_attrs = tuple(
            a.name for a in definition.attributes if a.type == AttributeType.OBJECT
        )
        self._native_plan = self._build_native_plan()

    def _build_native_plan(self):
        """Precompute the arguments the native encoder needs; None when the
        schema can't use it (OBJECT attrs or extension unavailable)."""
        from .. import native as native_mod
        if native_mod.native is None or self.object_attrs:
            return None
        codes, tables, nulls = [], [], []
        np_code = {"bool": "b", "int8": "b", "int32": "i", "int64": "l",
                   "float32": "f", "float64": "d"}
        for a in self.definition.attributes:
            if a.type == AttributeType.STRING:
                tbl = self.string_tables[a.name]
                codes.append("s")
                tables.append((tbl._to_code, tbl._to_str,
                               tbl._transient_code))
                nulls.append(0)
            else:
                c = np_code.get(self.np_dtypes[a.name].name)
                if c is None:
                    return None
                codes.append(c)
                tables.append(None)
                nv = dtypes.null_value(a.type)
                nulls.append(float(nv) if c in "fd" else int(nv))
        return ("".join(codes).encode("ascii"), tuple(tables), tuple(nulls),
                native_mod.native)

    def encode_value(self, attr_name: str, attr_type: AttributeType, value):
        if attr_type == AttributeType.STRING:
            return self.string_tables[attr_name].encode(value)
        if value is None:
            return dtypes.null_value(attr_type)
        return value

    def rows_to_columns(
        self, rows: Sequence[Sequence], n_pad: Optional[int] = None
    ) -> dict[str, np.ndarray]:
        """Encode host rows (tuples in attribute order) into numpy columns,
        zero-padded to n_pad lanes. Uses the native C marshaller when built
        (siddhi_tpu.native); Python fallback below is semantically identical."""
        n = len(rows)
        cap = n_pad if n_pad is not None else n
        if self._native_plan is not None:
            codes, tables, nulls, native = self._native_plan
            out = tuple(
                np.zeros(cap, dtype=self.np_dtypes[a.name])
                for a in self.definition.attributes)
            native.encode_rows(rows, codes, out, tables, nulls)
            return {a.name: arr
                    for a, arr in zip(self.definition.attributes, out)}
        cols: dict[str, np.ndarray] = {}
        for i, attr in enumerate(self.definition.attributes):
            if attr.type == AttributeType.OBJECT:
                continue
            arr = np.zeros(cap, dtype=self.np_dtypes[attr.name])
            if attr.type == AttributeType.STRING:
                tbl = self.string_tables[attr.name]
                for r in range(n):
                    arr[r] = tbl.encode(rows[r][i])
            else:
                for r in range(n):
                    v = rows[r][i]
                    arr[r] = dtypes.null_value(attr.type) if v is None else v
            cols[attr.name] = arr
        return cols

    def encode_columns(
        self, cols: dict[str, Sequence], n: int, n_pad: Optional[int] = None,
    ) -> dict[str, np.ndarray]:
        """Encode user-supplied COLUMNS (numpy arrays or sequences, one per
        attribute) into padded device-layout numpy columns. String columns
        accept either str/None object arrays (interned vectorized) or
        pre-encoded integer codes. The whole-array casts replace the
        per-row marshalling loop — this is the fastest public encode path."""
        cap = n_pad if n_pad is not None else n
        out: dict[str, np.ndarray] = {}
        for attr in self.definition.attributes:
            if attr.type == AttributeType.OBJECT:
                continue
            if attr.name not in cols:
                raise ValueError(
                    f"send_columns: missing column {attr.name!r} for stream "
                    f"{self.definition.id!r}")
            src = np.asarray(cols[attr.name])
            if src.shape[0] < n:
                raise ValueError(
                    f"send_columns: column {attr.name!r} has {src.shape[0]} "
                    f"rows, expected {n}")
            dst = np.zeros(cap, dtype=self.np_dtypes[attr.name])
            if attr.type == AttributeType.STRING and \
                    not np.issubdtype(src.dtype, np.integer):
                dst[:n] = self.string_tables[attr.name].encode_array(src[:n])
            else:
                dst[:n] = src[:n]
            out[attr.name] = dst
        return out

    def decode_value(self, attr_name: str, attr_type: AttributeType, raw):
        if attr_type == AttributeType.STRING:
            return self.string_tables[attr_name].decode(int(raw))
        if attr_type == AttributeType.BOOL:
            return bool(raw)
        if attr_type in (AttributeType.INT, AttributeType.LONG):
            return int(raw)
        if attr_type in (AttributeType.FLOAT, AttributeType.DOUBLE):
            return float(raw)
        return raw


@jax.tree_util.register_dataclass
@dataclass
class EventBatch:
    """Columnar micro-batch of events — a JAX pytree, so it flows through jit,
    scan, and shard_map directly."""

    ts: jax.Array  # int64[B]
    cols: dict[str, jax.Array]  # each [B]
    valid: jax.Array  # bool[B]
    types: jax.Array  # int8[B] EventType

    @property
    def capacity(self) -> int:
        return self.ts.shape[0]

    # -- constructors ----------------------------------------------------------

    @staticmethod
    def empty(definition: StreamDefinition, capacity: int) -> "EventBatch":
        cols = {
            a.name: jnp.zeros((capacity,), dtype=dtypes.device_dtype(a.type))
            for a in definition.attributes
            if a.type != AttributeType.OBJECT
        }
        return EventBatch(
            ts=jnp.zeros((capacity,), dtype=dtypes.TS_DTYPE),
            cols=cols,
            valid=jnp.zeros((capacity,), dtype=jnp.bool_),
            types=jnp.zeros((capacity,), dtype=jnp.int8),
        )

    @staticmethod
    def from_numpy(
        ts: np.ndarray,
        cols: dict[str, np.ndarray],
        n_valid: int,
        types: Optional[np.ndarray] = None,
    ) -> "EventBatch":
        cap = ts.shape[0]
        valid = np.zeros(cap, dtype=bool)
        valid[:n_valid] = True
        t = types if types is not None else np.zeros(cap, dtype=np.int8)
        return EventBatch(
            ts=jnp.asarray(ts, dtype=dtypes.TS_DTYPE),
            cols={k: jnp.asarray(v) for k, v in cols.items()},
            valid=jnp.asarray(valid),
            types=jnp.asarray(t, dtype=jnp.int8),
        )

    # -- device-side ops (all mask-based, shape-preserving) --------------------

    def where_valid(self, mask: jax.Array) -> "EventBatch":
        return dataclasses.replace(self, valid=self.valid & mask)

    def pad_to(self, capacity: int) -> "EventBatch":
        """Widen to `capacity` lanes: new lanes are invalid, columns zero,
        timestamps extended with the last value (monotone — searchsorted
        over raw batch ts stays correct). Runtimes whose compiled step is
        NOT shape-polymorphic use this to restore their traced capacity
        when a shape-bucketed junction hands them a narrower batch."""
        n = capacity - self.capacity
        if n <= 0:
            return self
        return EventBatch(
            ts=jnp.pad(self.ts, (0, n), mode="edge"),
            cols={k: jnp.pad(v, (0, n)) for k, v in self.cols.items()},
            valid=jnp.pad(self.valid, (0, n)),
            types=jnp.pad(self.types, (0, n)),
        )

    def count(self) -> jax.Array:
        return jnp.sum(self.valid.astype(jnp.int32))

    # -- host-side decode ------------------------------------------------------

    def to_host_events(self, codec: StreamCodec) -> list[Event]:
        """Compact valid lanes, in lane order, into host Events.

        Decode is vectorized: one device_get tree fetch (a synchronous
        np.asarray per array costs a full ~100 ms tunnel round trip EACH),
        then `.tolist()` per column (one C loop producing Python scalars)
        and a single zip-driven Event comprehension — ~10x the per-element
        np scalar indexing it replaces on wide batches."""
        tree = (self.ts, self.valid, self.types, dict(self.cols))
        if any(getattr(leaf, "is_fully_addressable", True) is False
               for leaf in jax.tree_util.tree_leaves(tree)):
            # multi-host: shards of this array live on OTHER processes
            # (e.g. a shard-merged aggregation find() over a global mesh).
            # process_allgather is a collective — every process reaches this
            # decode as part of the same global program (SPMD discipline,
            # parallel/multihost.py)
            from jax.experimental import multihost_utils
            ts, valid, types, host_cols = \
                multihost_utils.process_allgather(tree, tiled=True)
        else:
            ts, valid, types, host_cols = jax.device_get(tree)
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return []
        from .. import native as native_mod
        nat = native_mod.native
        attrs = codec.definition.attributes
        ts_sel = ts[idx]
        exp_sel = (types[idx] == int(EventType.EXPIRED))
        col_lists = []
        for a in attrs:
            if a.type == AttributeType.OBJECT:
                col_lists.append([None] * idx.size)
            elif a.type == AttributeType.STRING:
                tbl = codec.string_tables[a.name]
                codes = host_cols[a.name][idx]
                if nat is not None and (codes.size == 0 or
                                        int(codes.max()) < StringTable.TRANSIENT_BASE):
                    col_lists.append(nat.map_codes(codes, tbl._to_str))
                else:  # transient (UUID-ring) codes need the Python decode
                    col_lists.append(tbl.decode_array(codes.tolist()))
            elif a.type == AttributeType.BOOL:
                col_lists.append(host_cols[a.name][idx].astype(bool).tolist())
            else:
                col_lists.append(host_cols[a.name][idx].tolist())
        if nat is not None:
            return nat.build_events(Event, ts_sel,
                                    exp_sel.astype(np.uint8), tuple(col_lists))
        return [Event(t, d, is_expired=e)
                for t, d, e in zip(ts_sel.tolist(), zip(*col_lists),
                                   exp_sel.tolist())]
