"""Named windows — `define window W (...) <window spec> output <type> events`.

Reference: core/window/Window.java:65 — a shared window entity: queries
`insert into W` feed it, queries `from W ...` receive its emissions (CURRENT on
arrival, EXPIRED on expiry, filtered by the definition's `output ... events`
clause), joins and on-demand queries probe its current contents through the
FindableProcessor surface.

TPU design: ONE jitted append step per named window — `(wstate, batch, now) ->
(wstate', chunk)` — whose state pytree lives on device and is shared by every
consumer. Downstream `from W` queries subscribe to the window's output
junction; the emitted chunk rides device-to-device (no host hop). Joins and
pull queries read `WindowOp.contents(state, now)` — the same ring the append
step maintains, so there is no copy-per-consumer the way the reference clones
StreamEvents per findable processor.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..errors import SiddhiAppCreationError
from ..extension.registry import ExtensionKind, Registry
from ..ops.window_factories import WindowFactory
from ..ops.windows import PassThroughWindow, WindowOp
from ..query_api.definition import AttributeType, StreamDefinition, WindowDefinition
from . import dtypes
from .context import SiddhiAppContext
from .event import EventBatch, EventType, StreamCodec
from .stream import StreamJunction


class NamedWindow:
    """Runtime for one `define window` (reference: core/window/Window.java:65)."""

    def __init__(self, definition: WindowDefinition, ctx: SiddhiAppContext,
                 registry: Registry) -> None:
        self.definition = definition
        self.ctx = ctx
        self.attr_types = {a.name: a.type for a in definition.attributes
                           if a.type != AttributeType.OBJECT}
        # the window's emission stream shares the definition's schema
        self.stream_definition = StreamDefinition(
            id=definition.id, attributes=definition.attributes,
            annotations=definition.annotations)
        self.codec = StreamCodec(self.stream_definition, ctx.global_strings)
        self.output_junction = StreamJunction(
            self.stream_definition, ctx, codec=self.codec)

        from ..ops.windows import make_layout
        layout = make_layout(self.attr_types)
        batch_cap = ctx.effective_batch_size
        wh = definition.window
        if wh is not None:
            factory = registry.require(ExtensionKind.WINDOW, wh.namespace, wh.name)
            assert isinstance(factory, WindowFactory)
            from .query_runtime import eval_constant
            params = [eval_constant(p) for p in wh.parameters]
            registry.validate_params(ExtensionKind.WINDOW, wh.namespace,
                                     wh.name, params, what="window")
            self.window: WindowOp = factory.make(layout, batch_cap, params, True)
        else:
            # `define window W (...)` with no spec: pass-through emission, no
            # retained contents (reference: empty window)
            self.window = PassThroughWindow(layout, batch_cap)

        self.state = self.window.init_state()
        self._append = jax.jit(
            lambda s, b, n: self.window.step(s, b, n), donate_argnums=(0,))
        out_type = (definition.output_event_type or "all").lower()
        if out_type not in ("all", "current", "expired"):
            raise SiddhiAppCreationError(
                f"window {definition.id!r}: bad output event type {out_type!r}")
        self.output_event_type = out_type
        from ..ops.windows import window_has_time_semantics
        self.has_time_semantics = window_has_time_semantics(self.window)

    # ------------------------------------------------------------------ feed

    def append(self, batch: EventBatch, now: int) -> None:
        """Insert arrivals (CURRENT lanes of `batch`) and publish the window's
        emissions downstream."""
        cap = self.ctx.effective_batch_size
        if batch.capacity < cap and not self.window.shape_polymorphic:
            # shape-baked window op: widen narrower (bucketed / producer-
            # chunked) inserts to the traced capacity
            batch = batch.pad_to(cap)
        self.state, chunk = self._append(self.state, batch, jnp.int64(now))
        chunk = self._apply_output_event_type(chunk)
        self.output_junction.publish_batch(chunk, now)

    def heartbeat(self, now: int) -> None:
        """Advance time with no data so time-driven expirations emit."""
        cap = self.ctx.effective_batch_size
        if self.window.shape_polymorphic and dtypes.config.shape_buckets \
                and self.ctx.mesh is None:
            cap = dtypes.bucket_capacity(0, cap)  # timer batch: min bucket
        empty = EventBatch.empty(self.stream_definition, cap)
        self.append(empty, now)

    def _apply_output_event_type(self, chunk: EventBatch) -> EventBatch:
        import dataclasses as dc
        if self.output_event_type == "current":
            keep = chunk.types == EventType.CURRENT
        elif self.output_event_type == "expired":
            keep = chunk.types == EventType.EXPIRED
        else:
            return chunk
        return dc.replace(chunk, valid=chunk.valid & keep)

    # ----------------------------------------------------------------- probe

    def contents(self, state, now):
        """Current in-window rows as (cols, ts, valid) — the FindableProcessor
        surface for joins/on-demand queries. Traced: call inside jit with the
        window's state passed as an argument."""
        return self.window.contents(state, now)


class WindowJunctionAdapter:
    """Adapts the query-output junction interface onto a named-window insert,
    renaming the query's output columns positionally onto the window schema
    (reference: InsertIntoWindowCallback — schemas match by position)."""

    def __init__(self, window: NamedWindow, out_types: Optional[dict] = None):
        self.window = window
        self.rename: Optional[dict] = None
        if out_types is not None:
            out_names = list(out_types.keys())
            win_names = list(window.attr_types.keys())
            if len(out_names) != len(win_names):
                raise SiddhiAppCreationError(
                    f"insert into window {window.definition.id!r}: query emits "
                    f"{len(out_names)} attributes, window has {len(win_names)}")
            for on, wn in zip(out_names, win_names):
                if out_types[on] != window.attr_types[wn]:
                    raise SiddhiAppCreationError(
                        f"insert into window {window.definition.id!r}: attribute "
                        f"{on!r} is {out_types[on].name}, window attribute "
                        f"{wn!r} is {window.attr_types[wn].name}")
            if out_names != win_names:
                self.rename = dict(zip(out_names, win_names))

    def publish_batch(self, batch: EventBatch, now: int) -> None:
        if self.rename:
            import dataclasses as dc
            batch = dc.replace(
                batch, cols={self.rename[k]: v for k, v in batch.cols.items()})
        self.window.append(batch, now)
