"""Partitions — `partition with (<key> of Stream, ...) begin <queries> end`.

Reference: core/partition/ — PartitionRuntimeImpl.java:75 (per-key clones of
the inner queries + inner `#stream` junctions), PartitionStreamReceiver.java:44
(evaluates a PartitionExecutor per event, lazily clones query runtimes per key,
routes via key-suffixed junctions), ValuePartitionExecutor /
RangePartitionExecutor, PartitionStateHolder (per-key state keyed by
thread-local flow id), `@purge` idle-key cleanup (PartitionRuntimeImpl:120-136).

TPU re-design — clone STATE, never code: the reference clones whole
QueryRuntime object graphs per key; here every inner query is planned and
jit-compiled exactly ONCE, and a partition key owns only a pytree of state
(window rings + group tables) swapped into the shared compiled step. Keys
therefore cost state memory, not compile time. Batches are routed by evaluating
the compiled key expression on device, then splitting the batch into per-key
masked views (capacity unchanged — lanes outside the key are invalid). A
stateless inner graph (pure filter/projection — the BASELINE partitioned-filter
shape) skips splitting entirely: with no per-key state, one fused pass over the
whole batch is semantically identical and runs at full batch width.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import DefinitionNotExistError, SiddhiAppCreationError
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..query_api.definition import AttributeType
from ..query_api.execution import (
    JoinInputStream,
    OutputAction,
    Partition,
    Query,
    RangePartitionType,
    SingleInputStream,
    StateInputStream,
    ValuePartitionType,
)
from .event import EventBatch
from .stream import Receiver, StreamJunction


_TIME_UNITS_MS = {
    "millisecond": 1, "milliseconds": 1, "ms": 1,
    "second": 1000, "seconds": 1000, "sec": 1000,
    "minute": 60_000, "minutes": 60_000, "min": 60_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "day": 86_400_000, "days": 86_400_000,
    "month": 2_592_000_000, "months": 2_592_000_000,
    "year": 31_536_000_000, "years": 31_536_000_000,
}


def _parse_annotation_time(text: str) -> int:
    """Annotation time strings like '1 hour', '10 sec', '5000' → ms
    (reference: SiddhiConstants purge annotation values)."""
    parts = text.strip().lower().split()
    if len(parts) == 1:
        return int(parts[0])
    if len(parts) % 2 != 0:
        raise SiddhiAppCreationError(f"bad time literal {text!r}")
    total = 0
    for i in range(0, len(parts), 2):
        unit = _TIME_UNITS_MS.get(parts[i + 1])
        if unit is None:
            raise SiddhiAppCreationError(f"bad time literal {text!r}")
        total += int(parts[i]) * unit
    return total


def _referenced_streams(query: Query):
    """(stream_id, is_inner) pairs consumed by a query."""
    ins = query.input_stream
    if isinstance(ins, SingleInputStream):
        return [(ins.stream_id, ins.is_inner)]
    if isinstance(ins, JoinInputStream):
        return [(ins.left.stream_id, ins.left.is_inner),
                (ins.right.stream_id, ins.right.is_inner)]
    if isinstance(ins, StateInputStream):
        out = []

        def walk(el):
            from ..query_api.execution import (
                AbsentStreamStateElement,
                CountStateElement,
                EveryStateElement,
                LogicalStateElement,
                NextStateElement,
                StreamStateElement,
            )
            if isinstance(el, StreamStateElement):
                out.append((el.stream.stream_id, el.stream.is_inner))
            elif isinstance(el, AbsentStreamStateElement):
                out.append((el.stream.stream_id, el.stream.is_inner))
            elif isinstance(el, NextStateElement):
                walk(el.state)
                walk(el.next)
            elif isinstance(el, EveryStateElement):
                walk(el.state)
            elif isinstance(el, LogicalStateElement):
                walk(el.left)
                walk(el.right)
            elif isinstance(el, CountStateElement):
                walk(el.element)

        walk(ins.state)
        return out
    return []


class _KeySpec:
    """Compiled partition-key extraction for one partitioned stream."""

    def __init__(self, ptype, junction, registry) -> None:
        definition = junction.definition
        sid = definition.id
        attr_types = {a.name: a.type for a in definition.attributes
                      if a.type != AttributeType.OBJECT}
        resolver = TypeResolver({sid: attr_types}, sid, {sid: junction.codec})
        self.is_range = isinstance(ptype, RangePartitionType)
        if self.is_range:
            self.ranges = []  # (key_string, jitted bool fn)
            for rp in ptype.ranges:
                cond = compile_expression(rp.condition, resolver, registry)
                if cond.type != AttributeType.BOOL:
                    raise SiddhiAppCreationError(
                        f"range partition condition for {rp.partition_key!r} "
                        "must be boolean")
                self.ranges.append((rp.partition_key, self._jit(cond, sid)))
        else:
            executor = compile_expression(ptype.expression, resolver, registry)
            #: un-jitted batch→key-values closure, traceable inside larger
            #: jits (the mesh partition step); value_fn is its jitted form
            self.value_raw = self._wrap(executor, sid)
            self.value_fn = jax.jit(self.value_raw)

    @staticmethod
    def _wrap(executor, sid):
        def fn(batch: EventBatch):
            scope = Scope()
            scope.add_frame(sid, batch.cols, batch.ts, batch.valid, default=True)
            return executor(scope)

        return fn

    @classmethod
    def _jit(cls, executor, sid):
        return jax.jit(cls._wrap(executor, sid))


class PartitionRuntime:
    """One `partition ... begin ... end` block."""

    def __init__(self, partition: Partition, app_runtime, index: int) -> None:
        self.partition = partition
        self.rt = app_runtime
        self.ctx = app_runtime.ctx
        self.name = f"partition{index}"

        # --- key extraction per partitioned stream ---
        self.key_specs: dict[str, _KeySpec] = {}
        for pt in partition.partition_types:
            sid = pt.stream_id
            junction = app_runtime.junctions.get(sid)
            if junction is None:
                raise DefinitionNotExistError(
                    f"partition stream {sid!r} is not defined")
            if sid in self.key_specs:
                raise SiddhiAppCreationError(
                    f"stream {sid!r} partitioned twice in one partition")
            self.key_specs[sid] = _KeySpec(pt, junction, self.ctx.registry)

        # --- inner graph: proxies for outer streams, junctions for #streams ---
        self.proxies: dict[str, StreamJunction] = {}
        self.inner_junctions: dict[str, StreamJunction] = {}
        self.runtimes: dict[str, object] = {}
        self._build_inner_queries()

        # --- per-key state instances ---
        self.template_states = {name: qr.state
                                for name, qr in self.runtimes.items()}
        self.stateless = all(self._is_stateless(qr)
                             for qr in self.runtimes.values())
        self.instances: dict = {}  # key -> {qname: state pytree}
        self.last_seen: dict = {}  # key -> last routed ts
        self._active_key = None  # reentrancy guard for _run_keyed
        self._purge_idle_ms: Optional[int] = None
        ann = next((a for a in partition.annotations or ()
                    if a.name.lower() == "purge"), None)
        if ann is not None:
            idle = ann.element("idle.period") or ann.element("idlePeriod")
            if idle:
                self._purge_idle_ms = _parse_annotation_time(idle)

        # --- mesh-sharded execution (key-slot axis), when eligible ---
        self._mesh_step = None
        self._init_mesh_path()

        # --- routing subscriptions ---
        for sid, proxy in self.proxies.items():
            outer = app_runtime.junctions[sid]
            if sid in self.key_specs:
                outer.subscribe(_PartitionStreamReceiver(self, sid))
            else:
                outer.subscribe(_GlobalStreamReceiver(self, sid))

    # ------------------------------------------------------------------- mesh

    def _init_mesh_path(self) -> None:
        """Swap the per-key host loop for one SPMD step over a key-slot axis
        (parallel/sharded.PartitionedQueryStep) when a mesh is configured and
        the partition shape supports it: a single value-partitioned stream
        feeding a single plain query. Range partitions, joins/patterns,
        inner `#streams`, `in Table` deps, and `@purge` (slot states are
        permanent) stay on the host loop."""
        mesh = getattr(self.ctx, "mesh", None)
        if mesh is None or self.stateless:
            return
        if self._purge_idle_ms is not None:
            return
        if len(self.key_specs) != 1 or len(self.runtimes) != 1:
            return
        if self.inner_junctions or set(self.proxies) != set(self.key_specs):
            return
        from .query_runtime import QueryRuntime

        ((sid, spec),) = self.key_specs.items()
        ((_, qr),) = self.runtimes.items()
        if spec.is_range or not isinstance(qr, QueryRuntime) or qr.dep_tables:
            return

        from ..ops.groupby import hash_columns
        from ..parallel.sharded import PartitionedQueryStep

        axis = mesh.axis_names[0]
        n_slots = self.ctx.effective_partition_capacity

        def key_fn(batch: EventBatch):
            return hash_columns([spec.value_raw(batch)])

        self._mesh_step = PartitionedQueryStep(
            qr._make_step(), mesh, axis, n_slots, key_fn)
        self._mesh_states, self._mesh_keys = self._mesh_step.init_state(
            qr._init_state())
        self._mesh_qr = qr
        self._mesh_sid = sid
        self._mesh_batches = 0
        self._mesh_key_warned = False

    def _mesh_route(self, batch: EventBatch, now: int) -> None:
        import time as _time

        qr = self._mesh_qr
        t0 = _time.perf_counter_ns()
        debugger = getattr(self.ctx, "debugger", None)
        if debugger is not None:
            from .debugger import QueryTerminal
            if debugger.wants(qr.name, QueryTerminal.IN):
                debugger.check_break_point(
                    qr.name, QueryTerminal.IN, batch.to_host_events(qr.codec))
        self._mesh_states, self._mesh_keys, out = self._mesh_step(
            self._mesh_states, self._mesh_keys, batch, now)
        qr._distribute(out, now)
        self.ctx.statistics.track_latency(qr.name, _time.perf_counter_ns() - t0)
        self._mesh_batches += 1
        # key-slot occupancy: checked every batch (the _distribute host fetch
        # already synced the device, so reading count is cheap). Keys that
        # arrive past capacity get slot ids >= n_slots, matching no device
        # slot — their events are DROPPED, and a later small-hash key can
        # evict a live key's table entry (ops/groupby.py sorted merge).
        if not self._mesh_key_warned:
            used = int(self._mesh_keys.count)
            cap = self._mesh_step.n_slots
            if used >= cap:
                import warnings
                warnings.warn(
                    f"partition {self.name!r}: all {cap} key slots used — "
                    "events for any further partition keys are dropped; "
                    "raise partition_capacity", stacklevel=2)
                self._mesh_key_warned = True
        if (self._mesh_qr._has_custom_aggs
                and (self._mesh_batches in (1, 16, 64)
                     or self._mesh_batches % 256 == 0)):
            self._check_mesh_agg_capacity()

    def _check_mesh_agg_capacity(self) -> None:
        """Per-slot distinctCount pair tables overflow independently; warn on
        the fullest slot (mirrors QueryRuntime._check_custom_agg_capacity)."""
        import warnings

        from ..ops.groupby import GroupState, KeyTable
        for g in self._mesh_states[1].groups:
            if not (isinstance(g, tuple) and g):
                continue
            if isinstance(g[0], KeyTable):
                kt = g[0]
                cap = kt.keys.shape[-1] // 2  # hash array is 2x id capacity
                worst = int(np.max(np.asarray(kt.count)))
                if worst > int(0.85 * cap):
                    warnings.warn(
                        f"partition {self.name!r}: a key slot's distinctCount "
                        f"pair table is at {worst}/{cap} lifetime-unique "
                        "pairs; counts will corrupt past capacity — raise "
                        "group_capacity", stacklevel=2)
                elif int(np.max(np.asarray(kt.misses))) > 0:
                    warnings.warn(
                        f"partition {self.name!r}: key lookups exhausted "
                        "their hash probe window and aliased group 0 — raise "
                        "group_capacity", stacklevel=2)
            elif isinstance(g[0], GroupState) and len(g) == 2:
                # string-code fast path: pair table indexed by interning code
                cap = g[0].values.shape[-1]
                n_codes = len(self.ctx.global_strings)
                if n_codes > int(0.85 * cap):
                    warnings.warn(
                        f"partition {self.name!r}: distinctCount code table "
                        f"at {n_codes}/{cap} interned strings; codes past "
                        "capacity are dropped from the count — raise "
                        "group_capacity", stacklevel=2)

    # ------------------------------------------------------------------ build

    def _proxy_for(self, sid: str) -> StreamJunction:
        if sid not in self.proxies:
            outer = self.rt.junctions.get(sid)
            if outer is None:
                raise DefinitionNotExistError(
                    f"stream {sid!r} (used in partition) is not defined")
            proxy = StreamJunction(outer.definition, self.ctx, codec=outer.codec)
            # @OnError(action='STREAM') failures inside the partition route to
            # the same !stream as outside it
            proxy.fault_junction = outer.fault_junction
            self.proxies[sid] = proxy
        return self.proxies[sid]

    def _resolve_input(self, sid: str, is_inner: bool) -> StreamJunction:
        if is_inner:
            j = self.inner_junctions.get(sid)
            if j is None:
                raise DefinitionNotExistError(
                    f"inner stream #{sid} consumed before any query inserts "
                    "into it (order inner queries producer-first)")
            return j
        if sid in self.rt.windows:
            return self.rt.windows[sid].output_junction
        return self._proxy_for(sid)

    def _build_inner_queries(self) -> None:
        from .join_runtime import JoinQueryRuntime, _JoinSideReceiver
        from .pattern_runtime import PatternQueryRuntime, _PatternSideReceiver
        from .query_runtime import QueryRuntime

        rt = self.rt
        for i, query in enumerate(self.partition.queries):
            name = query.name or f"{self.name}_query{i + 1}"
            refs = _referenced_streams(query)
            # resolve inputs through proxies/inner junctions
            jmap = {}
            for sid, is_inner in refs:
                if sid in rt.tables or sid in rt.aggregations:
                    continue
                jmap[sid] = self._resolve_input(sid, is_inner)

            ins = query.input_stream
            if isinstance(ins, JoinInputStream):
                qr = JoinQueryRuntime(query, self.ctx, jmap, rt.tables,
                                      self.ctx.registry, name,
                                      windows=rt.windows,
                                      aggregations=rt.aggregations)
                if qr.left.junction is not None:
                    qr.left.junction.subscribe(_JoinSideReceiver(qr, True))
                if qr.right.junction is not None:
                    qr.right.junction.subscribe(_JoinSideReceiver(qr, False))
            elif isinstance(ins, StateInputStream):
                qr = PatternQueryRuntime(query, self.ctx, jmap, rt.tables,
                                         self.ctx.registry, name)
                for sid in qr.junctions:
                    qr.junctions[sid].subscribe(_PatternSideReceiver(qr, sid))
            elif isinstance(ins, SingleInputStream):
                junction = jmap.get(ins.stream_id)
                if junction is None:
                    raise DefinitionNotExistError(
                        f"stream {ins.stream_id!r} is not defined")
                qr = QueryRuntime(query, self.ctx, junction, self.ctx.registry,
                                  name=name, tables=rt.tables)
                junction.subscribe(qr)
            else:
                raise SiddhiAppCreationError(
                    f"{type(ins).__name__} queries are not supported in partitions")

            self._wire_inner_output(qr, query)
            qr._partitioned = True  # app-level heartbeat must not drive these
            self.runtimes[name] = qr
            rt.query_runtimes[name] = qr  # query callbacks reach inner queries

    def _wire_inner_output(self, qr, query: Query) -> None:
        out = query.output_stream
        if out.action == OutputAction.INSERT and out.target_id:
            if out.is_inner:
                # `insert into #Inner` — partition-scoped stream; schema comes
                # from the producing query (reference: PartitionRuntimeImpl:85)
                j = self.inner_junctions.get(out.target_id)
                if j is None:
                    j = StreamJunction(qr.output_definition, self.ctx,
                                       codec=qr.output_codec)
                    self.inner_junctions[out.target_id] = j
                qr.output_junction = j
                return
        # outer targets (streams/tables/windows) exit the partition
        self.rt._wire_output(qr, query)

    @staticmethod
    def _is_stateless(qr) -> bool:
        from ..ops.ratelimit import PassThroughLimiter
        from ..ops.windows import PassThroughWindow
        from .query_runtime import QueryRuntime

        if not isinstance(qr, QueryRuntime):
            return False  # joins/patterns always keep state
        return (isinstance(qr.window, PassThroughWindow)
                and not qr.selector.agg_specs
                and not (qr.query.selector.group_by or ())
                and isinstance(qr.rate_limiter, PassThroughLimiter))

    # ---------------------------------------------------------------- routing

    def _instance(self, key):
        inst = self.instances.get(key)
        if inst is None:
            # fresh per-key buffers: steps donate their state args, so
            # instances must never alias the template (or each other)
            inst = {name: jax.tree_util.tree_map(jnp.copy,
                                                 self.template_states[name])
                    for name in self.runtimes}
            self.instances[key] = inst
        return inst

    def route(self, sid: str, batch: EventBatch, now: int) -> None:
        if self._mesh_step is not None:
            self._mesh_route(batch, now)
            return
        proxy = self.proxies[sid]
        spec = self.key_specs[sid]
        if self.stateless and not spec.is_range:
            # value partitions: every valid event has a key, and with no
            # per-key state one full-width pass is semantically identical
            proxy.publish_batch(batch, now)
            return
        valid = np.asarray(batch.valid)
        if not valid.any():
            # timer batch: heartbeat every live instance so time windows fire
            for key in list(self.instances):
                self._run_keyed(key, lambda: proxy.publish_batch(batch, now))
            return
        if spec.is_range:
            # events matching no range are dropped (reference:
            # PartitionStreamReceiver — a null key routes nowhere)
            for key, fn in spec.ranges:
                mask = np.asarray(fn(batch)) & valid
                if mask.any():
                    sub = dataclasses.replace(batch, valid=jnp.asarray(mask))
                    self.last_seen[key] = now
                    self._run_keyed(key, lambda s=sub: proxy.publish_batch(s, now))
            return
        keys = np.asarray(spec.value_fn(batch))
        for key in np.unique(keys[valid]).tolist():
            mask = (keys == key) & valid
            sub = dataclasses.replace(batch, valid=jnp.asarray(mask))
            self.last_seen[key] = now
            self._run_keyed(key, lambda s=sub: proxy.publish_batch(s, now))

    def broadcast(self, sid: str, batch: EventBatch, now: int) -> None:
        """Non-partitioned stream feeding inner queries: goes to every live
        key instance (reference: PartitionStreamReceiver broadcast path)."""
        proxy = self.proxies[sid]
        if self.stateless:
            proxy.publish_batch(batch, now)
            return
        for key in list(self.instances):
            self._run_keyed(key, lambda: proxy.publish_batch(batch, now))

    def _run_keyed(self, key, action: Callable) -> None:
        # re-entrancy: an inner query inserting into an outer stream consumed
        # by this same partition re-enters here synchronously. Same key →
        # states are already live, run in place; different key → push/pop so
        # the active key's mid-batch state survives the nested run.
        if self._active_key is not None and key == self._active_key:
            action()
            return
        inst = self._instance(key)
        prev_states = {name: qr.state for name, qr in self.runtimes.items()}
        prev_key, self._active_key = self._active_key, key
        for name, qr in self.runtimes.items():
            qr.state = inst[name]
        try:
            action()
        finally:
            for name, qr in self.runtimes.items():
                inst[name] = qr.state
                qr.state = prev_states[name]
            self._active_key = prev_key

    # ----------------------------------------------------------------- timers

    def heartbeat(self, now: int) -> None:
        if self._mesh_step is not None:
            # one all-invalid batch heartbeats every key slot on device
            proxy = self.proxies[self._mesh_sid]
            empty = EventBatch.empty(proxy.definition, proxy.batch_size)
            self._mesh_route(empty, now)
            return
        if self._purge_idle_ms is not None:
            cutoff = now - self._purge_idle_ms
            for key in [k for k, ts in self.last_seen.items() if ts < cutoff]:
                self.instances.pop(key, None)
                self.last_seen.pop(key, None)
        if self.stateless:
            return
        for key in list(self.instances):
            self._run_keyed(
                key, lambda: [j.heartbeat(now) for j in self.proxies.values()])

    @property
    def has_time_semantics(self) -> bool:
        return any(getattr(qr, "has_time_semantics", False)
                   for qr in self.runtimes.values())

    # --------------------------------------------------------------- snapshot

    def snapshot_states(self, fetch: Optional[Callable] = None,
                        prefix: str = ""):
        """`fetch(key, state)` is SnapshotService's identity-memoized
        device-delta fetch; standalone callers get a plain host copy."""
        from ..state.persistence import _to_host
        if fetch is None:
            fetch = lambda _k, s: _to_host(s)  # noqa: E731

        if self._mesh_step is not None:
            return {"__mesh_states__": fetch(prefix + "ms", self._mesh_states),
                    "__mesh_keys__": fetch(prefix + "mk", self._mesh_keys)}
        return {repr(k): {n: fetch(f"{prefix}{k!r}:{n}", s)
                          for n, s in inst.items()}
                for k, inst in self.instances.items()}

    def restore_states(self, snap) -> None:
        import ast

        from ..errors import CannotRestoreStateError
        from ..state.persistence import _to_device
        if self._mesh_step is not None:
            if set(snap) != {"__mesh_states__", "__mesh_keys__"}:
                raise CannotRestoreStateError(
                    "snapshot was taken without a mesh; cannot restore into a "
                    "mesh-sharded partition (or vice versa)")
            self._mesh_states = _to_device(
                snap["__mesh_states__"], self._mesh_states)
            self._mesh_keys = _to_device(snap["__mesh_keys__"], self._mesh_keys)
            return
        self.instances = {}
        now = self.ctx.timestamp_generator.current_time()
        for k_repr, inst in snap.items():
            key = ast.literal_eval(k_repr)  # int/float/str keys only
            states = {}
            for n, s in inst.items():
                if n not in self.template_states:
                    raise CannotRestoreStateError(
                        f"partition snapshot has unknown query {n!r} "
                        "(app definition changed?)")
                states[n] = _to_device(s, self.template_states[n])
            self.instances[key] = states
            self.last_seen[key] = now  # restored keys age from restore time


class _PartitionStreamReceiver(Receiver):
    """Reference: core/partition/PartitionStreamReceiver.java:44."""

    def __init__(self, runtime: PartitionRuntime, sid: str) -> None:
        self.runtime = runtime
        self.sid = sid

    def on_batch(self, batch: EventBatch, now: int) -> None:
        self.runtime.route(self.sid, batch, now)


class _GlobalStreamReceiver(Receiver):
    def __init__(self, runtime: PartitionRuntime, sid: str) -> None:
        self.runtime = runtime
        self.sid = sid

    def on_batch(self, batch: EventBatch, now: int) -> None:
        self.runtime.broadcast(self.sid, batch, now)
