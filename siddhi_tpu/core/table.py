"""Tables: CRUD event stores queryable from streams.

Reference: core/table/InMemoryTable.java:58 (rows under a RW lock, CRUD via
CompiledCondition built by OperatorParser; index planning via
core/table/holder/IndexEventHolder + the CollectionExecutor mini-optimizer).

TPU-native design: a table is a **columnar device store** — capacity-padded
arrays + validity mask held as a pytree (`TableState`) so table contents can be
passed *into* jitted query steps as arguments (contents change between
batches; they must never be baked into a trace as constants). CRUD is
vectorized:

- conditions compile once into broadcastable column functions: stream frames
  enter the scope as [B,1] columns, the table frame as [C] columns, so any
  mixed condition evaluates to a [B,C] cross mask — the TPU analogue of the
  reference's per-event `Operator.find` walks;
- delete = any-over-B of the mask clears row validity;
- update = last-matching-event-wins gather (the reference applies events
  sequentially; per-row multi-event read-modify-write chains are the one
  divergence, documented in tests);
- insert/update-or-insert scatter into free slots computed by stable argsort
  of the validity mask.

The reference's primary-key/index holders become: primary key = compiled
key-equality condition used by update-or-insert/contains fast paths; duplicate
primary-key inserts are dropped (reference throws; we surface a counter).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import CapacityExceededError, SiddhiAppCreationError
from ..ops.search import stable_partition_order
from ..query_api.definition import AttributeType, TableDefinition
from ..query_api.execution import OutputAction, OutputStream, UpdateSetAttribute
from ..query_api.expression import Compare, CompareOp, Expression, Variable
from . import dtypes
from .context import SiddhiAppContext
from .event import EventBatch, EventType, StreamCodec


class TableState(NamedTuple):
    """Device-resident table contents (a pytree; jit-argument friendly)."""

    cols: dict
    ts: jax.Array  # int64[C]
    valid: jax.Array  # bool[C]


def _broadcast_scope(scope, table_id: str, tstate: TableState):
    """Clone a [B]-shaped scope into a [B,1]-shaped one and add the [C] table
    frame, so compiled conditions evaluate to [B,C] cross masks."""
    from ..ops.expr_compile import Scope

    s2 = Scope()
    for ref, cols in scope.frames.items():
        s2.add_frame(
            ref,
            {k: v[:, None] for k, v in cols.items()},
            scope.ts[ref][:, None],
            scope.valids[ref][:, None],
            default=(ref == scope.default_frame),
        )
    s2.add_frame(table_id, tstate.cols, tstate.ts, tstate.valid)
    s2.extras = dict(scope.extras)
    return s2


class InMemoryTable:
    """Host handle owning the device TableState + compiled per-query ops."""

    def __init__(self, definition: TableDefinition, ctx: SiddhiAppContext,
                 capacity: Optional[int] = None) -> None:
        self.definition = definition
        self.ctx = ctx
        self.codec = StreamCodec(definition, ctx.global_strings)
        cap_ann = definition.annotation("capacity") if definition.annotations else None
        self.capacity = capacity or (
            int(cap_ann.element(None)) if cap_ann is not None and cap_ann.element(None)
            else dtypes.config.default_table_capacity)
        self.attr_types = {a.name: a.type for a in definition.attributes
                          if a.type != AttributeType.OBJECT}
        self._state = TableState(
            cols={n: jnp.zeros((self.capacity,), dtypes.device_dtype(t))
                  for n, t in self.attr_types.items()},
            ts=jnp.zeros((self.capacity,), dtypes.TS_DTYPE),
            valid=jnp.zeros((self.capacity,), jnp.bool_),
        )
        # @PrimaryKey('a' [, 'b']) — reference: EventHolderPasser.java reads it
        # to pick an IndexEventHolder.
        pk = definition.annotation("PrimaryKey") if definition.annotations else None
        self.primary_keys: tuple[str, ...] = tuple(
            e.value for e in pk.elements) if pk is not None else ()
        # @Index('a' [, 'b']) — reference: IndexEventHolder.java:60 secondary
        # TreeMap indexes. TPU form: a sorted copy of each indexed column
        # (invalid rows sort to the end as dtype-max sentinels) rebuilt
        # lazily after mutations; equality probes binary-search it instead
        # of scanning the [B, C] cross mask.
        idx_ann = definition.annotation("Index") if definition.annotations else None
        self.index_attrs: tuple[str, ...] = tuple(
            e.value for e in idx_ann.elements) if idx_ann is not None else ()
        for a in self.index_attrs:
            if a not in self.attr_types:
                raise SiddhiAppCreationError(
                    f"@Index({a!r}): no such attribute on {definition.id!r}")
            if self.attr_types[a] == AttributeType.BOOL:
                raise SiddhiAppCreationError(
                    f"@Index({a!r}): bool attributes are not indexable")
        self._indexes = None  # dict[attr, (sorted_vals[C], n_live)] | None
        self._index_fn = None
        self.dropped_duplicates = 0
        self._insert_fn = jax.jit(self._make_insert())

    # ------------------------------------------------------------------ state

    @property
    def state(self) -> TableState:
        return self._state

    @state.setter
    def state(self, new_state: TableState) -> None:
        self._state = new_state
        self._indexes = None  # any mutation invalidates the sorted copies

    def clear(self) -> None:
        """Reset to empty, keeping compiled kernels and capacity."""
        self.state = TableState(
            cols={k: jnp.zeros_like(v) for k, v in self._state.cols.items()},
            ts=jnp.zeros_like(self._state.ts),
            valid=jnp.zeros_like(self._state.valid),
        )

    def probe_indexes(self) -> dict:
        """Sorted-copy indexes for in-kernel equality probes; rebuilt lazily
        (one jitted sort per indexed column) after mutations."""
        indexable = tuple(sorted(self.indexable_eq_attrs()))
        if not indexable:
            return {}
        if self._indexes is None:
            if self._index_fn is None:
                attrs = indexable

                def build(tstate: TableState):
                    out = {}
                    n_live = jnp.sum(tstate.valid, dtype=jnp.int32)
                    for a in attrs:
                        col = tstate.cols[a]
                        if jnp.issubdtype(col.dtype, jnp.floating):
                            big = jnp.asarray(jnp.inf, col.dtype)
                        else:
                            big = jnp.asarray(jnp.iinfo(col.dtype).max,
                                              col.dtype)
                        keys = jnp.where(tstate.valid, col, big)
                        out[a] = (jnp.sort(keys), n_live)
                    return out

                self._index_fn = jax.jit(build)
            self._indexes = self._index_fn(self._state)
        return self._indexes

    # ------------------------------------------------------------------ insert

    def _make_insert(self):
        pk = self.primary_keys

        def insert(tstate: TableState, batch: EventBatch):
            C = tstate.ts.shape[0]
            B = batch.ts.shape[0]
            ins = batch.valid
            if pk:
                # drop rows whose primary key already exists (reference throws
                # PrimaryKeyViolationException; we drop + count host-side)
                eq = jnp.ones((B, C), bool)
                for k in pk:
                    eq = eq & (batch.cols[k][:, None] == tstate.cols[k][None, :])
                dup = (eq & tstate.valid[None, :]).any(axis=1)
                # also dedupe within the batch: keep first occurrence
                eq_b = jnp.ones((B, B), bool)
                for k in pk:
                    eq_b = eq_b & (batch.cols[k][:, None] == batch.cols[k][None, :])
                earlier = jnp.tril(jnp.ones((B, B), bool), k=-1)
                dup_in_batch = (eq_b & earlier & ins[None, :]).any(axis=1)
                ins = ins & ~dup & ~dup_in_batch
            n_ins = jnp.sum(ins.astype(jnp.int32))
            # free slots in row order: argsort(valid) puts False (free) first
            free_order = stable_partition_order(~tstate.valid)
            n_free = jnp.sum((~tstate.valid).astype(jnp.int32))
            rank = jnp.cumsum(ins.astype(jnp.int32)) - 1
            fits = ins & (rank < n_free)
            slot = jnp.where(fits, free_order[jnp.clip(rank, 0, C - 1)], C)
            new_cols = {k: v.at[slot].set(batch.cols[k], mode="drop")
                        for k, v in tstate.cols.items()}
            new_ts = tstate.ts.at[slot].set(batch.ts, mode="drop")
            new_valid = tstate.valid.at[slot].set(True, mode="drop")
            overflow = n_ins - jnp.sum(fits.astype(jnp.int32))
            dropped = jnp.sum((batch.valid & ~ins).astype(jnp.int32))
            return TableState(new_cols, new_ts, new_valid), overflow, dropped

        return insert

    def insert_batch(self, batch: EventBatch) -> None:
        new_state, overflow, dropped = self._insert_fn(self.state, batch)
        ov = int(overflow)
        if ov:
            # all-or-nothing: leave self.state untouched on overflow
            raise CapacityExceededError(
                f"table {self.definition.id} capacity {self.capacity} exceeded "
                f"({ov} rows would be dropped)")
        self.state = new_state
        self.dropped_duplicates += int(dropped)

    def insert_rows(self, rows, timestamp: int = 0) -> None:
        cols = self.codec.rows_to_columns(rows, n_pad=len(rows))
        ts = np.full(len(rows), timestamp, dtype=np.int64)
        self.insert_batch(EventBatch.from_numpy(ts, cols, len(rows)))

    # ------------------------------------------------------------------ reads

    def find_mask(self, cond: Optional[Callable], scope) -> jax.Array:
        """[B,C] cross mask of (stream event, table row) matches. `cond` is a
        compiled condition; None matches every valid row."""
        s2 = _broadcast_scope(scope, self.definition.id, self.state)
        B = next(iter(scope.valids.values())).shape[0]
        m = jnp.ones((B, self.capacity), bool) if cond is None else \
            jnp.broadcast_to(cond(s2), (B, self.capacity))
        return m & self.state.valid[None, :]

    def contains_probe(self, scope, inner, eq_plan=None) -> jax.Array:
        """`expr in Table` membership (reference: InConditionExpressionExecutor):
        any-match over table rows per stream lane. Reads the table state from
        scope.extras so jitted steps see fresh contents each call.

        When the condition is a single equality on an @Index'd (or sole
        primary-key) attribute, `eq_plan` carries (attr, stream_expr) and the
        probe binary-searches the sorted index — O(B log C) instead of the
        [B, C] cross mask (reference: IndexEventHolder index-aware plans)."""
        tid = self.definition.id
        if eq_plan is not None:
            attr, sexpr = eq_plan
            idx = scope.extras.get(f"tableidx:{tid}")
            if idx and attr in idx:
                from ..ops.search import searchsorted32
                sorted_vals, n_live = idx[attr]
                C = sorted_vals.shape[0]
                v = sexpr(scope).astype(sorted_vals.dtype)
                pos = searchsorted32(sorted_vals, v, side="left")
                return (pos < n_live) & \
                    (sorted_vals[jnp.clip(pos, 0, C - 1)] == v)
        tstate: TableState = scope.extras.get(f"table:{tid}", self.state)
        s2 = _broadcast_scope(scope, tid, tstate)
        if inner is None:
            raise SiddhiAppCreationError("`in Table` requires a condition")
        m = inner(s2) & tstate.valid
        return m.any(axis=-1)

    def indexable_eq_attrs(self) -> set:
        """Attributes whose equality probes can use a sorted index."""
        out = set(self.index_attrs)
        if len(self.primary_keys) == 1:
            out.add(self.primary_keys[0])
        return out

    def all_rows(self) -> list[tuple]:
        batch = EventBatch(ts=self.state.ts, cols=self.state.cols,
                           valid=self.state.valid,
                           types=jnp.zeros((self.capacity,), jnp.int8))
        return [e.data for e in batch.to_host_events(self.codec)]

    def __len__(self) -> int:
        return int(jnp.sum(self.state.valid))


class TableOutputExecutor:
    """Compiled runtime for one query output targeting a table — the analogue
    of the reference's {Delete,Update,UpdateOrInsert}TableCallback +
    OperatorParser-compiled Operator.

    Built once per query at plan time; executes as one jitted device function
    `(table_state, out_batch) -> table_state'`.
    """

    def __init__(self, table: InMemoryTable, output_stream: OutputStream,
                 out_types: dict[str, AttributeType],
                 out_codec: StreamCodec, registry,
                 out_frame_aliases: Sequence[str] = ()) -> None:
        from ..ops.expr_compile import Scope, TypeResolver, compile_expression

        self.table = table
        self.action = output_stream.action
        tid = table.definition.id

        # resolver over {output-stream frame} + {table frame}; the ON condition
        # may reference output attrs via the query's input-stream name
        # (reference: the matching meta carries the stream alias)
        frames = {"__out__": dict(out_types), tid: dict(table.attr_types)}
        codecs = {"__out__": out_codec, tid: table.codec}
        for alias in out_frame_aliases:
            if alias and alias not in frames:
                frames[alias] = dict(out_types)
                codecs[alias] = out_codec
        self.out_frame_aliases = tuple(
            a for a in out_frame_aliases if a and a != tid)
        resolver = TypeResolver(frames, "__out__", codecs)

        cond = output_stream.on_condition
        if cond is None:
            raise SiddhiAppCreationError(
                f"{self.action.name} into table requires an ON condition")
        self.cond = compile_expression(cond, resolver, registry)
        if self.cond.type != AttributeType.BOOL:
            raise SiddhiAppCreationError("table ON condition must be boolean")

        # SET clause (update/update-or-insert); default: set every table attr
        # from the same-named output attr (reference: UpdateSet defaults)
        sets: list[tuple[str, object]] = []
        if output_stream.set_attributes:
            for sa in output_stream.set_attributes:
                if sa.table_variable.stream_id not in (None, tid):
                    raise SiddhiAppCreationError(
                        f"SET target must be a {tid} attribute")
                sets.append((sa.table_variable.attribute,
                             compile_expression(sa.expression, resolver, registry)))
        else:
            for name, t in table.attr_types.items():
                if name in out_types:
                    sets.append((name, compile_expression(
                        Variable(name, stream_id="__out__"), resolver, registry)))
        self.sets = sets

        self._fn = jax.jit(self._make())

    def _make(self):
        from ..ops.expr_compile import Scope

        table = self.table
        tid = table.definition.id
        action = self.action
        cond = self.cond
        sets = self.sets

        aliases = self.out_frame_aliases

        def run(tstate: TableState, out: EventBatch):
            B = out.ts.shape[0]
            C = tstate.ts.shape[0]
            scope = Scope()
            scope.add_frame("__out__", out.cols, out.ts, out.valid, default=True)
            for alias in aliases:
                scope.add_frame(alias, out.cols, out.ts, out.valid)
            s2 = _broadcast_scope(scope, tid, tstate)
            mask = jnp.broadcast_to(cond(s2), (B, C))
            mask = mask & out.valid[:, None] & tstate.valid[None, :]

            if action == OutputAction.DELETE:
                hit = mask.any(axis=0)
                return TableState(tstate.cols, tstate.ts, tstate.valid & ~hit), \
                    jnp.int32(0)

            # update: last matching event wins per row
            has = mask.any(axis=0)
            b_star = (B - 1) - jnp.argmax(mask[::-1, :], axis=0)  # [C]
            new_cols = dict(tstate.cols)
            rows = jnp.arange(C)
            for name, ce in sets:
                vals = jnp.broadcast_to(ce(s2), (B, C))  # [B,C]
                picked = vals[b_star, rows].astype(tstate.cols[name].dtype)
                new_cols[name] = jnp.where(has, picked, tstate.cols[name])
            updated = TableState(new_cols, tstate.ts, tstate.valid)

            if action == OutputAction.UPDATE:
                return updated, jnp.int32(0)

            # update-or-insert: events matching no row are inserted
            ev_matched = mask.any(axis=1)
            to_insert = dataclasses.replace(out, valid=out.valid & ~ev_matched)
            return updated, to_insert

        return run

    def apply(self, out: EventBatch) -> None:
        if self.action == OutputAction.UPDATE_OR_INSERT:
            new_state, to_insert = self._fn(self.table.state, out)
            self.table.state = new_state
            self.table.insert_batch(to_insert)
        else:
            self.table.state, _ = self._fn(self.table.state, out)
