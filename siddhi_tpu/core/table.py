"""Tables: CRUD event stores queryable from streams.

Reference: core/table/InMemoryTable.java:58 (rows under a RW lock, CRUD via
CompiledCondition) with index-aware planning (core/table/holder/IndexEventHolder
+ the CollectionExecutor mini-optimizer). TPU round-1 design: a table is a
columnar device store (capacity-padded arrays + valid mask) supporting
vectorized insert/find/delete/update, with host-side primary-key hash index for
point operations. Joins probe tables on device.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import CapacityExceededError, SiddhiAppCreationError
from ..query_api.definition import AttributeType, TableDefinition
from ..query_api.execution import OutputAction, OutputStream
from . import dtypes
from .context import SiddhiAppContext
from .event import EventBatch, StreamCodec


class InMemoryTable:
    def __init__(self, definition: TableDefinition, ctx: SiddhiAppContext,
                 capacity: Optional[int] = None) -> None:
        self.definition = definition
        self.ctx = ctx
        self.codec = StreamCodec(definition)
        self.capacity = capacity or dtypes.config.default_window_capacity
        self.cols = {
            a.name: jnp.zeros((self.capacity,), dtypes.device_dtype(a.type))
            for a in definition.attributes if a.type != AttributeType.OBJECT
        }
        self.ts = jnp.zeros((self.capacity,), dtypes.TS_DTYPE)
        self.valid = jnp.zeros((self.capacity,), jnp.bool_)
        self._next = 0  # next free slot (append pointer; freed slots reused lazily)

    # ------------------------------------------------------------------- CRUD

    def insert_batch(self, batch: EventBatch) -> None:
        valid = np.asarray(batch.valid)
        idxs = np.nonzero(valid)[0]
        n = len(idxs)
        if n == 0:
            return
        # find free slots (host-side append pointer with compaction fallback)
        free = np.nonzero(~np.asarray(self.valid))[0]
        if len(free) < n:
            raise CapacityExceededError(
                f"table {self.definition.id} capacity {self.capacity} exceeded")
        slots = jnp.asarray(free[:n])
        src = jnp.asarray(idxs)
        for k in self.cols:
            self.cols[k] = self.cols[k].at[slots].set(batch.cols[k][src])
        self.ts = self.ts.at[slots].set(batch.ts[src])
        self.valid = self.valid.at[slots].set(True)

    def insert_rows(self, rows, timestamp: int = 0) -> None:
        cols = self.codec.rows_to_columns(rows, n_pad=len(rows))
        ts = np.full(len(rows), timestamp, dtype=np.int64)
        self.insert_batch(EventBatch.from_numpy(ts, cols, len(rows)))

    def apply_output(self, action: OutputAction, out: EventBatch,
                     output_stream: OutputStream) -> None:
        """Handle `insert into T` / `delete T on ...` / `update T ...` from a
        query's output batch (reference: core/query/output/callback/
        {InsertIntoTable,DeleteTable,UpdateTable,UpdateOrInsertTable}Callback)."""
        from ..ops.expr_compile import Scope, TypeResolver, compile_expression

        if action == OutputAction.INSERT:
            self.insert_batch(out)
            return

        # Build a scope where the table frame is the stored columns [C] and the
        # stream frame is the output batch [B]; the on-condition is evaluated
        # as a [B, C] cross mask via vmap over the batch axis.
        raise SiddhiAppCreationError(
            "delete/update table outputs are planned via TableOutputExecutor")

    # ------------------------------------------------------------------ reads

    def all_rows(self) -> list[tuple]:
        batch = EventBatch(ts=self.ts, cols=self.cols, valid=self.valid,
                           types=jnp.zeros((self.capacity,), jnp.int8))
        return [e.data for e in batch.to_host_events(self.codec)]

    def __len__(self) -> int:
        return int(jnp.sum(self.valid))
