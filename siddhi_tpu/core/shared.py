"""Shared-execution groups: N co-resident queries, ONE compiled step.

The execution half of the multi-query optimizer (analysis/optimizer.py is
the plan-level half). `build_shared_groups` walks every junction's receiver
list, finds maximal CONTIGUOUS runs of eligible single-input QueryRuntimes
with the same dispatch shape, and splices each run out for a single
SharedStepGroup receiver. The group traces every member's untracked step
body inside one `jax.jit`:

    fused((s1..sN), batch, now) -> ((s1'..sN'), (out1..outN))

so one junction delivery drives all members, one XLA compile covers the
whole group per shape bucket, and XLA's own CSE computes shared scans /
common subexpressions once — the rewrites the plan pass detects
(shared-scan + predicate vectorization, CSE) fall out of tracing together,
with per-member math EXACTLY the graph the unfused step would run. That is
the parity argument: optimizer-on output is bit-identical to optimizer-off
(tests/test_optimizer_parity.py proves it).

What stays per-member: the state tuple (written back after every fused
step, so SnapshotService / restore / upgrade / collect_overflow see the
unfused layout unchanged), callbacks, output junctions, rate limiting,
latency attribution, and the post-step maintenance hooks. Contiguous-run
formation preserves global delivery order exactly — a fused run replaces
its first member's slot, and receivers outside the run never move.

Queries that would change isolation semantics under fusion are DECLINED
loudly (@breaker, partitions, OBJECT attributes, table dependencies,
custom-aggregate compaction) — the reasons surface through SL114 and
statistics_report()["optimizer"]["declined"].
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..query_api.definition import AttributeType
from . import dtypes
from .event import EventBatch
from .query_runtime import QueryRuntime, _sink_dark, aot_warm
from .stream import Receiver

from ..analysis.optimizer import (
    DECLINE_BREAKER,
    DECLINE_CUSTOM_AGG,
    DECLINE_FAULT,
    DECLINE_JOIN_PATTERN,
    DECLINE_OBJECT,
    DECLINE_PARTITION,
    DECLINE_TABLE,
    SPLICE_DECLINE_CAP,
    SPLICE_DECLINE_SHAPE,
    analyze_sharing,
)


#: default ceiling on members per fused group. XLA compile time (and, on
#: CPU, executable quality) degrade superlinearly with graph size; chunking
#: a 256-query run into ceil(256/cap) groups keeps every graph tractable
#: while the compile count stays O(N/cap) — still sublinear in queries.
_DEFAULT_GROUP_CAP = 32


def group_cap() -> int:
    """Members-per-group ceiling (env SIDDHI_OPTIMIZE_GROUP_CAP, min 2)."""
    try:
        cap = int(os.environ.get("SIDDHI_OPTIMIZE_GROUP_CAP", "")
                  or _DEFAULT_GROUP_CAP)
    except ValueError:
        cap = _DEFAULT_GROUP_CAP
    return max(cap, 2)


def runtime_decline(qr) -> Optional[str]:
    """Why this receiver cannot join a shared group (None = eligible).
    Extends the static taxonomy (analysis/optimizer.py decline_reason) with
    the runtime-only facts: table fallbacks and custom-aggregate state."""
    if type(qr) is not QueryRuntime:
        return DECLINE_JOIN_PATTERN
    if getattr(qr, "_partitioned", False):
        return DECLINE_PARTITION
    if qr.breaker is not None:
        return DECLINE_BREAKER
    if qr.query.input_stream.is_fault:
        return DECLINE_FAULT
    if any(a.type == AttributeType.OBJECT
           for a in qr.input_junction.definition.attributes):
        return DECLINE_OBJECT
    if qr.dep_tables or qr._in_fallbacks:
        return DECLINE_TABLE
    if qr._has_custom_aggs:
        return DECLINE_CUSTOM_AGG
    return None


def _apply_pushdown(qr: QueryRuntime) -> int:
    """Predicate pushdown for the provably-safe shape: a windowless query
    (pass-through emits every surviving arrival as CURRENT, so
    `f | (types != CURRENT)` degenerates to `f`) with no stream functions
    whose computed columns the post filter could read. Moves the compiled
    post-window filters into the pre-window conjunction IN PLACE — both the
    member's own step closure and the fused trace capture these list
    objects, so the rewrite applies to whichever executes. Returns the
    number of predicates moved."""
    from ..ops.windows import PassThroughWindow
    if not isinstance(qr.window, PassThroughWindow):
        return 0
    if qr.pre_window_fns or qr.post_window_fns or not qr.post_filters:
        return 0
    moved = len(qr.post_filters)
    qr.filters.extend(qr.post_filters)
    qr.post_filters.clear()
    return moved


class SharedStepGroup(Receiver):
    """One fused receiver standing in for a contiguous run of member
    QueryRuntimes on the same junction.

    The superstep runner (core/superstep.py) scans groups too: it reuses
    `_steps` (the untracked member step closures) inside its `lax.scan`
    body and `_current_emit_flags()` for its per-dispatch emit/DCE
    revalidation, and replays `_post_step_maintenance` + the equal-share
    telemetry attribution per inner batch — keep those surfaces stable."""

    #: junction._deliver consults this before dispatch; members with
    #: breakers never fuse, so the group itself is never diverted
    breaker = None

    def __init__(self, name: str, members: list[QueryRuntime],
                 junction) -> None:
        assert len(members) >= 2
        self.name = name
        self.members = members
        self.junction = junction
        self.ctx = members[0].ctx
        self._batch_cap = members[0]._batch_cap
        self._bucket_ok = all(m._bucket_ok for m in members)
        self.has_time_semantics = any(m.has_time_semantics for m in members)
        self._batches_seen = 0

        self._steps = [m._make_step(track_compiles=False) for m in members]
        self._emit_flags = self._current_emit_flags()
        self._step = self._make_jit(self._emit_flags)
        self._member_names = [m.name for m in members]
        self._tele_cells = None  # resolved on first telemetry-on batch
        for m in members:
            m._fused_group = self

    def _current_emit_flags(self) -> tuple:
        """Per-member: does anything observe this member's emission? Dark
        members' outputs are DROPPED from the fused return value — XLA then
        dead-code-eliminates their output materialization, so the group
        only pays (device buffers + host jax.Array wrapping) for outputs
        somebody consumes. Flags are the stable part of the dark-sink test
        (receivers/taps/WAL/redirect/statistics), so a staged-row blip
        never forces a retrace; a flag flip (callback attached mid-run)
        rebuilds the jit once — one tracked compile."""
        flags = []
        for m in self.members:
            j = m.output_junction
            observable = (bool(m.callbacks) or m.table_executor is not None
                          or j is None or not _sink_dark(j))
            flags.append(observable)
        return tuple(flags)

    def _make_jit(self, emit_flags: tuple):
        stats = self.ctx.statistics
        gname = self.name
        steps = self._steps

        def fused(states, batch, now):
            # one compile per (group, shape) — vs one per (member, shape).
            # outs is COMPACT (emitting members only, source order): a None
            # placeholder in the traced output pytree would knock every
            # call off pjit's C++ fastpath onto the slow python path
            stats.track_compile(gname, batch.capacity)
            new_states, outs = [], []
            for st, step, emit in zip(states, steps, emit_flags):
                s2, out = step(st, batch, now, None)
                new_states.append(s2)
                if emit:
                    outs.append(out)
            return tuple(new_states), tuple(outs)

        return jax.jit(fused, donate_argnums=(0,))

    # ------------------------------------------------------------- dispatch

    def on_batch(self, batch: EventBatch, now: int) -> None:
        debugger = getattr(self.ctx, "debugger", None)
        if debugger is not None:
            # per-query breakpoints need per-query dispatch: fall back to
            # each member's own step (identical math, separate compiles)
            for m in self.members:
                m.on_batch(batch, now)
            return
        t0 = time.perf_counter_ns()
        if batch.capacity < self._batch_cap and not self._bucket_ok:
            batch = batch.pad_to(self._batch_cap)
        flags = self._current_emit_flags()
        if flags != self._emit_flags:
            # a sink lit up (callback/subscriber attached) or went dark:
            # rebuild the jit so the traced return value matches — costs
            # one retrace, visible in the compile counters
            self._emit_flags = flags
            self._step = self._make_jit(flags)
        states = tuple(m.state for m in self.members)
        new_states, outs = self._step(states, batch, jnp.int64(now))
        # write ALL states back before any distribution: a member's output
        # cascade can re-enter this junction (and this group) synchronously
        for m, s in zip(self.members, new_states):
            m.state = s
        elapsed = time.perf_counter_ns() - t0
        share = elapsed // len(self.members)
        stats = self.ctx.statistics
        meter = getattr(self.ctx, "tenant_meter", None)
        if meter is not None:
            # equal-share attribution, same split as stats/telemetry below
            meter.record_block(self._member_names, share)
        tele = getattr(self.ctx, "telemetry", None)
        outs_it = iter(outs)
        stats_on = stats.detail
        for m, emit in zip(self.members, flags):
            if emit:
                m._distribute(next(outs_it), now)
            # per-query attribution survives fusion: each member reports an
            # equal share of the fused step's wall time
            if stats_on:
                stats.track_latency(m.name, share)
            m._post_step_maintenance()
        if tele is not None and tele.on:
            cells = self._tele_cells
            if cells is None:
                cells = self._tele_cells = [
                    tele.query_cell(n) for n in self._member_names]
            tele.record_query_block(cells, self._member_names, share)
        stats.track_latency(self.name, elapsed)
        if tele is not None:
            sess = tele.profile
            if sess is not None and sess.active:
                w0 = time.perf_counter_ns()
                jax.block_until_ready([m.state for m in self.members])
                wait = time.perf_counter_ns() - w0
                sess.record(self.name, elapsed + wait, wait)
        self._batches_seen += 1

    # -------------------------------------------------------------- warmup

    def warmup(self, buckets=None) -> int:
        """AOT-compile the fused step per lane bucket (see
        QueryRuntime.warmup / aot_warm — compile-only, no execution, no
        state mutation). Returns fresh compiles under the group's name."""
        if buckets is None:
            buckets = (dtypes.bucket_ladder(self._batch_cap)
                       if self._bucket_ok and dtypes.config.shape_buckets
                       and self.ctx.mesh is None else (self._batch_cap,))
        flags = self._current_emit_flags()
        if flags != self._emit_flags:
            self._emit_flags = flags
            self._step = self._make_jit(flags)
        n0 = self.ctx.statistics.compiles.get(self.name, 0)
        now = jnp.int64(self.ctx.timestamp_generator.current_time())
        states = tuple(m.state for m in self.members)
        for cap in buckets:
            batch = EventBatch.empty(self.junction.definition, cap)
            aot_warm(self._step, states, batch, now)
        return self.ctx.statistics.compiles.get(self.name, 0) - n0

    # -------------------------------------------------------------- splice
    #
    # One-retrace membership change: the dark-sink re-light mechanism
    # above (emit-flag flip -> _make_jit once) generalized to the member
    # list itself. `_make_jit` reads `self._steps` when BUILDING the jit,
    # so every splice REBINDS members/_steps/_member_names to fresh lists
    # — the pre-splice jit keeps closing over the old list object and
    # stays valid, which is what makes rollback a pure attribute restore.
    # Sibling state tensors need no migration: states are assembled from
    # `m.state` per dispatch and written back per member, so the unfused
    # layout IS the fused layout (same property snapshots/upgrades rely
    # on). The retrace covers exactly one compile; departing members are
    # dead-code-eliminated the same way dark sinks are.

    def splice_decline(self, qr) -> Optional[str]:
        """Why `qr` cannot splice into THIS group (None = spliceable).
        Extends runtime_decline with the group-shape facts."""
        reason = runtime_decline(qr)
        if reason is not None:
            return reason
        if qr._batch_cap != self._batch_cap:
            return SPLICE_DECLINE_SHAPE
        if len(self.members) >= group_cap():
            return SPLICE_DECLINE_CAP
        return None

    def splice_in(self, qr: QueryRuntime) -> float:
        """Trace `qr` into the group: siblings' step bodies unchanged,
        their state tensors carried over untouched, ONE retrace eagerly
        compiled before return (deploy pays the compile, not traffic).
        Transactional — any failure restores the exact pre-splice
        bindings and re-raises. Returns wall milliseconds spent."""
        snap = (self.members, self._steps, self._member_names,
                self._emit_flags, self._step, self._bucket_ok,
                self.has_time_semantics, self._tele_cells)
        t0 = time.perf_counter_ns()
        try:
            _apply_pushdown(qr)
            self.members = self.members + [qr]
            self._steps = self._steps + [
                qr._make_step(track_compiles=False)]
            self._member_names = self._member_names + [qr.name]
            self._bucket_ok = self._bucket_ok and qr._bucket_ok
            self.has_time_semantics = (self.has_time_semantics
                                       or qr.has_time_semantics)
            self._emit_flags = self._current_emit_flags()
            self._step = self._splice_commit(self._emit_flags)
            self._tele_cells = None
            qr._fused_group = self
        except BaseException:
            (self.members, self._steps, self._member_names,
             self._emit_flags, self._step, self._bucket_ok,
             self.has_time_semantics, self._tele_cells) = snap
            qr._fused_group = None
            raise
        return (time.perf_counter_ns() - t0) / 1e6

    def splice_out(self, qr: QueryRuntime) -> float:
        """Remove `qr` from the group with siblings undisturbed: the
        departing member's step body drops out of the fused return value
        and XLA DCEs it on the (single) retrace. The caller dissolves
        instead when membership would fall below 2. Returns wall ms."""
        idx = self.members.index(qr)
        assert len(self.members) > 2, "dissolve() below 2 members"
        snap = (self.members, self._steps, self._member_names,
                self._emit_flags, self._step, self._bucket_ok,
                self.has_time_semantics, self._tele_cells)
        t0 = time.perf_counter_ns()
        try:
            self.members = self.members[:idx] + self.members[idx + 1:]
            self._steps = self._steps[:idx] + self._steps[idx + 1:]
            self._member_names = (self._member_names[:idx]
                                  + self._member_names[idx + 1:])
            self._bucket_ok = all(m._bucket_ok for m in self.members)
            self.has_time_semantics = any(m.has_time_semantics
                                          for m in self.members)
            self._emit_flags = self._current_emit_flags()
            self._step = self._splice_commit(self._emit_flags)
            self._tele_cells = None
            qr._fused_group = None
        except BaseException:
            (self.members, self._steps, self._member_names,
             self._emit_flags, self._step, self._bucket_ok,
             self.has_time_semantics, self._tele_cells) = snap
            qr._fused_group = self
            raise
        return (time.perf_counter_ns() - t0) / 1e6

    def dissolve(self) -> list:
        """Unfuse every member (group shrank below 2, or a full rebuild
        was requested). Members keep their own steps/state — the caller
        re-inserts them into the junction's receiver slot in order."""
        members = list(self.members)
        for m in members:
            m._fused_group = None
        return members

    def _splice_commit(self, emit_flags: tuple):
        """Build the post-splice jit and eagerly compile it at the group's
        traced capacity, so the one retrace lands inside deploy latency
        instead of stalling the next traffic batch. (Smaller warmed
        buckets of the pre-splice jit recompile lazily if the group is
        bucket-eligible — full-capacity traffic never stalls.)

        The warm is an actual EXECUTION on an empty batch, not just
        lower().compile(): on this jax line the AOT executable is not
        shared with the normal dispatch cache, so a lower-only warm still
        leaves the first traffic batch paying the backend compile (~100s
        of ms — the exact cliff the splice exists to avoid). The step is
        pure and the batch empty, so the run has no observable effect;
        states are deep-copied first because donate_argnums=(0,) would
        otherwise invalidate the live member state buffers.

        A separate method so fault injection (util.faults.inject) can
        fail a splice mid-flight; splice_in/splice_out roll back to the
        pre-splice bindings on any exception raised here."""
        step = self._make_jit(emit_flags)
        now = jnp.int64(self.ctx.timestamp_generator.current_time())
        states = jax.tree_util.tree_map(
            jnp.array, tuple(m.state for m in self.members))
        batch = EventBatch.empty(self.junction.definition, self._batch_cap)
        jax.block_until_ready(step(states, batch, now))
        return step


# ---------------------------------------------------------------- formation


def build_shared_groups(rt) -> dict:
    """Form shared groups on a freshly built SiddhiAppRuntime. Mutates
    junction receiver lists (contiguous-run splice) and per-member filter
    lists (pushdown); returns the runtime optimizer report dict stored as
    rt.optimizer_report and surfaced by statistics_report()["optimizer"].

    MUST run before start()/warmup() and before any traffic: the fused jit
    re-traces member step bodies, and pushdown mutates the captured filter
    lists — both are only safe while every step is still cold."""
    static = analyze_sharing(rt.app, enabled=True)
    groups: list[SharedStepGroup] = []
    # statically-decided declines (partitions, OBJECT streams, ...) carry
    # over even for queries that never appear as junction receivers here
    # (partition inner queries route through per-key runtimes)
    declined: dict[str, str] = dict(static.declined)
    pushdowns = 0

    # every junction that can host QueryRuntime receivers: app streams,
    # fault streams, trigger streams, named-window emissions
    seen: set[int] = set()
    junctions = list(rt.junctions.values())
    junctions += list(rt.fault_junctions.values())
    junctions += [w.output_junction for w in rt.windows.values()
                  if getattr(w, "output_junction", None) is not None]

    for junction in junctions:
        if id(junction) in seen:
            continue
        seen.add(id(junction))
        receivers = junction.receivers
        qrs_here = [r for r in receivers if isinstance(r, QueryRuntime)]
        # runs of (index, member) with identical dispatch shape
        i, out, seq = 0, [], 0
        while i < len(receivers):
            r = receivers[i]
            reason = runtime_decline(r) if isinstance(r, QueryRuntime) \
                else DECLINE_JOIN_PATTERN
            if not isinstance(r, QueryRuntime):
                out.append(r)
                i += 1
                continue
            if reason is not None:
                if len(qrs_here) >= 2:
                    declined[r.name] = reason
                out.append(r)
                i += 1
                continue
            # members only need the same traced capacity; mixed _bucket_ok
            # is fine — the group pads to full capacity when ANY member is
            # shape-baked (exactly what that member's own on_batch does)
            key = r._batch_cap
            run = [r]
            j = i + 1
            while j < len(receivers):
                nxt = receivers[j]
                if (not isinstance(nxt, QueryRuntime)
                        or runtime_decline(nxt) is not None
                        or nxt._batch_cap != key):
                    break
                run.append(nxt)
                j += 1
            if len(run) >= 2:
                # chunk long runs at the group cap: compile count stays
                # O(run/cap) — sublinear — while each fused graph stays
                # small enough for XLA to compile and schedule well
                cap = group_cap()
                for k in range(0, len(run), cap):
                    chunk = run[k:k + cap]
                    if len(chunk) < 2:
                        out.extend(chunk)
                        continue
                    for m in chunk:
                        pushdowns += _apply_pushdown(m)
                    seq += 1
                    group = SharedStepGroup(
                        f"shared:{junction.definition.id}:{seq}", chunk,
                        junction)
                    groups.append(group)
                    out.append(group)
            else:
                out.extend(run)
            i = j
        junction.receivers[:] = out

    rt.shared_groups = groups
    report = {
        "enabled": True,
        "groups": len(groups),
        "queries_fused": sum(len(g.members) for g in groups),
        "group_members": {g.name: [m.name for m in g.members]
                          for g in groups},
        # static-analysis counts: what the one traced computation shares
        # (XLA CSE realizes these inside the fused executable)
        "cse_hits": static.cse_hits,
        "pane_candidates": static.pane_candidates,
        "pushdowns": pushdowns,
        "declined": declined,
    }
    rt.optimizer_report = report
    return report
