"""Attribute-type → device dtype mapping.

Reference semantics: Siddhi attributes are STRING/INT/LONG/FLOAT/DOUBLE/BOOL/OBJECT
(query/api/definition/Attribute.java). On TPU:

- INT  -> int32            (native)
- LONG -> int64            (requires jax x64; we enable it at package import —
                            timestamps are int64 milliseconds like the reference)
- FLOAT -> float32         (native, VPU/MXU friendly)
- DOUBLE -> float32 by default. Java doubles sequentially accumulated and f64 on
  TPU is software-emulated and ~10x slower; tests use tolerances. Set
  `siddhi_tpu.config.double_dtype = jnp.float64` for bit-closer parity.
- BOOL -> bool_
- STRING -> int32 dictionary codes. Strings are interned host-side per
  (stream, attribute) in a StringTable at ingestion; device sees codes, so
  string equality/group-by are integer ops. Code 0 is reserved for null/missing.
- OBJECT -> host-only (kept in a Python list column; cannot enter device exprs).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..query_api.definition import AttributeType

#: sentinel string code for null
NULL_CODE = 0

#: timestamp dtype — milliseconds since epoch, matching the reference's long ts.
TS_DTYPE = jnp.int64


class _Config:
    double_dtype = jnp.float32
    #: default micro-batch capacity per stream (events); the batching unit that
    #: replaces the reference's Disruptor ring (StreamJunction.java:68 batchSize).
    default_batch_size = 8192
    #: default window ring-buffer capacity when not statically inferable.
    default_window_capacity = 1 << 16
    #: default max distinct group-by keys tracked on device per query.
    default_group_capacity = 1 << 20
    #: key slots for mesh-sharded partitions (per-key state is preallocated
    #: for every slot, so this is deliberately small; raise per app)
    default_partition_capacity = 64
    #: default table row capacity (rows are capacity-padded device arrays).
    default_table_capacity = 1 << 16
    #: max matched build rows per probe event in joins (static join fan-out).
    join_max_matches = 16
    #: compacted pair-block width as a multiple of the probe batch size —
    #: total matches per step beyond factor*B are dropped (bounded fan-out)
    join_pair_cap_factor = 4
    #: max concurrent partial matches per pattern position.
    pattern_pending_capacity = 1024
    #: retained groups for `output snapshot ... group by` (rows per snapshot)
    snapshot_group_capacity = 1024
    #: full-window snapshot limiter ring (non-aggregated `output snapshot`);
    #: sized up automatically when the window's own capacity is known
    snapshot_window_capacity = 4096
    #: key slots for keyed session windows (session(gap, key))
    session_key_capacity = 4096
    #: expansion bound for unbounded pattern counts `<m:>`.
    pattern_unbounded_count_extra = 8
    #: mid-pattern `every` (sticky positions): qualifying arrivals advanced
    #: per entry per BATCH (leftover counts into `dropped`; cross-batch
    #: repetition is unbounded/exact)
    pattern_sticky_passes = 4
    #: HyperLogLog registers per group for hll:distinctCount (power of two;
    #: std error ~1.04/sqrt(m))
    hll_registers = 1024
    #: max groups tracked by hll:distinctCount (each holds hll_registers)
    hll_group_capacity = 4096
    #: shape-bucketed dispatch: junctions pad partial micro-batches to the
    #: smallest power-of-two lane bucket >= the staged row count (instead of
    #: always the full batch capacity), so each shape-polymorphic query step
    #: compiles at most log2(batch_size / min_bucket) + 1 executables while
    #: small/heartbeat batches run kernels sized to their data. Disabled
    #: automatically for mesh-sharded apps (bucket widths must stay aligned
    #: with the device mesh).
    shape_buckets = True
    #: smallest bucket capacity in the ladder (power of two)
    min_bucket = 16
    #: debug-mode invariant checks inside jitted steps (also enabled by
    #: SIDDHI_DEBUG_CHECKS=1): currently the windows' nondecreasing
    #: emission-key check before rank-merge scatters (ops/windows.py
    #: _merge_order). Trace-time gated — zero cost when off.
    debug_checks = False


config = _Config()

import os as _os

if _os.environ.get("SIDDHI_DEBUG_CHECKS", "") not in ("", "0"):
    config.debug_checks = True
if _os.environ.get("SIDDHI_SHAPE_BUCKETS", "") == "0":
    config.shape_buckets = False


def bucket_ladder(cap: int) -> tuple[int, ...]:
    """Ascending power-of-two lane-bucket ladder for one junction capacity:
    (min_bucket, 2*min_bucket, ..., cap). `cap` itself is always the top
    rung even when it is not a power of two, so full batches never pad."""
    mb = max(int(config.min_bucket), 1)
    out = []
    b = mb
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(cap)
    return tuple(out)


def bucket_capacity(n: int, cap: int) -> int:
    """Smallest ladder bucket holding `n` valid rows (n == 0 -> min bucket,
    n >= cap -> cap)."""
    if n >= cap:
        return cap
    b = max(int(config.min_bucket), 1)
    while b < n:
        b <<= 1
    return min(b, cap)


def device_dtype(t: AttributeType):
    if t == AttributeType.INT:
        return jnp.int32
    if t == AttributeType.LONG:
        return jnp.int64
    if t == AttributeType.FLOAT:
        return jnp.float32
    if t == AttributeType.DOUBLE:
        return config.double_dtype
    if t == AttributeType.BOOL:
        return jnp.bool_
    if t == AttributeType.STRING:
        return jnp.int32  # dictionary codes
    raise ValueError(f"attribute type {t} has no device dtype (OBJECT is host-only)")


def numpy_dtype(t: AttributeType):
    return np.dtype(device_dtype(t).__name__ if hasattr(device_dtype(t), "__name__") else device_dtype(t))


def null_value(t: AttributeType):
    """Fill value used in padded/invalid lanes."""
    if t in (AttributeType.INT, AttributeType.LONG, AttributeType.STRING):
        return 0
    if t in (AttributeType.FLOAT, AttributeType.DOUBLE):
        return 0.0
    if t == AttributeType.BOOL:
        return False
    return None


def is_numeric(t: AttributeType) -> bool:
    return t in (AttributeType.INT, AttributeType.LONG, AttributeType.FLOAT, AttributeType.DOUBLE)


#: promotion lattice for binary math, mirroring the reference's per-type-pair
#: executor selection (core/executor/math/*): int < long < float < double.
_RANK = {
    AttributeType.INT: 0,
    AttributeType.LONG: 1,
    AttributeType.FLOAT: 2,
    AttributeType.DOUBLE: 3,
}


def promote(a: AttributeType, b: AttributeType) -> AttributeType:
    if not (is_numeric(a) and is_numeric(b)):
        raise TypeError(f"cannot apply arithmetic to {a}/{b}")
    return a if _RANK[a] >= _RANK[b] else b
