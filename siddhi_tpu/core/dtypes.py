"""Attribute-type → device dtype mapping.

Reference semantics: Siddhi attributes are STRING/INT/LONG/FLOAT/DOUBLE/BOOL/OBJECT
(query/api/definition/Attribute.java). On TPU:

- INT  -> int32            (native)
- LONG -> int64            (requires jax x64; we enable it at package import —
                            timestamps are int64 milliseconds like the reference)
- FLOAT -> float32         (native, VPU/MXU friendly)
- DOUBLE -> float32 by default. Java doubles sequentially accumulated and f64 on
  TPU is software-emulated and ~10x slower; tests use tolerances. Set
  `siddhi_tpu.config.double_dtype = jnp.float64` for bit-closer parity.
- BOOL -> bool_
- STRING -> int32 dictionary codes. Strings are interned host-side per
  (stream, attribute) in a StringTable at ingestion; device sees codes, so
  string equality/group-by are integer ops. Code 0 is reserved for null/missing.
- OBJECT -> host-only (kept in a Python list column; cannot enter device exprs).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..query_api.definition import AttributeType

#: sentinel string code for null
NULL_CODE = 0

#: timestamp dtype — milliseconds since epoch, matching the reference's long ts.
TS_DTYPE = jnp.int64


class _Config:
    double_dtype = jnp.float32
    #: default micro-batch capacity per stream (events); the batching unit that
    #: replaces the reference's Disruptor ring (StreamJunction.java:68 batchSize).
    default_batch_size = 8192
    #: default window ring-buffer capacity when not statically inferable.
    default_window_capacity = 1 << 16
    #: default max distinct group-by keys tracked on device per query.
    default_group_capacity = 1 << 20
    #: key slots for mesh-sharded partitions (per-key state is preallocated
    #: for every slot, so this is deliberately small; raise per app)
    default_partition_capacity = 64
    #: default table row capacity (rows are capacity-padded device arrays).
    default_table_capacity = 1 << 16
    #: max matched build rows per probe event in joins (static join fan-out).
    join_max_matches = 16
    #: compacted pair-block width as a multiple of the probe batch size —
    #: total matches per step beyond factor*B are dropped (bounded fan-out)
    join_pair_cap_factor = 4
    #: max concurrent partial matches per pattern position.
    pattern_pending_capacity = 1024
    #: retained groups for `output snapshot ... group by` (rows per snapshot)
    snapshot_group_capacity = 1024
    #: full-window snapshot limiter ring (non-aggregated `output snapshot`);
    #: sized up automatically when the window's own capacity is known
    snapshot_window_capacity = 4096
    #: key slots for keyed session windows (session(gap, key))
    session_key_capacity = 4096
    #: expansion bound for unbounded pattern counts `<m:>`.
    pattern_unbounded_count_extra = 8
    #: mid-pattern `every` (sticky positions): qualifying arrivals advanced
    #: per entry per BATCH (leftover counts into `dropped`; cross-batch
    #: repetition is unbounded/exact)
    pattern_sticky_passes = 4
    #: HyperLogLog registers per group for hll:distinctCount (power of two;
    #: std error ~1.04/sqrt(m))
    hll_registers = 1024
    #: max groups tracked by hll:distinctCount (each holds hll_registers)
    hll_group_capacity = 4096


config = _Config()


def device_dtype(t: AttributeType):
    if t == AttributeType.INT:
        return jnp.int32
    if t == AttributeType.LONG:
        return jnp.int64
    if t == AttributeType.FLOAT:
        return jnp.float32
    if t == AttributeType.DOUBLE:
        return config.double_dtype
    if t == AttributeType.BOOL:
        return jnp.bool_
    if t == AttributeType.STRING:
        return jnp.int32  # dictionary codes
    raise ValueError(f"attribute type {t} has no device dtype (OBJECT is host-only)")


def numpy_dtype(t: AttributeType):
    return np.dtype(device_dtype(t).__name__ if hasattr(device_dtype(t), "__name__") else device_dtype(t))


def null_value(t: AttributeType):
    """Fill value used in padded/invalid lanes."""
    if t in (AttributeType.INT, AttributeType.LONG, AttributeType.STRING):
        return 0
    if t in (AttributeType.FLOAT, AttributeType.DOUBLE):
        return 0.0
    if t == AttributeType.BOOL:
        return False
    return None


def is_numeric(t: AttributeType) -> bool:
    return t in (AttributeType.INT, AttributeType.LONG, AttributeType.FLOAT, AttributeType.DOUBLE)


#: promotion lattice for binary math, mirroring the reference's per-type-pair
#: executor selection (core/executor/math/*): int < long < float < double.
_RANK = {
    AttributeType.INT: 0,
    AttributeType.LONG: 1,
    AttributeType.FLOAT: 2,
    AttributeType.DOUBLE: 3,
}


def promote(a: AttributeType, b: AttributeType) -> AttributeType:
    if not (is_numeric(a) and is_numeric(b)):
        raise TypeError(f"cannot apply arithmetic to {a}/{b}")
    return a if _RANK[a] >= _RANK[b] else b
