"""SiddhiDebugger — breakpoint inspection at query terminals.

Reference: core/debugger/SiddhiDebugger.java:36 — breakpoints at query IN/OUT
terminals (:249), acquireBreakPoint:95, blocking checkBreakPoint:133 driven
from ProcessStreamReceiver:101-175, next()/play() stepping, and a
SiddhiDebuggerCallback receiving each held event.

TPU adaptation: execution is synchronous single-controller, so a breakpoint
does not suspend a thread — the debugger callback runs INLINE at the terminal
with the decoded events (batch-level capture of the masked lanes, per SURVEY
§7 "mask-level event capture"). The callback's return value steers stepping:
SiddhiDebugger.PLAY keeps flowing, SiddhiDebugger.NEXT keeps the breakpoint
armed (the default). Returning STOP releases all breakpoints.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional


class QueryTerminal(enum.Enum):
    IN = "in"
    OUT = "out"


class SiddhiDebugger:
    PLAY = "play"
    NEXT = "next"
    STOP = "stop"

    def __init__(self, runtime) -> None:
        self.runtime = runtime
        self._breakpoints: set[tuple[str, QueryTerminal]] = set()
        self._callback: Optional[Callable] = None

    def acquire_break_point(self, query_name: str,
                            terminal: QueryTerminal | str) -> None:
        """Reference: SiddhiDebugger.acquireBreakPoint:95."""
        if query_name not in self.runtime.query_runtimes:
            raise KeyError(f"query {query_name!r} is not defined")
        self._breakpoints.add((query_name, QueryTerminal(terminal)))

    def release_break_point(self, query_name: str,
                            terminal: QueryTerminal | str) -> None:
        self._breakpoints.discard((query_name, QueryTerminal(terminal)))

    def release_all_break_points(self) -> None:
        self._breakpoints.clear()

    def set_debugger_callback(self, callback: Callable) -> None:
        """callback(events, query_name, terminal, debugger) -> PLAY|NEXT|STOP
        (reference: SiddhiDebuggerCallback.debugEvent)."""
        self._callback = callback

    def detach(self) -> None:
        """Remove the debugger from the runtime's hot path entirely."""
        self.release_all_break_points()
        self._callback = None
        self.runtime.ctx.debugger = None

    # ------------------------------------------------------------------ hooks

    def wants(self, query_name: str, terminal: QueryTerminal) -> bool:
        """Cheap hot-path guard: the runtime only decodes a batch to host
        events when a callback AND a matching breakpoint exist."""
        return (self._callback is not None
                and (query_name, terminal) in self._breakpoints)

    def check_break_point(self, query_name: str, terminal: QueryTerminal,
                          events: list) -> None:
        """Called from the query runtime at each terminal (the batch analogue
        of ProcessStreamReceiver's per-event checkBreakPoint:133)."""
        if not events or not self.wants(query_name, terminal):
            return
        action = self._callback(events, query_name, terminal, self)
        if action == self.PLAY:
            self.release_break_point(query_name, terminal)
        elif action == self.STOP:
            self.release_all_break_points()
