"""SiddhiDebugger — breakpoint inspection at query terminals.

Reference: core/debugger/SiddhiDebugger.java:36 — breakpoints at query IN/OUT
terminals (:249), acquireBreakPoint:95, blocking checkBreakPoint:133 driven
from ProcessStreamReceiver:101-175, next()/play() stepping, and a
SiddhiDebuggerCallback receiving each held event.

TPU adaptation: execution is synchronous single-controller. The debugger
callback runs at the terminal with decoded events, per event. Two modes:

- INLINE: the callback RETURNS an action — PLAY releases the rest of the
  batch with the breakpoint still armed, NEXT steps to the next event
  (the default), STOP releases every breakpoint.
- INTERACTIVE (the reference's blocking checkBreakPoint:133): the callback
  returns None and the CONTROLLER THREAD BLOCKS on each held event until
  another thread (or the callback itself) calls next()/play()/stop() —
  next() steps one event, play() releases the rest of the batch with
  breakpoints still armed, stop() releases every breakpoint.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

from ..util.locks import named_condition


class QueryTerminal(enum.Enum):
    IN = "in"
    OUT = "out"


class SiddhiDebugger:
    PLAY = "play"
    NEXT = "next"
    STOP = "stop"

    def __init__(self, runtime) -> None:
        self.runtime = runtime
        self._breakpoints: set[tuple[str, QueryTerminal]] = set()
        self._callback: Optional[Callable] = None
        self._cv = named_condition("debug.stepper")
        self._actions: list[str] = []  # FIFO: scripted next();next() queues

    # ------------------------------------------------------------- stepping

    def next(self) -> None:
        """Release the currently held event and stop at the next one
        (reference: SiddhiDebugger.next():182)."""
        self._post(self.NEXT)

    def play(self) -> None:
        """Release the held event and the rest of its batch; breakpoints
        stay armed for future batches (reference: play():190)."""
        self._post(self.PLAY)

    def stop(self) -> None:
        """Release everything and drop all breakpoints."""
        self._post(self.STOP)

    def _post(self, action: str) -> None:
        with self._cv:
            self._actions.append(action)
            self._cv.notify_all()

    def _wait_action(self) -> str:
        with self._cv:
            while not self._actions:
                self._cv.wait()
            return self._actions.pop(0)

    def acquire_break_point(self, query_name: str,
                            terminal: QueryTerminal | str) -> None:
        """Reference: SiddhiDebugger.acquireBreakPoint:95."""
        if query_name not in self.runtime.query_runtimes:
            raise KeyError(f"query {query_name!r} is not defined")
        self._breakpoints.add((query_name, QueryTerminal(terminal)))

    def release_break_point(self, query_name: str,
                            terminal: QueryTerminal | str) -> None:
        self._breakpoints.discard((query_name, QueryTerminal(terminal)))

    def release_all_break_points(self) -> None:
        self._breakpoints.clear()

    def set_debugger_callback(self, callback: Callable) -> None:
        """callback(events, query_name, terminal, debugger) -> PLAY|NEXT|STOP
        (reference: SiddhiDebuggerCallback.debugEvent)."""
        self._callback = callback

    def detach(self) -> None:
        """Remove the debugger from the runtime's hot path entirely."""
        self.release_all_break_points()
        self._callback = None
        self.runtime.ctx.debugger = None

    # ------------------------------------------------------------------ hooks

    def wants(self, query_name: str, terminal: QueryTerminal) -> bool:
        """Cheap hot-path guard: the runtime only decodes a batch to host
        events when a callback AND a matching breakpoint exist."""
        return (self._callback is not None
                and (query_name, terminal) in self._breakpoints)

    def check_break_point(self, query_name: str, terminal: QueryTerminal,
                          events: list) -> None:
        """Called from the query runtime at each terminal (the batch analogue
        of ProcessStreamReceiver's per-event checkBreakPoint:133).

        A callback returning an action keeps the legacy inline protocol; a
        callback returning None holds each event and BLOCKS the controller
        until next()/play()/stop() arrives."""
        if not events or not self.wants(query_name, terminal):
            return
        for i, ev in enumerate(events):
            if not self.wants(query_name, terminal):
                return
            action = self._callback([ev], query_name, terminal, self)
            if action is None:  # interactive: block for next()/play()/stop()
                action = self._wait_action()
                if action == self.NEXT:
                    continue
            if action == self.PLAY:
                return  # release the rest of the batch; stays armed
            if action == self.STOP:
                self.release_all_break_points()
                return
