"""On-demand (pull) queries against tables / named windows / aggregations.

Reference: core/util/parser/OnDemandQueryParser.java:87 builds
{Find,Select,...}OnDemandQueryRuntime objects executed from
SiddhiAppRuntimeImpl.query():309-371. TPU design: one jitted pull function per
(query text) — table rows form a CURRENT chunk, the optional ON condition masks
it, and the shared CompiledSelector runs in `emit_final_per_group` mode so
aggregates produce one row per group (not per-event running values).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..errors import DefinitionNotExistError, SiddhiAppCreationError
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..ops.selector import CompiledSelector
from ..query_api.definition import Attribute, AttributeType, StreamDefinition
from ..query_api.execution import OnDemandQuery
from ..query_api.expression import Variable
from .event import Event, EventBatch, StreamCodec
from .table import InMemoryTable, TableState


class OnDemandQueryRuntime:
    """Compiled pull query over one store (table or named window)."""

    def __init__(self, odq: OnDemandQuery, table, ctx,
                 registry) -> None:
        self.odq = odq
        self.table = table
        from ..io.record_table import RecordTableRuntime
        self.is_record = isinstance(table, RecordTableRuntime)
        self.is_window = (not self.is_record
                          and not isinstance(table, InMemoryTable))
        tid = table.definition.id

        frames = {tid: dict(table.attr_types)}
        tsp = set(getattr(table, "set_projection_attrs", ()) or ())
        resolver = TypeResolver(frames, tid, {tid: table.codec},
                                {tid: tsp} if tsp else None)

        self.cond = None
        if odq.on_condition is not None:
            self.cond = compile_expression(odq.on_condition, resolver, registry)
            if self.cond.type != AttributeType.BOOL:
                raise SiddhiAppCreationError("ON condition must be boolean")

        select_all = list(table.attr_types.items())
        self.selector = CompiledSelector(
            odq.selector, resolver, registry, ctx.effective_group_capacity,
            tid, select_all_attrs=select_all, emit_final_per_group=True)

        if odq.within_range is not None or odq.per is not None:
            # within/per apply to aggregation stores (reference:
            # AggregationRuntime.find); meaningless on plain tables
            raise SiddhiAppCreationError(
                f"within/per are not applicable to table {tid!r} "
                "(only to aggregation stores)")

        out_attrs = tuple(Attribute(n, t)
                          for n, t in self.selector.out_types.items())
        self.output_definition = StreamDefinition(id=f"{tid}_find", attributes=out_attrs)
        # app-global string interning: codes in output columns decode directly
        self.output_codec = StreamCodec(self.output_definition, ctx.global_strings)

        self._fn = jax.jit(self._make())

    def _make(self):
        tid = self.table.definition.id
        cond = self.cond
        selector = self.selector
        is_window = self.is_window
        window = self.table if is_window else None

        def run(tstate, now):
            if is_window:
                cols, ts, valid = window.contents(tstate, now)
                tstate = TableState(cols=cols, ts=ts, valid=valid)
            C = tstate.ts.shape[0]
            scope = Scope()
            scope.add_frame(tid, tstate.cols, tstate.ts, tstate.valid, default=True)
            scope.extras["now"] = now
            valid = tstate.valid
            if cond is not None:
                valid = valid & cond(scope)
            chunk = EventBatch(ts=tstate.ts, cols=tstate.cols, valid=valid,
                               types=jnp.zeros((C,), jnp.int8))
            scope.valids[tid] = valid
            _, out = selector.step(selector.init_state(), chunk, scope)
            return out

        return run

    def execute(self, now: int = 0) -> list[Event]:
        if self.is_record:
            # authoritative fetch from the store (read-through refreshes the
            # cache); the device selector then projects/aggregates the rows
            tstate = self._record_state()
        else:
            tstate = self.table.state
        out = self._fn(tstate, jnp.int64(now))
        return out.to_host_events(self.output_codec)

    def _record_state(self) -> TableState:
        import numpy as np
        rows = self.table.find_rows(self.odq.on_condition)
        names = list(self.table.attr_types)
        tuples = [tuple(r.get(n) for n in names) for r in rows]
        n = len(tuples)
        cap = max(16, 1 << (n - 1).bit_length() if n else 4)
        cols = self.table.codec.rows_to_columns(tuples, n_pad=cap)
        batch = EventBatch.from_numpy(
            np.zeros(cap, dtype=np.int64), cols, cap)
        valid = jnp.arange(cap) < n
        return TableState(cols=batch.cols, ts=batch.ts, valid=valid)



def eval_standalone_insert_row(selector, registry, definition) -> dict:
    """Standalone `select <const exprs> insert into T` (reference: the
    insert OnDemandQueryRuntime with no source): evaluate the select list
    once on a dummy lane, validate names against the table schema, return
    {attr: python value}. Shared by the in-memory and record-table paths so
    the same query text means the same thing on either backend."""
    import numpy as np

    empty = TypeResolver({"__out__": {}}, "__out__", {"__out__": None})
    scope = Scope()
    scope.add_frame("__out__", {}, jnp.zeros((1,), jnp.int64),
                    jnp.ones((1,), bool), default=True)
    by_name = {}
    for oa in selector.attributes:
        name = oa.rename or getattr(oa.expression, "attribute", None)
        if name is None:
            raise SiddhiAppCreationError(
                "standalone insert select items need `as` names")
        ce = compile_expression(oa.expression, empty, registry)
        val = ce(scope)
        by_name[name] = (val if isinstance(val, str)
                         else np.asarray(val).reshape(()).item())
    schema = [a.name for a in definition.attributes]
    unknown = set(by_name) - set(schema)
    missing = set(schema) - set(by_name)
    if unknown or missing:
        raise SiddhiAppCreationError(
            f"insert into {definition.id!r}: select list must name every "
            f"table attribute exactly (missing {sorted(missing)}, unknown "
            f"{sorted(unknown)})")
    return by_name


class OnDemandCrudRuntime:
    """Write-form on-demand queries (reference: Insert/Delete/Update/
    UpdateOrInsert OnDemandQueryRuntime under core/query/):

      delete T on <cond>
      update T set T.a = <expr>, ... [on <cond>]
      select <consts> update or insert into T [set ...] on <cond>
      from Store ... select ... insert into T

    Reuses the query-output TableOutputExecutor (one jitted device op); the
    standalone forms evaluate against a single dummy lane since their
    conditions/sets reference only the table frame and constants."""

    def __init__(self, odq: OnDemandQuery, target: InMemoryTable, ctx,
                 registry, source_store=None) -> None:
        from ..query_api.execution import OutputAction, OutputStream
        from .table import TableOutputExecutor

        self.odq = odq
        self.target = target
        self.ctx = ctx
        self.action = odq.action
        self.select_runtime = None
        self._out_batch = None

        self._const_row = None
        if self.action == OutputAction.INSERT:
            if odq.input_store_id is None:
                by_name = eval_standalone_insert_row(
                    odq.selector, registry, target.definition)
                self._const_row = tuple(
                    by_name[a.name] for a in target.definition.attributes)
                self.executor = None
                return
            # select over the source store, insert results into the target
            import dataclasses as dc
            sel_odq = dc.replace(odq, action=OutputAction.RETURN, target_id=None)
            self.select_runtime = OnDemandQueryRuntime(
                sel_odq, source_store, ctx, registry)
            self.executor = None
            return

        out_types: dict = {}
        out_cols: dict = {}
        if self.action == OutputAction.UPDATE_OR_INSERT:
            # the SELECT list supplies the row to insert on no-match:
            # constant expressions evaluated once into a 1-lane out frame
            empty = TypeResolver({"__out__": {}}, "__out__",
                                 {"__out__": None})
            scope = Scope()
            scope.add_frame("__out__", {}, jnp.zeros((1,), jnp.int64),
                            jnp.ones((1,), bool), default=True)
            for oa in odq.selector.attributes:
                ce = compile_expression(oa.expression, empty, registry)
                name = oa.rename or getattr(oa.expression, "attribute", None)
                if name is None:
                    raise SiddhiAppCreationError(
                        "update-or-insert select items need `as` names")
                out_types[name] = ce.type
                val = ce(scope)
                if isinstance(val, str):  # bare string constant → intern
                    val = ctx.global_strings.encode(val)
                    out_cols[name] = jnp.full((1,), val, jnp.int32)
                else:
                    out_cols[name] = jnp.broadcast_to(jnp.asarray(val), (1,))

        out_def = StreamDefinition(
            id="__out__", attributes=tuple(
                Attribute(n, t) for n, t in out_types.items()))
        out_codec = StreamCodec(out_def, ctx.global_strings)
        from ..query_api.expression import Constant
        out_stream = OutputStream(
            action=self.action, target_id=target.definition.id,
            # bare `update T set ...` applies to every row
            on_condition=odq.on_condition or Constant(True, "bool"),
            set_attributes=odq.set_attributes)
        self.executor = TableOutputExecutor(
            target, out_stream, out_types, out_codec, registry)
        self._out_batch = EventBatch(
            ts=jnp.zeros((1,), jnp.int64),
            cols=out_cols,
            valid=jnp.ones((1,), bool),
            types=jnp.zeros((1,), jnp.int8))

    def execute(self, now: int = 0) -> list[Event]:
        if self._const_row is not None:
            self.target.insert_rows([self._const_row], timestamp=now)
            return []
        if self.select_runtime is not None:
            events = self.select_runtime.execute(now)
            rows = [tuple(e.data) for e in events]
            self.target.insert_rows(rows, timestamp=now)
            return events
        self.executor.apply(self._out_batch)
        return []
