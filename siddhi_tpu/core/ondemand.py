"""On-demand (pull) queries against tables / named windows / aggregations.

Reference: core/util/parser/OnDemandQueryParser.java:87 builds
{Find,Select,...}OnDemandQueryRuntime objects executed from
SiddhiAppRuntimeImpl.query():309-371. TPU design: one jitted pull function per
(query text) — table rows form a CURRENT chunk, the optional ON condition masks
it, and the shared CompiledSelector runs in `emit_final_per_group` mode so
aggregates produce one row per group (not per-event running values).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..errors import DefinitionNotExistError, SiddhiAppCreationError
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..ops.selector import CompiledSelector
from ..query_api.definition import Attribute, AttributeType, StreamDefinition
from ..query_api.execution import OnDemandQuery
from ..query_api.expression import Variable
from .event import Event, EventBatch, StreamCodec
from .table import InMemoryTable, TableState


class OnDemandQueryRuntime:
    """Compiled pull query over one store (table or named window)."""

    def __init__(self, odq: OnDemandQuery, table, ctx,
                 registry) -> None:
        self.odq = odq
        self.table = table
        self.is_window = not isinstance(table, InMemoryTable)
        tid = table.definition.id

        frames = {tid: dict(table.attr_types)}
        resolver = TypeResolver(frames, tid, {tid: table.codec})

        self.cond = None
        if odq.on_condition is not None:
            self.cond = compile_expression(odq.on_condition, resolver, registry)
            if self.cond.type != AttributeType.BOOL:
                raise SiddhiAppCreationError("ON condition must be boolean")

        select_all = list(table.attr_types.items())
        self.selector = CompiledSelector(
            odq.selector, resolver, registry, ctx.effective_group_capacity,
            tid, select_all_attrs=select_all, emit_final_per_group=True)

        if odq.within_range is not None or odq.per is not None:
            # within/per apply to aggregation stores (reference:
            # AggregationRuntime.find); meaningless on plain tables
            raise SiddhiAppCreationError(
                f"within/per are not applicable to table {tid!r} "
                "(only to aggregation stores)")

        out_attrs = tuple(Attribute(n, t)
                          for n, t in self.selector.out_types.items())
        self.output_definition = StreamDefinition(id=f"{tid}_find", attributes=out_attrs)
        # app-global string interning: codes in output columns decode directly
        self.output_codec = StreamCodec(self.output_definition, ctx.global_strings)

        self._fn = jax.jit(self._make())

    def _make(self):
        tid = self.table.definition.id
        cond = self.cond
        selector = self.selector
        is_window = self.is_window
        window = self.table if is_window else None

        def run(tstate, now):
            if is_window:
                cols, ts, valid = window.contents(tstate, now)
                tstate = TableState(cols=cols, ts=ts, valid=valid)
            C = tstate.ts.shape[0]
            scope = Scope()
            scope.add_frame(tid, tstate.cols, tstate.ts, tstate.valid, default=True)
            scope.extras["now"] = now
            valid = tstate.valid
            if cond is not None:
                valid = valid & cond(scope)
            chunk = EventBatch(ts=tstate.ts, cols=tstate.cols, valid=valid,
                               types=jnp.zeros((C,), jnp.int8))
            scope.valids[tid] = valid
            _, out = selector.step(selector.init_state(), chunk, scope)
            return out

        return run

    def execute(self, now: int = 0) -> list[Event]:
        out = self._fn(self.table.state, jnp.int64(now))
        return out.to_host_events(self.output_codec)
