"""Zero-copy parallel ingress pipeline (the host half of the perf story).

BENCH_r04 measured the device sustaining 61–105M ev/s while e2e throughput
topped out at 0.7–3M ev/s: the product is host-bound, not TPU-bound. The
pipeline here closes that gap by overlapping the three host stages that the
synchronous path runs strictly in sequence:

    producers ──claim──▶ [decode/intern worker pool] ──publish──▶
        lock-free columnar ring ──pop──▶ [feeder] ──device_put──▶
            double-buffered EventBatch ──deliver──▶ engine compute

  stage 1  submit: producer threads CAS-claim contiguous ring runs
           (claim order is a total order — it IS delivery order) and hand
           the raw payload to the worker pool. Claiming is the only
           producer-side work; a full ring is blocking backpressure.
  stage 2  decode/intern: N workers convert rows/columns to fixed-width
           native buffers and write them into their pre-claimed slots with
           the GIL released (columnar.c colring_write is a plain memcpy).
           String interning is the one stage that must be deterministic —
           dictionary codes are assigned by first appearance — so workers
           take an "intern ticket" and intern in claim order; numeric
           conversion runs unordered.
  stage 3  feed: a single consumer pops contiguous published runs,
           assembles batch_size chunks, and starts the host→device
           transfer for chunk k+1 (EventBatch.from_numpy = device_put)
           BEFORE delivering chunk k under the controller lock, so H2D
           overlaps engine compute (double buffering; SIDDHI_DOUBLE_BUFFER=0
           disables).

Determinism/parity: with a single producer the delivered batches are
bit-identical to the synchronous path — same chunk boundaries (batch_size
from offset 0), same padding (monotone ts, zero columns, _pad_cap buckets),
same string codes (ticket-ordered interning). With multiple producers the
interleaving is the claim order, and conservation (sent == delivered +
dropped) is the invariant; tests/test_ingress_parity.py asserts both.

Gating: the pipeline is opt-in via @Async(workers='N') or
SIDDHI_INGRESS_WORKERS, and only engages when the junction has no WAL
(durability serializes through the controller lock by design), no sequence
taps (they need true per-row send order on the producer thread), a 'block'
overflow policy (drop/fault accounting lives in the bounded path), and no
OBJECT attributes (no columnar layout). Everything else falls back to the
existing MPSC ring or synchronous staging untouched.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as np

from ..util.locks import named_condition, named_lock, note_blocking

_log = logging.getLogger("siddhi_tpu")

#: np dtype name -> colring type code (widths: b=1, i=4, l=8, f=4, d=8)
_NP_TYPECODE = {"bool": "b", "int8": "b", "int32": "i", "int64": "l",
                "float32": "f", "float64": "d"}


def _typecodes(np_dtypes: Sequence[np.dtype]) -> Optional[bytes]:
    codes = []
    for dt in np_dtypes:
        c = _NP_TYPECODE.get(dt.name)
        if c is None:
            return None
        codes.append(c)
    return "".join(codes).encode("ascii")


class _NativeColRing:
    """Thin adapter over the columnar.c lock-free ring."""

    def __init__(self, cap: int, typecodes: bytes, nmod) -> None:
        self._n = nmod
        self._r = nmod.colring_new(cap, typecodes)
        self.capacity = nmod.colring_capacity(self._r)

    def claim(self, n: int) -> int:
        return self._n.colring_claim(self._r, n)

    def write(self, start: int, n: int, ts, cols) -> None:
        self._n.colring_write(self._r, start, n, ts, cols)

    def pop(self, max_n: int, ts_out, cols_out) -> int:
        return self._n.colring_pop(self._r, max_n, ts_out, cols_out)

    def size(self) -> int:
        return self._n.colring_size(self._r)

    def hwm(self) -> int:
        return self._n.colring_hwm(self._r)


class _PyColRing:
    """Pure-Python fallback with the same surface: a lock guards claim()
    (the CAS), numpy slice copies do write/pop, and per-slot sequence
    stamps carry the publish ordering exactly like the C ring. Correctness
    twin for environments without a C toolchain — and the reference the
    parity test runs against."""

    def __init__(self, cap: int, dtypes_list: Sequence[np.dtype]) -> None:
        c = 1
        while c < cap:
            c <<= 1
        self.capacity = c
        self._mask = c - 1
        self._ts = np.zeros(c, dtype=np.int64)
        self._cols = [np.zeros(c, dtype=dt) for dt in dtypes_list]
        self._seq = np.zeros(c, dtype=np.int64)
        self._head = 0
        self._tail = 0
        self._hwm = 0
        self._lock = named_lock("ingress.pyring")

    def claim(self, n: int) -> int:
        with self._lock:
            if self._head + n - self._tail > self.capacity:
                return -1
            s = self._head
            self._head += n
            depth = self._head - self._tail
            if depth > self._hwm:
                self._hwm = depth
            return s

    def write(self, start: int, n: int, ts, cols) -> None:
        cap, mask = self.capacity, self._mask
        s0 = start & mask
        first = min(cap - s0, n)
        second = n - first
        self._ts[s0:s0 + first] = ts[:first]
        if second:
            self._ts[:second] = ts[first:n]
        for dst, src in zip(self._cols, cols):
            dst[s0:s0 + first] = src[:first]
            if second:
                dst[:second] = src[first:n]
        idx = np.arange(start, start + n) & mask
        self._seq[idx] = np.arange(start + 1, start + n + 1)

    def pop(self, max_n: int, ts_out, cols_out) -> int:
        t, cap, mask = self._tail, self.capacity, self._mask
        max_n = min(max_n, len(ts_out))
        if max_n <= 0:
            return 0
        want = np.arange(t + 1, t + max_n + 1)
        got = self._seq[np.arange(t, t + max_n) & mask]
        ok = got == want
        n = max_n if ok.all() else int(ok.argmin())
        if n == 0:
            return 0
        s0 = t & mask
        first = min(cap - s0, n)
        second = n - first
        ts_out[:first] = self._ts[s0:s0 + first]
        if second:
            ts_out[first:n] = self._ts[:second]
        for dst, src in zip(cols_out, self._cols):
            dst[:first] = src[s0:s0 + first]
            if second:
                dst[first:n] = src[:second]
        self._seq[np.arange(t, t + n) & mask] = 0
        self._tail = t + n
        return n

    def size(self) -> int:
        return self._head - self._tail

    def hwm(self) -> int:
        return self._hwm


class IngressPipeline:
    """Per-junction parallel ingress: worker pool + columnar ring + feeder.

    Thread/lock discipline (the deadlock audit):
      - producers take only the submit lock (claim+enqueue ordering) and
        never the controller lock;
      - workers take the intern ticket and, while interning, the controller
        lock (interning mutates the app-global StringTable, which
        synchronous paths mutate under that lock) — never the submit lock;
      - the feeder takes the controller lock only around delivery;
      - drain() is called only by threads NOT holding the controller lock
        (junction.flush guards on _lock_owned), so the feeder can always
        acquire it to make progress.
    """

    def __init__(self, junction, workers: int) -> None:
        from .. import native as native_mod

        self.j = junction
        self.ctx = junction.ctx
        self.workers = max(1, int(workers))
        defn = junction.definition
        if junction.codec.object_attrs:
            raise ValueError("ingress pipeline: OBJECT attrs have no "
                             "columnar layout")
        self.attrs = [a.name for a in defn.attributes]
        self.np_dtypes = [junction.codec.np_dtypes[n] for n in self.attrs]
        tcs = _typecodes(self.np_dtypes)
        if tcs is None:
            raise ValueError("ingress pipeline: unsupported dtype in schema")
        self._string_attrs = set(junction.codec.string_tables)
        self._ordered = bool(self._string_attrs)
        cap = junction._ring_cap
        if native_mod.native is not None and \
                hasattr(native_mod.native, "colring_new"):
            self.ring = _NativeColRing(cap, tcs, native_mod.native)
        else:
            self.ring = _PyColRing(cap, self.np_dtypes)
        self._q: queue.Queue = queue.Queue()
        #: claim+enqueue run under this lock so queue order == claim order —
        #: the invariant the intern tickets (and 1-worker liveness) need
        self._submit_lock = named_lock("ingress.submit")
        self._ticket_cv = named_condition("ingress.ticket")
        self._next_ticket = 0
        self._stopping = False
        self._threads: list[threading.Thread] = []
        self._feeder: Optional[threading.Thread] = None
        self._feeder_stop = threading.Event()
        self._flush_req = threading.Event()
        # a BARRIER flush (drain/stop: everything submitted must deliver)
        # as opposed to the producer-backpressure flush _claim_blocking
        # raises while the ring is full. Only a barrier may disassemble a
        # staged superstep stack: under backpressure the staging itself
        # keeps popping the ring, so space frees without flushing — and
        # at steady state backpressure is the NORMAL state, so honoring
        # it would stop supersteps from ever reaching K staged chunks.
        self._barrier_req = threading.Event()
        self._feeder_idle = threading.Event()
        self._feeder_idle.set()
        self._double_buffer = os.environ.get(
            "SIDDHI_DOUBLE_BUFFER", "1").strip() != "0"
        # device-resident supersteps (@app:superstep(k=) / SIDDHI_SUPERSTEP_K,
        # core/superstep.py): the feeder stages K full chunks and runs the
        # eligible query chain as one lax.scan dispatch. Built lazily at the
        # first staged superstep; a decline is logged once and recorded here
        # (statistics_report surfaces it), then the K=1 path runs forever.
        self._ss_k = 1
        self._ss_runner = None
        self._ss_decline: Optional[str] = None
        self._ss_supersteps = 0  # feeder only: dispatched supersteps
        self._ss_scan_ns = 0    # feeder only: lax.scan + device_get wall
        self._ss_replay_ns = 0  # feeder only: host replay/distribution wall
        # --- statistics (each slot has a single writer thread) ---
        self._t0 = time.monotonic()
        self._worker_busy_ns = [0] * self.workers
        self._worker_decode_ns = [0] * self.workers
        self._worker_intern_ns = [0] * self.workers
        self._worker_runs = [0] * self.workers
        self._h2d_ns = 0        # feeder only
        self._h2d_count = 0     # feeder only
        self._device_ns = 0     # feeder only
        self._batches = 0       # feeder only
        self._overlapped = 0    # feeder only
        self._rows_in = 0       # under submit lock
        self._runs_in = 0       # under submit lock
        self._frames_in = 0     # wire path, under submit lock

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        sid = self.j.definition.id
        for i in range(self.workers):
            t = threading.Thread(target=self._worker_loop, args=(i,),
                                 daemon=True,
                                 name=f"siddhi-ingress-{sid}-w{i}")
            t.start()
            self._threads.append(t)
        self._feeder = threading.Thread(target=self._feed_loop, daemon=True,
                                        name=f"siddhi-ingress-{sid}-feed")
        self._feeder.start()

    def stop(self) -> None:
        """Orderly shutdown: no new submits, queued runs finish (every
        claimed slot publishes — an unpublished hole would strand the rows
        behind it), the feeder delivers the remainder, threads join."""
        self._stopping = True
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=120)
        self._barrier_req.set()
        self._flush_req.set()
        self._feeder_stop.set()
        if self._feeder is not None:
            self._feeder.join(timeout=120)
            if self._feeder.is_alive():  # pragma: no cover — wedged device
                _log.warning("ingress feeder for %r did not stop",
                             self.j.definition.id)

    # ---------------------------------------------------------------- submit

    def _claim_blocking(self, n: int,
                        deadline: Optional[float]) -> int:
        """Claim n contiguous slots, blocking while the ring is full (the
        Disruptor blocking wait strategy — a full ring IS backpressure).
        Returns -1 on block.timeout expiry, -2 when the pipeline stopped."""
        ring = self.ring
        while True:
            if self._stopping:
                return -2
            s = ring.claim(n)
            if s >= 0:
                return s
            if deadline is not None and time.monotonic() >= deadline:
                return -1
            self._flush_req.set()
            note_blocking("ring.claim_wait", allow=("ingress.submit",))
            time.sleep(0.0002)  # noqa: SL404 — blocking claim IS the backpressure

    def _deadline(self) -> Optional[float]:
        bt = self.j.block_timeout_s
        return None if bt is None else time.monotonic() + bt

    def submit_rows(self, tss: Sequence[int], rows: Sequence) -> int:
        """Producer-thread entry for the row path. Chunks into runs of at
        most batch_size, claims each, and hands (start, rows) to the
        workers. Returns the number of rows CONSUMED (claimed or shed): a
        short count means the pipeline is stopping and the caller owns the
        remainder (fall back to synchronous staging)."""
        if self._stopping or self.j._redirect is not None:
            # redirected junction (blue-green cutover): the caller's
            # synchronous fallback forwards the rows to the live junction
            return 0
        bs = self.j.batch_size
        n = len(rows)
        i = 0
        deadline = self._deadline()
        while i < n:
            m = min(bs, n - i)
            with self._submit_lock:
                s = self._claim_blocking(m, deadline)
                if s == -2:
                    return i  # claimed prefix is in flight; caller owns rest
                if s == -1:
                    self.ctx.statistics.track_ingress_drop(
                        self.j.definition.id, "block.timeout", n - i)
                    return n  # shed per block.timeout: consumed by policy
                self._rows_in += m
                self._runs_in += 1
                self._q.put(  # noqa: SL404 — unbounded queue, never blocks
                    ("rows", s, m, tss[i:i + m], rows[i:i + m]))
            i += m
        return n

    def submit_columns(self, ts_arr: np.ndarray, columns: dict,
                       n: int, frame: bool = False) -> int:
        """Producer-thread entry for the columnar/wire path. `columns` maps
        attr -> numpy array (numeric, pre-encoded int codes, or str/None
        objects) or, for wire frames, attr -> ('dict', strings, idx) where
        idx is int32 with -1 = null — the zero-copy dictionary form.
        Returns rows consumed; see submit_rows."""
        if self._stopping or self.j._redirect is not None:
            return 0
        specs = []
        for name in self.attrs:
            if name not in columns:
                raise ValueError(
                    f"send_columns: missing column {name!r} for stream "
                    f"{self.j.definition.id!r}")
            src = columns[name]
            if isinstance(src, tuple) and len(src) == 3 and src[0] == "dict":
                specs.append(src)
                continue
            arr = np.asarray(src)
            if arr.shape[0] < n:
                raise ValueError(
                    f"send_columns: column {name!r} has {arr.shape[0]} "
                    f"rows, expected {n}")
            if name in self._string_attrs and \
                    not np.issubdtype(arr.dtype, np.integer):
                specs.append(("strs", arr, None))
            else:
                specs.append(("num", arr, None))
        ts_arr = np.asarray(ts_arr, dtype=np.int64)
        bs = self.j.batch_size
        i = 0
        deadline = self._deadline()
        while i < n:
            m = min(bs, n - i)
            run = []
            for kind, a, b in specs:
                if kind == "dict":
                    run.append(("dict", a, b[i:i + m]))
                else:
                    run.append((kind, a[i:i + m], None))
            with self._submit_lock:
                s = self._claim_blocking(m, deadline)
                if s == -2:
                    return i
                if s == -1:
                    self.ctx.statistics.track_ingress_drop(
                        self.j.definition.id, "block.timeout", n - i)
                    return n
                self._rows_in += m
                self._runs_in += 1
                if frame:
                    self._frames_in += 1
                self._q.put(  # noqa: SL404 — unbounded queue, never blocks
                    ("cols", s, m, ts_arr[i:i + m], run))
            i += m
        return n

    # --------------------------------------------------------------- workers

    def _take_ticket(self, start: int) -> None:
        with self._ticket_cv:
            while self._next_ticket != start:
                self._ticket_cv.wait(timeout=0.05)

    def _release_ticket(self, start: int, n: int) -> None:
        with self._ticket_cv:
            self._next_ticket = start + n
            self._ticket_cv.notify_all()

    def _worker_loop(self, wid: int) -> None:
        codec = self.j.codec
        dtypes_list = self.np_dtypes
        attrs = self.attrs
        string_attrs = self._string_attrs
        ordered = self._ordered
        clock = self.ctx.controller_lock
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            t0 = time.perf_counter_ns()
            try:
                kind, start, m, ts, payload = item
                intern_ns = 0
                if kind == "rows":
                    if ordered:
                        # rows_to_columns interns inline (native
                        # encode_rows is one call): ticket-order the whole
                        # decode, under the controller lock because the
                        # StringTable is also mutated by synchronous paths
                        # that hold it
                        self._take_ticket(start)
                        ti = time.perf_counter_ns()
                        try:
                            with clock:
                                cols_d = codec.rows_to_columns(payload,
                                                               n_pad=m)
                        finally:
                            self._release_ticket(start, m)
                        intern_ns = time.perf_counter_ns() - ti
                        cols = tuple(cols_d[a] for a in attrs)
                    else:
                        cols_d = codec.rows_to_columns(payload, n_pad=m)
                        cols = tuple(cols_d[a] for a in attrs)
                    ts_buf = np.asarray(ts, dtype=np.int64)
                else:  # "cols"
                    out = []
                    took = False
                    try:
                        for name, dt, (ck, a, b) in zip(attrs, dtypes_list,
                                                        payload):
                            if ck == "num":
                                out.append(np.ascontiguousarray(a, dtype=dt))
                            elif ck == "strs":
                                if not took and ordered:
                                    self._take_ticket(start)
                                    took = True
                                ti = time.perf_counter_ns()
                                tbl = codec.string_tables[name]
                                with clock:
                                    codes = tbl.encode_array(a)
                                intern_ns += time.perf_counter_ns() - ti
                                out.append(np.ascontiguousarray(
                                    codes, dtype=dt))
                            else:  # "dict": intern DISTINCT values, take
                                if not took and ordered:
                                    self._take_ticket(start)
                                    took = True
                                ti = time.perf_counter_ns()
                                tbl = codec.string_tables[name]
                                with clock:
                                    codes = tbl.encode_array(
                                        np.asarray(a, dtype=object))
                                # idx -1 = null -> code 0 via a shifted LUT
                                lut = np.empty(len(codes) + 1,
                                               dtype=np.int32)
                                lut[0] = 0
                                lut[1:] = codes
                                out.append(np.ascontiguousarray(
                                    lut[b.astype(np.int64) + 1], dtype=dt))
                                intern_ns += time.perf_counter_ns() - ti
                    finally:
                        if ordered:
                            if not took:
                                self._take_ticket(start)
                            self._release_ticket(start, m)
                    cols = tuple(out)
                    ts_buf = np.ascontiguousarray(ts, dtype=np.int64)
                self.ring.write(start, m, ts_buf, cols)
                self._worker_intern_ns[wid] += intern_ns
                spent = time.perf_counter_ns() - t0
                self._worker_busy_ns[wid] += spent
                self._worker_decode_ns[wid] += spent - intern_ns
                self._worker_runs[wid] += 1
                self._feeder_idle.clear()
            except Exception:  # pragma: no cover — logged, slot published 0s
                _log.exception("ingress worker error on %r",
                               self.j.definition.id)
                try:
                    zero = tuple(np.zeros(m, dtype=dt)
                                 for dt in dtypes_list)
                    self.ring.write(start, m,
                                    np.zeros(m, dtype=np.int64), zero)
                except Exception:
                    pass
            finally:
                self._q.task_done()

    # ---------------------------------------------------------------- feeder

    def _deliver_locked(self, batch, m: int) -> None:
        j = self.j
        t0 = time.perf_counter_ns()
        with self.ctx.controller_lock:
            if j._staged_rows or j._tap_queue:
                j.flush()  # staged (sync-path) rows first: arrival order
            j._deliver(batch, self.ctx.timestamp_generator.current_time())
        self._device_ns += time.perf_counter_ns() - t0
        self._batches += 1

    def _superstep_dispatch(self, sstack: list) -> bool:
        """Run the staged chunks as ONE K-batch lax.scan dispatch
        (core/superstep.py). Returns False when the staged chunks must fall
        back to the per-batch path (plan declined, debugger attached,
        topology changed)."""
        if self._ss_decline is not None:
            return False
        if self._ss_runner is None or not self._ss_runner.revalidate():
            from .superstep import build_runner
            self._ss_runner, reason = build_runner(self, self._ss_k)
            if self._ss_runner is None:
                # decline LOUDLY, once — then the K=1 path runs forever
                self._ss_decline = reason
                _log.warning(
                    "superstep(k=%d) declined for stream %r: %s — "
                    "falling back to per-batch dispatch (see SL506)",
                    self._ss_k, self.j.definition.id, reason)
                return False
        try:
            dispatched = self._ss_runner.dispatch(sstack)
        except Exception as e:
            # A dispatch error must not kill the feeder thread (producers
            # would wedge in _claim_blocking forever). Disable supersteps
            # for this stream and keep running on the K=1 path. Whether the
            # staged slots were consumed depends on WHERE it failed: after
            # the scan wrote state back (superstep_committed), re-delivering
            # them through the per-batch path would double-count every
            # window and aggregate — report them consumed instead.
            committed = bool(getattr(e, "superstep_committed", False))
            self._ss_decline = f"runtime error during dispatch: {e!r}"
            self._ss_runner = None
            _log.exception(
                "superstep(k=%d) dispatch failed for stream %r "
                "(committed=%s) — disabling supersteps, falling back to "
                "per-batch dispatch", self._ss_k, self.j.definition.id,
                committed)
            return committed
        if dispatched:
            self._ss_supersteps += 1
            return True
        return False

    def _deliver_chunk(self, ts_buf, col_bufs, fill_t0: int) -> None:  # noqa: SL402 — feeder-thread only (called from _feed_loop / superstep fallback)
        """K=1 delivery of one staged full chunk (the superstep fallback
        path — identical to the inline full-chunk branch of _feed_loop)."""
        from .event import EventBatch
        tele = getattr(self.ctx, "telemetry", None)
        tracing = tele is not None and tele.on
        bs = self.j.batch_size
        t0 = time.perf_counter_ns()
        batch = EventBatch.from_numpy(
            ts_buf, dict(zip(self.attrs, col_bufs)), bs)
        h2d = time.perf_counter_ns() - t0
        self._h2d_ns += h2d
        self._h2d_count += 1
        if tracing:
            trace = tele.mint(self.j.definition.id, bs, t0=fill_t0)
            trace.h2d_ns = h2d
            batch._trace = trace
            tele.record_lag(self.j.definition.id, int(ts_buf[bs - 1]))
        self._deliver_locked(batch, bs)

    def _feed_loop(self) -> None:
        from .event import EventBatch
        j = self.j
        bs = j.batch_size
        ring = self.ring
        attrs = self.attrs
        tele = getattr(self.ctx, "telemetry", None)
        tracing = tele is not None and tele.on
        sid = j.definition.id
        self._ss_k = max(1, int(getattr(self.ctx, "superstep_k", 1) or 1))
        superstep = self._ss_k > 1
        sstack: list = []  # staged full chunks awaiting one K-batch dispatch
        pending = None  # the double buffer: built + transferring, undelivered
        fill = 0
        fill_t0 = 0  # when the first row popped into the (empty) chunk
        ts_buf = np.zeros(bs, dtype=np.int64)
        col_bufs = [np.zeros(bs, dtype=dt) for dt in self.np_dtypes]
        while True:
            got = ring.pop(bs - fill, ts_buf[fill:],
                           tuple(c[fill:] for c in col_bufs))
            if got:
                if fill == 0 and (tracing or superstep):
                    fill_t0 = time.perf_counter_ns()
                fill += got
            if fill == bs:
                if superstep:
                    # stage the host chunk; at K staged chunks the whole
                    # stack rides one device dispatch. The staging itself
                    # is the pipelining, so the double buffer is bypassed.
                    sstack.append((ts_buf, col_bufs, fill_t0))
                    ts_buf = np.zeros(bs, dtype=np.int64)
                    col_bufs = [np.zeros(bs, dtype=dt)
                                for dt in self.np_dtypes]
                    fill = 0
                    if len(sstack) >= self._ss_k:
                        if not self._superstep_dispatch(sstack):
                            for c_ts, c_cols, c_t0 in sstack:
                                self._deliver_chunk(c_ts, c_cols, c_t0)
                        sstack = []
                        if self._ss_decline is not None:
                            superstep = False
                    continue
                # full chunk: start its H2D NOW (from_numpy = device_put),
                # then deliver the PREVIOUS chunk while this transfer runs
                t0 = time.perf_counter_ns()
                batch = EventBatch.from_numpy(
                    ts_buf, dict(zip(attrs, col_bufs)), bs)
                h2d = time.perf_counter_ns() - t0
                self._h2d_ns += h2d
                self._h2d_count += 1
                if tracing:
                    trace = tele.mint(sid, bs, t0=fill_t0)
                    trace.h2d_ns = h2d
                    batch._trace = trace
                    tele.record_lag(sid, int(ts_buf[bs - 1]))
                ts_buf = np.zeros(bs, dtype=np.int64)
                col_bufs = [np.zeros(bs, dtype=dt) for dt in self.np_dtypes]
                fill = 0
                if self._double_buffer:
                    if pending is not None:
                        self._deliver_locked(pending, bs)
                        self._overlapped += 1
                    pending = batch
                else:
                    self._deliver_locked(batch, bs)
                continue
            if got:
                continue  # partially filled; keep popping while data flows
            # ring momentarily empty
            flushing = self._flush_req.is_set()
            if flushing and sstack and not self._barrier_req.is_set() \
                    and not self._feeder_stop.is_set():
                # producer-backpressure flush (_claim_blocking: ring full)
                # while a superstep stack is staging: ignore it. Staging
                # keeps popping the ring, so producer space frees without
                # delivering anything — and delivering the partial fill
                # ahead of the staged chunks would reorder rows. Only a
                # drain()/stop() barrier flushes a staged stack.
                flushing = False
            if flushing and (fill or pending is not None or sstack):
                if sstack:
                    # partial superstep at a flush barrier: the staged
                    # chunks deliver per-batch (same step math, same state
                    # — bit-identical), oldest first
                    for c_ts, c_cols, c_t0 in sstack:
                        self._deliver_chunk(c_ts, c_cols, c_t0)
                    sstack = []
                if pending is not None:
                    self._deliver_locked(pending, bs)
                    pending = None
                if fill:
                    m = fill
                    pcap = j._pad_cap(m)
                    ts_c = np.empty(pcap, dtype=np.int64)
                    ts_c[:m] = ts_buf[:m]
                    ts_c[m:] = ts_buf[m - 1]  # monotone pad
                    cols_c = {}
                    for name, src in zip(attrs, col_bufs):
                        pad = np.zeros(pcap, dtype=src.dtype)
                        pad[:m] = src[:m]
                        cols_c[name] = pad
                    t0 = time.perf_counter_ns()
                    batch = EventBatch.from_numpy(ts_c, cols_c, m)
                    h2d = time.perf_counter_ns() - t0
                    self._h2d_ns += h2d
                    self._h2d_count += 1
                    if tracing:
                        trace = tele.mint(sid, m, t0=fill_t0)
                        trace.h2d_ns = h2d
                        batch._trace = trace
                        tele.record_lag(sid, int(ts_c[m - 1]))
                    fill = 0
                    ts_buf = np.zeros(bs, dtype=np.int64)
                    col_bufs = [np.zeros(bs, dtype=dt)
                                for dt in self.np_dtypes]
                    self._deliver_locked(batch, m)
                continue
            if fill == 0 and pending is None and not sstack \
                    and ring.size() == 0 and self._q.unfinished_tasks == 0:
                self._feeder_idle.set()
                if self._feeder_stop.is_set():
                    return
                self._flush_req.clear()
                self._barrier_req.clear()
                self._flush_req.wait(timeout=0.001)
            elif self._feeder_stop.is_set() and ring.size() == 0 \
                    and self._q.unfinished_tasks == 0:
                # stopping with a partial chunk: force the final flush
                self._flush_req.set()
            else:
                time.sleep(0.0002)

    # ----------------------------------------------------------------- drain

    def drain(self, timeout: float = 120.0) -> None:
        """Barrier: every row submitted before this call is delivered when
        it returns. Callers must NOT hold the controller lock (the feeder
        needs it to deliver); junction.flush() guards on _lock_owned."""
        self._q.join()  # all claimed runs are encoded + published
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._barrier_req.set()
            self._flush_req.set()
            if self._feeder_idle.is_set() and self.ring.size() == 0 \
                    and self._q.unfinished_tasks == 0:
                return
            time.sleep(0.0005)
        _log.warning("ingress drain timed out on %r (ring=%d)",  # pragma: no cover
                     self.j.definition.id, self.ring.size())

    def size(self) -> int:
        return self.ring.size() + self._q.unfinished_tasks

    # ------------------------------------------------------------ statistics

    def stats_snapshot(self) -> dict:
        elapsed_ns = max((time.monotonic() - self._t0) * 1e9, 1.0)
        busy = sum(self._worker_busy_ns)
        delivered = self._batches
        return {
            "workers": self.workers,
            "superstep_k": self._ss_k,
            "supersteps_dispatched": self._ss_supersteps,
            "superstep_decline": self._ss_decline,
            "superstep_scan_ms": self._ss_scan_ns / 1e6,
            "superstep_replay_ms": self._ss_replay_ns / 1e6,
            "ring_capacity": self.ring.capacity,
            "ring_depth_hwm": self.ring.hwm(),
            "rows_in": self._rows_in,
            "runs_in": self._runs_in,
            "frames_in": self._frames_in,
            "batches_delivered": delivered,
            "batches_overlapped": self._overlapped,
            "h2d_overlap_ratio": (self._overlapped / delivered
                                  if delivered else 0.0),
            "worker_utilization": busy / (elapsed_ns * self.workers),
            # per-stage: cumulative wall, how many units it covers, and the
            # per-unit mean — total alone made per-batch math impossible
            # (decode/intern are per worker RUN; h2d/device are per BATCH)
            "stage_ms": {
                "decode": _stage_cell(sum(self._worker_decode_ns),
                                      sum(self._worker_runs)),
                "intern": _stage_cell(sum(self._worker_intern_ns),
                                      sum(self._worker_runs)),
                "h2d": _stage_cell(self._h2d_ns, self._h2d_count),
                "device": _stage_cell(self._device_ns, self._batches),
            },
        }


def _stage_cell(total_ns: int, count: int) -> dict:
    total_ms = total_ns / 1e6
    return {"total_ms": total_ms, "batches": count,
            "mean_ms": total_ms / count if count else 0.0}


# ==========================================================================
# partition-key shard router (parallel/shard_plane.py's ingress half)
# ==========================================================================


class ShardRouter:
    """Routes rows to shard replicas by partition-key hash BEFORE any
    interning — dictionary codes are process- (and shard-) local, so the
    hash runs over ORIGINAL values: raw UTF-8 bytes for strings, the
    int64/float-bit mixing of `parallel.sharded.np_shard_of` for numerics
    (host routing stays bit-exact with the device key hash).

    Two-level map: `slot = hash(value) % n_slots` is stable for the life of
    the app; `assignment[slot] -> shard` is the mutable part — rebalancing
    republishes the assignment table instead of rehashing the world, and
    per-slot routed-row counters feed the skew detector. Row order within
    one (producer, key) pair is preserved: a boolean-mask split keeps
    relative order, and a key maps to exactly one shard per epoch."""

    #: FNV-1a 64-bit parameters — shared with np_shard_of
    _FNV_OFFSET = 0xCBF29CE484222325
    _FNV_PRIME = 0x100000001B3
    _MASK = (1 << 64) - 1

    def __init__(self, key: str, n_shards: int, n_slots: int = 64,
                 assignment=None) -> None:
        import threading

        import numpy as np
        if n_slots < n_shards:
            n_slots = n_shards
        self.key = key
        self.n_shards = n_shards
        self.n_slots = n_slots
        if assignment is not None:
            assignment = np.asarray(assignment, dtype=np.int64)
            if assignment.shape[0] != n_slots or \
                    (len(assignment) and assignment.max() >= n_shards):
                raise ValueError(
                    f"shard assignment must map {n_slots} slots to "
                    f"[0, {n_shards})")
            self.assignment = assignment.copy()
        else:
            self.assignment = np.arange(n_slots, dtype=np.int64) % n_shards
        self._lock = named_lock("ingress.shard_router")
        #: rows routed per slot / per shard since the current epoch began
        self.slot_rows = np.zeros(n_slots, dtype=np.int64)
        self.routed = np.zeros(n_shards, dtype=np.int64)
        self.total_rows = 0
        #: string value -> slot memo (the router-side analogue of the
        #: string table: the key universe is the dictionary universe)
        self._str_slots: dict = {}

    # ------------------------------------------------------------ hashing

    def _slot_of_str(self, s: str) -> int:
        slot = self._str_slots.get(s)
        if slot is None:
            h = self._FNV_OFFSET
            for b in s.encode("utf-8"):
                h = ((h ^ b) * self._FNV_PRIME) & self._MASK
            h ^= h >> 29
            slot = (h & 0xFFFFFFFF) % self.n_slots
            self._str_slots[s] = slot
        return slot

    def slot_of(self, value) -> int:
        """Stable slot of one ORIGINAL key value (scalar mirror of
        `slots_of_column` — tests assert they agree)."""
        import struct
        if value is None:
            return 0
        if isinstance(value, str):
            return self._slot_of_str(value)
        if isinstance(value, bool):
            value = int(value)
        if isinstance(value, float):
            value = struct.unpack("<q", struct.pack("<d", value))[0]
        x = int(value) & self._MASK
        h = ((self._FNV_OFFSET ^ x) * self._FNV_PRIME) & self._MASK
        h ^= h >> 29
        return (h & 0xFFFFFFFF) % self.n_slots

    def slots_of_column(self, col, n=None):
        """Vectorized `slot_of` over one key column: a numpy array, an
        object array of strings, or a `('dict', values, idx)` wire triple
        (hashed per DISTINCT value, mapped through the index)."""
        import numpy as np
        if isinstance(col, tuple) and len(col) == 3 and col[0] == "dict":
            _tag, values, idx = col
            idx = np.asarray(idx)[:n] if n is not None else np.asarray(idx)
            vslots = np.array(
                [self.slot_of(v) for v in values], dtype=np.int64) \
                if len(values) else np.zeros(0, dtype=np.int64)
            out = np.zeros(idx.shape[0], dtype=np.int64)
            valid = idx >= 0
            if valid.any():
                out[valid] = vslots[idx[valid]]
            return out
        arr = np.asarray(col)
        if n is not None:
            arr = arr[:n]
        if arr.dtype.kind in ("O", "U"):
            return np.array([self.slot_of(v) for v in arr.tolist()],
                            dtype=np.int64)
        from ..parallel.sharded import np_shard_of
        return np_shard_of([arr], self.n_slots).astype(np.int64)

    # ------------------------------------------------------------ routing

    def shard_of(self, value) -> int:
        return int(self.assignment[self.slot_of(value)])

    def republish(self, assignment) -> None:
        """Atomically swap the slot→shard table. A rebalance (or a front
        tier refreshing its view from a newer shardmeta epoch) republishes
        the assignment instead of rehashing the world — `slot = hash(key)
        % n_slots` never changes, so in-flight `slot_of` results stay
        valid across the swap."""
        import numpy as np
        arr = np.asarray(assignment, dtype=np.int64)
        if arr.shape[0] != self.n_slots or \
                (len(arr) and arr.max() >= self.n_shards):
            raise ValueError(
                f"shard assignment must map {self.n_slots} slots to "
                f"[0, {self.n_shards})")
        with self._lock:
            self.assignment = arr.copy()

    def note_routed(self, slots) -> None:
        """Account one routed batch into the skew counters."""
        import numpy as np
        counts = np.bincount(slots, minlength=self.n_slots)
        with self._lock:
            self.slot_rows += counts
            np.add.at(self.routed, self.assignment, counts)
            self.total_rows += int(counts.sum())

    def split_rows(self, tss, rows, key_index: int):
        """{shard: (tss, rows)} preserving per-shard row order."""
        groups: dict = {}
        slots = []
        for ts, row in zip(tss, rows):
            slot = self.slot_of(row[key_index])
            slots.append(slot)
            shard = int(self.assignment[slot])
            g = groups.get(shard)
            if g is None:
                g = groups[shard] = ([], [])
            g[0].append(ts)
            g[1].append(row)
        import numpy as np
        self.note_routed(np.asarray(slots, dtype=np.int64))
        return groups

    def split_columns(self, columns: dict, ts_arr, n: int):
        """{shard: (ts_sub, cols_sub, count)} — columns may mix numpy
        arrays and `('dict', values, idx)` triples; dict columns are
        COMPACTED per shard (`io.wire.subset_dict_column`) so each shard
        interns only the values its keys reference."""
        import numpy as np

        from ..io.wire import subset_dict_column
        key_col = columns.get(self.key)
        if key_col is None:
            raise KeyError(
                f"shard routing: batch has no partition-key column "
                f"{self.key!r}")
        slots = self.slots_of_column(key_col, n)
        self.note_routed(slots)
        shards = self.assignment[slots]
        out: dict = {}
        for shard in np.unique(shards):
            sel = shards == shard
            cols_sub = {}
            for name, col in columns.items():
                if isinstance(col, tuple) and len(col) == 3 \
                        and col[0] == "dict":
                    cols_sub[name] = subset_dict_column(
                        col[1], np.asarray(col[2])[:n], sel)
                else:
                    cols_sub[name] = np.asarray(col)[:n][sel]
            out[int(shard)] = (np.asarray(ts_arr)[:n][sel], cols_sub,
                               int(sel.sum()))
        return out

    # ------------------------------------------------------- skew detector

    def skew_report(self) -> dict:
        """Per-shard routed totals + the imbalance ratio the rebalance
        trigger keys off (max shard load over the even-split ideal)."""
        import numpy as np
        with self._lock:
            routed = self.routed.copy()
            slot_rows = self.slot_rows.copy()
            total = self.total_rows
        ideal = total / self.n_shards if self.n_shards else 0.0
        imbalance = float(routed.max() / ideal) if ideal > 0 else 1.0
        hot = np.argsort(slot_rows)[::-1][:8]
        return {
            "total_rows": int(total),
            "per_shard": {f"s{i}": int(r) for i, r in enumerate(routed)},
            "imbalance": imbalance,
            "hot_slots": [
                {"slot": int(s), "shard": int(self.assignment[s]),
                 "rows": int(slot_rows[s])}
                for s in hot if slot_rows[s] > 0],
        }

    def propose_assignment(self):
        """Greedy LPT bin-packing of slots onto shards by observed load —
        heaviest slot first onto the lightest shard. Slots with no traffic
        keep their current shard (no gratuitous state moves)."""
        import numpy as np
        with self._lock:
            slot_rows = self.slot_rows.copy()
        proposal = self.assignment.copy()
        load = np.zeros(self.n_shards, dtype=np.int64)
        active = [int(s) for s in np.argsort(slot_rows)[::-1]
                  if slot_rows[s] > 0]
        for slot in active:
            shard = int(np.argmin(load))
            proposal[slot] = shard
            load[shard] += int(slot_rows[slot])
        return proposal

    def reset_counters(self) -> None:
        with self._lock:
            self.slot_rows[:] = 0
            self.routed[:] = 0
            self.total_rows = 0
