"""Join query runtime (reference: core/query/input/stream/join/JoinProcessor.java:45,
JoinInputStreamParser.java:75).

One runtime serves `from L#w() join R#w() on cond`. Each side keeps its own
window ring; a batch arriving on a triggering side is appended to its own
window and probed against the *opposite* side's current contents (the
reference's `find()` with a CompiledCondition becomes a batched sort-merge /
cross probe — ops/join.py). Table sides probe the table's device state.

Ordering note (divergence, documented): within one micro-batch of a self-join,
intra-batch pairs are not emitted (each batch probes the opposite ring as of
the previous flush). Across junction flushes the reference's per-event
interleaving is preserved.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..errors import DefinitionNotExistError, SiddhiAppCreationError
from ..extension.registry import ExtensionKind, Registry
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..ops.join import (JoinPlan, _hash_exprs, collect_vars, compact_pairs,
                        multimap_append, multimap_buckets, multimap_init,
                        plan_join, probe_cross, probe_equi, probe_equi_mm)
from ..ops.selector import CompiledSelector
from ..ops.window_factories import WindowFactory
from ..ops.windows import (PassThroughWindow, SlidingWindow, WindowOp,
                           _unpack_rows)
from ..query_api.definition import Attribute, AttributeType, StreamDefinition
from ..query_api.execution import (
    EventTrigger,
    JoinInputStream,
    JoinType,
    OutputAction,
    Query,
    SingleInputStream,
)
from . import dtypes
from .context import SiddhiAppContext
from .event import EventBatch, EventType, StreamCodec
from .query_runtime import QueryCallback, eval_constant
from .stream import Receiver, StreamJunction


def _qualify_for_store(expr, probe_side, table_side, resolver):
    """Rewrite a join ON condition for the store walk: table-side variables
    (by RESOLVER classification — aliases and unqualified attrs included)
    get the table DEFINITION id (walk_condition's table_id), probe-side
    variables get the probe ref (the parameter-name prefix). Variables
    resolving to neither frame raise — no fallback for them."""
    import dataclasses as _dc

    from ..ops.join import frames_of
    from ..query_api.expression import Expression, Variable
    table_id = table_side.table.definition.id

    from ..query_api.expression import IsNull

    def walk(e):
        if isinstance(e, IsNull) and isinstance(e.expression, Variable):
            fr = frames_of(e.expression, resolver)
            if not fr <= {table_side.ref}:
                # walk_condition's isNull compiles against the TABLE row
                # only — a probe-side null test would silently evaluate the
                # wrong column; no fallback for those conditions
                raise SiddhiAppCreationError(
                    "store fallback cannot express probe-side isNull")
            return _dc.replace(e, expression=_dc.replace(
                e.expression, stream_id=table_id))
        if isinstance(e, Variable):
            fr = frames_of(e, resolver)
            if fr <= {table_side.ref}:
                return _dc.replace(e, stream_id=table_id)
            if fr <= {probe_side.ref}:
                return _dc.replace(e, stream_id=probe_side.ref)
            raise SiddhiAppCreationError(
                f"store fallback cannot classify {e.attribute!r}")
        kw = {}
        for a in ("left", "right", "expression"):
            sub = getattr(e, a, None)
            if isinstance(sub, Expression):
                kw[a] = walk(sub)
        if getattr(e, "parameters", None):
            return _dc.replace(e, parameters=tuple(
                walk(p) if isinstance(p, Expression) else p
                for p in e.parameters))
        if kw:
            return _dc.replace(e, **kw)
        return e

    return walk(expr)


class _Side:
    """One join side: a stream (junction + window), a table, or a named
    window (probed via its shared contents; its emissions also trigger)."""

    def __init__(self, ins: SingleInputStream, ctx, registry, junctions, tables,
                 windows=None, aggregations=None, per=None):
        self.ref = ins.reference_id  # alias or stream id
        self.stream_id = ins.stream_id
        self.is_table = ins.stream_id in tables
        self.table = tables.get(ins.stream_id)
        if self.is_table:
            from ..io.record_table import RecordTableRuntime
            if isinstance(self.table, RecordTableRuntime):
                if self.table.cache is None:
                    raise SiddhiAppCreationError(
                        f"record table {ins.stream_id!r} has no @cache: joins "
                        "probe tables inside the jitted step and need "
                        "@cache(size='N', policy='FIFO|LRU|LFU')")
                self.table._used_in_probe = True  # cache-miss monitor
        self.named_window = (windows or {}).get(ins.stream_id)
        self.is_named_window = self.named_window is not None and not self.is_table
        self.aggregation = (aggregations or {}).get(ins.stream_id)
        self.is_aggregation = (self.aggregation is not None and not self.is_table
                               and not self.is_named_window)
        self.agg_view = None
        self.junction: Optional[StreamJunction] = None
        self.window: Optional[WindowOp] = None
        self.filters = []
        if self.is_aggregation:
            # `from S join Agg per "duration" on ...` (reference:
            # AggregationRuntime.compileExpression:384+ / JoinInputStreamParser).
            # Divergence, documented: `within start, end` bucket-range bounds on
            # joins are not supported — use the ON condition over AGG_TIMESTAMP.
            if per is None:
                raise SiddhiAppCreationError(
                    f"joining aggregation {ins.stream_id!r} needs `per '<duration>'`")
            if ins.handlers.window is not None:
                raise SiddhiAppCreationError(
                    "aggregations cannot take windows in joins")
            self.agg_view = self.aggregation.view(per)
            self.attr_types = dict(self.aggregation.output_attr_types)
            self.codec = self.aggregation.output_codec
        elif self.is_table:
            if ins.handlers.window is not None:
                raise SiddhiAppCreationError("tables cannot take windows in joins")
            self.attr_types = dict(self.table.attr_types)
            self.codec = self.table.codec
        elif self.is_named_window:
            if ins.handlers.window is not None:
                raise SiddhiAppCreationError(
                    "named windows cannot take further windows in joins")
            self.attr_types = dict(self.named_window.attr_types)
            self.codec = self.named_window.codec
            # the window's emission stream triggers this side
            self.junction = self.named_window.output_junction
        else:
            self.junction = junctions.get(ins.stream_id)
            if self.junction is None:
                raise DefinitionNotExistError(
                    f"stream {ins.stream_id!r} is not defined")
            self.codec = self.junction.codec
            self.attr_types = {
                a.name: a.type for a in self.junction.definition.attributes
                if a.type != AttributeType.OBJECT}
            from ..ops.windows import make_layout
            layout = make_layout(self.attr_types)
            batch_cap = self.junction.batch_size
            wh = ins.handlers.window
            if wh is not None:
                factory = registry.require(ExtensionKind.WINDOW, wh.namespace, wh.name)
                assert isinstance(factory, WindowFactory)
                params = [eval_constant(p) for p in wh.parameters]
                registry.validate_params(ExtensionKind.WINDOW, wh.namespace,
                                         wh.name, params, what="window")
                self.window = factory.make(layout, batch_cap, params, True)
            else:
                self.window = PassThroughWindow(layout, batch_cap)
        self.handlers = ins.handlers


class JoinQueryRuntime:
    def __init__(self, query: Query, ctx: SiddhiAppContext,
                 junctions: dict, tables: dict, registry: Registry,
                 name: str, windows: Optional[dict] = None,
                 aggregations: Optional[dict] = None) -> None:
        assert isinstance(query.input_stream, JoinInputStream)
        jis: JoinInputStream = query.input_stream
        self.query = query
        self.ctx = ctx
        self.name = name
        self.registry = registry
        self.callbacks: list[QueryCallback] = []
        self._dropped_dev = None
        self._drop_checks = 0
        self._drop_warned = False
        self.output_junction = None
        self.table_executor = None
        self.k_max = dtypes.config.join_max_matches

        self.left = _Side(jis.left, ctx, registry, junctions, tables, windows,
                          aggregations, jis.per)
        self.right = _Side(jis.right, ctx, registry, junctions, tables, windows,
                           aggregations, jis.per)
        if self.left.is_table and self.right.is_table:
            raise SiddhiAppCreationError("cannot join two tables in a stream query")
        if self.left.is_aggregation and self.right.is_aggregation:
            raise SiddhiAppCreationError("cannot join two aggregations")
        if self.left.ref == self.right.ref:
            raise SiddhiAppCreationError(
                "self-joins need an alias: `from S as a join S as b ...`")
        self.join_type = jis.join_type
        self.trigger = jis.trigger
        self.within_ms = jis.within_ms

        # --- resolver over both frames ---
        frames = {self.left.ref: self.left.attr_types,
                  self.right.ref: self.right.attr_types}
        codecs = {self.left.ref: self.left.codec, self.right.ref: self.right.codec}

        def _sp(side):
            # unionSet-projection provenance: junction-fed sides read the
            # upstream output definition's markers; table sides the marker
            # set at wiring time (app_runtime._wire_output)
            if side.is_table:
                return set(getattr(side.table, "set_projection_attrs", ())
                           or ())
            if side.junction is not None:
                return {a.name for a in side.junction.definition.attributes
                        if getattr(a, "set_projection", False)}
            return set()

        set_projections = {ref: sp for ref, sp in
                           ((self.left.ref, _sp(self.left)),
                            (self.right.ref, _sp(self.right))) if sp}
        self.resolver = TypeResolver(frames, self.left.ref, codecs,
                                     set_projections)

        for side in (self.left, self.right):
            side.filters = [compile_expression(f, self.resolver, registry)
                            for f in side.handlers.filters]

        # --- join plans (one per probe direction) ---
        self.plan_from_left = plan_join(jis.on, self.left.ref, self.right.ref,
                                        self.resolver, registry)
        self.plan_from_right = plan_join(jis.on, self.right.ref, self.left.ref,
                                         self.resolver, registry)

        # --- store-fallback key extraction for cached @store sides ---
        # (reference: AbstractQueryableRecordTable.java:109,207-238 — the
        # cache read path falls back to the store on miss). Per table side,
        # record the simple-attribute equi pairs so on_side_batch can
        # pre-warm the cache with the batch's keys once the store outgrows it.
        from ..io.record_table import RecordTableRuntime
        for t_side, p_side in ((self.left, self.right),
                               (self.right, self.left)):
            t_side._fallback_pairs = None
            t_side._fallback_cond = None
            if (t_side.is_table and isinstance(t_side.table, RecordTableRuntime)
                    and t_side.table.cache_policy is not None):
                pairs = self._simple_equi_pairs(jis.on, p_side, t_side)
                t_side._fallback_pairs = pairs
                if pairs:
                    t_side.table._probe_fallback_ready = True
                else:
                    # non-equi / mixed conditions (`S.k < T.k`): compile the
                    # WHOLE ON condition into a parameterized store
                    # predicate; each probing batch then warms the cache
                    # with every store row matching any probe row
                    # (ensure_cached_for_condition). Conditions the store
                    # walk cannot express (math/functions over table attrs)
                    # keep the documented cache-only miss
                    try:
                        on_rw = _qualify_for_store(
                            jis.on, p_side, t_side, self.resolver)
                        pred = t_side.table.compile_param_condition(on_rw)
                        probe_attrs = sorted({
                            v.attribute
                            for v in collect_vars(on_rw)
                            if v.stream_id == p_side.ref})
                        t_side._fallback_cond = (pred, tuple(probe_attrs))
                        t_side.table._probe_fallback_ready = True
                    except SiddhiAppCreationError:
                        t_side.table._probe_nofallback = True

        # --- selector over the pair frames ---
        select_all = [(n, t) for n, t in self.left.attr_types.items()]
        for n, t in self.right.attr_types.items():
            if n not in dict(select_all):
                select_all.append((n, t))
        self.selector = CompiledSelector(
            query.selector, self.resolver, registry,
            ctx.effective_group_capacity, self.left.ref,
            select_all_attrs=select_all)

        self.output_attributes = tuple(
            Attribute(n, t,
                      set_projection=n in self.selector.host_set_slots)
            for n, t in self.selector.out_types.items())
        self.output_definition = StreamDefinition(
            id=query.output_stream.target_id or f"{name}_out",
            attributes=self.output_attributes)
        self.output_codec = StreamCodec(self.output_definition, ctx.global_strings)

        # --- incremental hash multimaps (one per hashable build side) ---
        # A side's multimap serves probes FROM the other side; it indexes the
        # side's sliding ring by the equi-key hash of the plan that treats it
        # as the build frame. Inserted at append time, probed chain-walk only
        # — no per-step build sort (reference find(): JoinProcessor.java:140).
        def _mm_setup(side, plan_as_build):
            if (isinstance(side.window, SlidingWindow)
                    and plan_as_build.probe_keys):
                return multimap_buckets(side.window.C)
            return None

        self.left._mm_buckets = _mm_setup(self.left, self.plan_from_right)
        self.right._mm_buckets = _mm_setup(self.right, self.plan_from_left)
        self.left._mm_build_keys = self.plan_from_right.build_keys
        self.right._mm_build_keys = self.plan_from_left.build_keys

        def _side_state(s):
            if s.is_table or s.is_named_window or s.is_aggregation:
                return ()
            return s.window.init_state()

        def _mm_state(s):
            if s._mm_buckets is None:
                return ()
            return multimap_init(s.window.C, s._mm_buckets)

        self.state = (
            _side_state(self.left),
            _side_state(self.right),
            _mm_state(self.left),
            _mm_state(self.right),
            self.selector.init_state(),
        )
        self._step_left = jax.jit(self._make_step(from_left=True),
                                  donate_argnums=(0,))
        self._step_right = jax.jit(self._make_step(from_left=False),
                                   donate_argnums=(0,))
        from ..ops.windows import window_has_time_semantics
        self.has_time_semantics = any(
            s.window is not None and window_has_time_semantics(s.window)
            for s in (self.left, self.right))

    # ------------------------------------------------------------------- plan

    def _simple_equi_pairs(self, on, probe_side, table_side):
        """(probe_attr, table_attr) pairs from `a.x == T.y` conjuncts —
        the shapes the host store fallback can key on. Computed-key equi
        joins (e.g. `f(a.x) == T.y`) get no fallback (documented)."""
        from ..ops.join import frames_of, split_conjuncts
        from ..query_api.expression import Compare, CompareOp, Variable
        pairs = []
        for conj in split_conjuncts(on):
            if not (isinstance(conj, Compare) and conj.op == CompareOp.EQUAL):
                continue
            l, r = conj.left, conj.right
            if not (isinstance(l, Variable) and isinstance(r, Variable)):
                continue
            lf = frames_of(l, self.resolver)
            rf = frames_of(r, self.resolver)
            if lf <= {probe_side.ref} and rf <= {table_side.ref}:
                pairs.append((l.attribute, r.attribute))
            elif lf <= {table_side.ref} and rf <= {probe_side.ref}:
                pairs.append((r.attribute, l.attribute))
        return pairs or None

    def _maybe_store_fallback(self, build, probe, batch: EventBatch) -> None:
        """Pre-warm an overflowed probe cache with this batch's join keys
        (host read-through) so the device probe cannot miss evicted rows.
        Runs BEFORE the step — outer joins then emit nulls only for true
        non-matches, and the selector sees one consistent pass."""
        table = build.table
        pol = getattr(table, "cache_policy", None)
        if pol is None or not pol.overflowed:
            return
        pairs = build._fallback_pairs
        if not pairs:
            if build._fallback_cond is not None:
                self._condition_fallback(build, probe, batch)
            return  # else: PARITY-documented miss warning applies
        valid, host = jax.device_get(
            (batch.valid, {pa: batch.cols[pa] for pa, _ in pairs}))
        import numpy as np
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return
        key_cols = []
        for pa, _ta in pairs:
            arr = host[pa][idx]
            at = probe.attr_types[pa]
            if at == AttributeType.STRING:
                key_cols.append(
                    probe.codec.string_tables[pa].decode_array(arr.tolist()))
            elif at == AttributeType.BOOL:
                key_cols.append(arr.astype(bool).tolist())
            else:
                key_cols.append(arr.tolist())
        table.ensure_cached_for_keys(
            tuple(ta for _pa, ta in pairs), set(zip(*key_cols)))

    def _condition_fallback(self, build, probe, batch: EventBatch) -> None:
        """Non-equi / computed probe conditions: warm the cache with every
        store row matching ANY of this batch's probe rows through the
        parameterized store predicate (reference:
        AbstractQueryableRecordTable.java:207-238 — the store is queried
        with streamVariable parameters on every cache miss)."""
        import numpy as np
        pred, probe_attrs = build._fallback_cond
        valid, host = jax.device_get(
            (batch.valid, {a: batch.cols[a] for a in probe_attrs}))
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return
        cols = {}
        for a in probe_attrs:
            arr = host[a][idx]
            at = probe.attr_types[a]
            if at == AttributeType.STRING:
                cols[a] = probe.codec.string_tables[a].decode_array(
                    arr.tolist())
            elif at == AttributeType.BOOL:
                cols[a] = arr.astype(bool).tolist()
            else:
                cols[a] = arr.tolist()
        # distinct probe parameter rows, keyed the way walk_condition names
        # stream values ("<probe_ref>.<attr>")
        seen = set()
        param_rows = []
        keys = []
        for i in range(len(idx)):
            t = tuple(cols[a][i] for a in probe_attrs)
            if t in seen:
                continue
            seen.add(t)
            keys.append(t)
            param_rows.append({f"{probe.ref}.{a}": v
                               for a, v in zip(probe_attrs, t)})
        # skip the quadratic store scan for parameter rows already warmed
        # while BOTH the store (rev) and the cache residency (evictions)
        # were unchanged — steady-state probing of a quiet store then costs
        # zero host scans (ADVICE r5); any store write or cache eviction
        # invalidates the memo, falling back to the per-batch scan
        epoch = (build.table._store_rev, build.table.cache_policy.evictions)
        warmed = getattr(build, "_cond_warmed", None)
        if warmed is None or warmed[0] != epoch:
            warmed = (epoch, set())
        fresh = [(t, p) for t, p in zip(keys, param_rows)
                 if t not in warmed[1]]
        if not fresh:
            build._cond_warmed = warmed
            return
        build.table.ensure_cached_for_condition(pred, [p for _, p in fresh])
        # the warm itself may evict (counter moved): re-key so the NEXT
        # batch revalidates; the fresh keys stay memoized under the new
        # epoch only if nothing was displaced
        epoch2 = (build.table._store_rev, build.table.cache_policy.evictions)
        memo = warmed[1] if epoch2 == epoch else set()
        memo.update(t for t, _ in fresh)
        if len(memo) > (1 << 16):  # bounded memo
            memo.clear()
        build._cond_warmed = (epoch2, memo)

    def _probe_outer(self, from_left: bool) -> bool:
        if self.join_type == JoinType.FULL_OUTER:
            return True
        if self.join_type == JoinType.LEFT_OUTER:
            return from_left
        if self.join_type == JoinType.RIGHT_OUTER:
            return not from_left
        return False

    def _make_step(self, from_left: bool):
        probe_side = self.left if from_left else self.right
        build_side = self.right if from_left else self.left
        plan = self.plan_from_left if from_left else self.plan_from_right
        selector = self.selector
        k_max = self.k_max
        within = self.within_ms
        outer = self._probe_outer(from_left)
        filters = probe_side.filters

        use_mm = (build_side._mm_buckets is not None
                  and not (build_side.is_table or build_side.is_named_window
                           or build_side.is_aggregation)
                  and bool(plan.probe_keys))
        stats = self.ctx.statistics
        qname = self.name

        def step(state, batch: EventBatch, now, build_tstate=None):
            # trace-time: per-query compile counter (see Statistics)
            stats.track_compile(qname, batch.ts.shape[0])
            wl, wr, mml, mmr, sel = state
            w_probe, w_build = (wl, wr) if from_left else (wr, wl)
            mm_probe, mm_build = (mml, mmr) if from_left else (mmr, mml)

            # --- probe-side filter + window append ---
            pscope = Scope()
            pscope.add_frame(probe_side.ref, batch.cols, batch.ts, batch.valid,
                             default=True)
            pscope.extras["now"] = now
            mask = batch.valid
            if probe_side.is_named_window:
                # window emissions carry CURRENT + EXPIRED; only arrivals probe
                mask = mask & (batch.types == EventType.CURRENT)
            for f in filters:
                mask = mask & f(pscope)
            batch = dataclasses.replace(batch, valid=mask)
            pscope.valids[probe_side.ref] = mask

            if not (probe_side.is_table or probe_side.is_named_window
                    or probe_side.is_aggregation):
                appended0 = getattr(w_probe, "appended", None)
                w_probe, _chunk = probe_side.window.step(w_probe, batch, now)
                if probe_side._mm_buckets is not None:
                    live = mask & (batch.types == EventType.CURRENT)
                    hashes = _hash_exprs(probe_side._mm_build_keys, pscope)
                    mm_probe = multimap_append(mm_probe, hashes, live,
                                               appended0)

            # --- build-side contents (multimap path never materializes
            #     the full ring — candidates gather packed rows below) ---
            if use_mm:
                b_cols = b_ts = b_valid = None
            elif build_side.is_table:
                b_cols = build_tstate.cols
                b_ts = build_tstate.ts
                b_valid = build_tstate.valid
            elif build_side.is_named_window:
                b_cols, b_ts, b_valid = build_side.named_window.contents(
                    build_tstate, now)
            elif build_side.is_aggregation:
                b_cols, b_ts, b_valid = build_side.agg_view.contents(
                    build_tstate, now)
            else:
                b_cols, b_ts, b_valid = build_side.window.contents(w_build, now)
            if (not use_mm) and build_side.filters and (
                    build_side.is_table or build_side.is_named_window
                    or build_side.is_aggregation):
                # stream sides are filtered before their ring append; probed
                # contents (tables / named windows) are filtered here
                bscope = Scope()
                bscope.add_frame(build_side.ref, b_cols, b_ts, b_valid,
                                 default=True)
                bscope.extras["now"] = now
                for f in build_side.filters:
                    b_valid = b_valid & f(bscope)

            # --- candidate pairs ---
            truncated = jnp.int32(0)
            if use_mm:
                bw = build_side.window
                window_len = w_build.appended - jnp.maximum(
                    w_build.expired, w_build.appended - bw.C)
                lane, brow, pv, truncated = probe_equi_mm(
                    plan, pscope, mask, mm_build, w_build.appended,
                    window_len, k_max)
                if bw.time_ms is not None:
                    # probe-time expiry BEFORE pair compaction, mirroring
                    # SlidingWindow.contents(): a time window whose own side
                    # went idle holds rows past their deadline that would
                    # otherwise consume pair_cap slots and evict live matches
                    tsw = w_build.ring[-2:, brow]
                    cand_ts = jax.lax.bitcast_convert_type(
                        jnp.stack([tsw[0], tsw[1]], axis=-1), jnp.int64)
                    pv = pv & (cand_ts + jnp.int64(bw.time_ms) > now)
            elif plan.probe_keys:
                lane, brow, pv = probe_equi(
                    plan, pscope, mask, b_cols, b_ts, b_valid,
                    build_side.ref, k_max)
            else:
                lane, brow, pv = probe_cross(mask, b_valid, k_max)
            # compact the sparse [B*k_max] block before any per-pair gather —
            # frame materialization, verification, and the selector then run
            # at ~the real match count instead of k_max x batch. Small blocks
            # keep full width (compaction would only risk truncation there);
            # big blocks cap at factor*B with a monitored drop counter.
            B_probe = batch.ts.shape[0]
            pair_cap = min(lane.shape[0],
                           max(dtypes.config.join_pair_cap_factor * B_probe,
                               32768))
            if pair_cap < lane.shape[0]:
                n_matches = jnp.sum(pv, dtype=jnp.int32)
                dropped = jnp.maximum(n_matches - pair_cap, 0) + truncated
                lane, brow, pv = compact_pairs(lane, brow, pv, pair_cap)
            else:
                dropped = truncated

            # --- pair frames ---
            p_cols = {k: v[lane] for k, v in batch.cols.items()}
            p_ts = batch.ts[lane]
            if use_mm:
                rows = w_build.ring[:, brow]  # [W, P] packed lane gather
                g_cols, g_ts = _unpack_rows(rows, build_side.window.layout)
            else:
                g_cols = {k: v[brow] for k, v in b_cols.items()}
                g_ts = b_ts[brow]

            pair = Scope()
            if from_left:
                pair.add_frame(probe_side.ref, p_cols, p_ts, pv, default=True)
                pair.add_frame(build_side.ref, g_cols, g_ts, pv)
            else:
                pair.add_frame(build_side.ref, g_cols, g_ts, pv)
                pair.add_frame(probe_side.ref, p_cols, p_ts, pv, default=True)
                pair.default_frame = probe_side.ref
            pair.extras["now"] = now

            # --- exact verification: full ON condition + within ---
            if plan.residual is not None:
                pv = pv & plan.residual(pair)
            if within is not None:
                pv = pv & (jnp.abs(p_ts - g_ts) <= jnp.int64(within))

            P = lane.shape[0]
            B = batch.ts.shape[0]
            if outer:
                # unmatched probe lanes join a null build frame
                matched = jax.ops.segment_max(
                    pv.astype(jnp.int32), lane, num_segments=B) > 0
                o_valid = mask & ~matched
                if use_mm:
                    zero_g = {k: jnp.zeros((B,), jnp.dtype(dt))
                              for k, dt in build_side.window.layout.items()}
                else:
                    zero_g = {k: jnp.zeros((B,), v.dtype)
                              for k, v in b_cols.items()}
                lane = jnp.concatenate([lane, jnp.arange(B)])
                all_pv = jnp.concatenate([pv, o_valid])
                has_build = jnp.concatenate(
                    [jnp.ones((P,), bool), jnp.zeros((B,), bool)])
                p_cols = {k: jnp.concatenate([v, batch.cols[k]])
                          for k, v in p_cols.items()}
                p_ts = jnp.concatenate([p_ts, batch.ts])
                g_cols = {k: jnp.concatenate([v, zero_g[k]])
                          for k, v in g_cols.items()}
                g_ts = jnp.concatenate([g_ts, jnp.zeros((B,), g_ts.dtype)])
                pv = all_pv
            else:
                has_build = jnp.ones((P,), bool)

            # zero the build frame on no-build lanes so projections emit nulls
            bf_valid = pv & has_build
            g_cols = {k: jnp.where(bf_valid, v, jnp.zeros((), v.dtype))
                      for k, v in g_cols.items()}

            out_scope = Scope()
            lf_cols, lf_ts = (p_cols, p_ts) if from_left else (g_cols, g_ts)
            rf_cols, rf_ts = (g_cols, g_ts) if from_left else (p_cols, p_ts)
            lf_valid = pv if from_left else bf_valid
            rf_valid = bf_valid if from_left else pv
            out_scope.add_frame(self.left.ref, lf_cols, lf_ts, lf_valid,
                                default=True)
            out_scope.add_frame(self.right.ref, rf_cols, rf_ts, rf_valid)
            out_scope.extras["now"] = now

            W = pv.shape[0]
            chunk = EventBatch(
                ts=p_ts, cols={},
                valid=pv,
                types=jnp.zeros((W,), jnp.int8))  # CURRENT
            sel, out = selector.step(sel, chunk, out_scope)

            new_wl, new_wr = (w_probe, w_build) if from_left else (w_build, w_probe)
            new_mml, new_mmr = ((mm_probe, mm_build) if from_left
                                else (mm_build, mm_probe))
            return (new_wl, new_wr, new_mml, new_mmr, sel), out, dropped

        return step

    # ---------------------------------------------------------------- runtime

    def warmup(self, buckets=None) -> int:
        """AOT-compile both probe directions at their planned batch capacity
        (join steps always receive full-capacity batches — on_side_batch
        pads bucketed deliveries back up) without executing them
        (query_runtime.aot_warm). Returns fresh compiles triggered."""
        from .query_runtime import aot_warm
        n0 = self.ctx.statistics.compiles.get(self.name, 0)
        now = jnp.int64(self.ctx.timestamp_generator.current_time())
        for from_left in (True, False):
            side = self.left if from_left else self.right
            build = self.right if from_left else self.left
            if side.junction is None:
                continue
            triggers = (self.trigger == EventTrigger.ALL
                        or (self.trigger == EventTrigger.LEFT and from_left)
                        or (self.trigger == EventTrigger.RIGHT
                            and not from_left))
            if not triggers:
                continue
            if build.is_table:
                tstate = build.table.state
            elif build.is_named_window:
                tstate = build.named_window.state
            elif build.is_aggregation:
                tstate = build.agg_view.state
            else:
                tstate = None
            step = self._step_left if from_left else self._step_right
            batch = EventBatch.empty(side.junction.definition,
                                     side.junction.batch_size)
            aot_warm(step, self.state, batch, now, tstate)
        return self.ctx.statistics.compiles.get(self.name, 0) - n0

    def on_side_batch(self, from_left: bool, batch: EventBatch, now: int) -> None:
        side = self.left if from_left else self.right
        build = self.right if from_left else self.left
        if side.junction is not None and \
                batch.capacity < side.junction.batch_size:
            # join steps are traced at the side's full batch capacity;
            # bucketed junction deliveries widen back (invalid lanes)
            batch = batch.pad_to(side.junction.batch_size)
        triggers = (self.trigger == EventTrigger.ALL
                    or (self.trigger == EventTrigger.LEFT and from_left)
                    or (self.trigger == EventTrigger.RIGHT and not from_left))
        step = self._step_left if from_left else self._step_right
        if build.is_table:
            if getattr(build, "_fallback_pairs", None) is not None or \
                    getattr(build, "_fallback_cond", None) is not None:
                self._maybe_store_fallback(build, side, batch)
            tstate = build.table.state
        elif build.is_named_window:
            tstate = build.named_window.state
        elif build.is_aggregation:
            tstate = build.agg_view.state
        else:
            tstate = None
        if not triggers:
            # non-triggering side still feeds its window (+ multimap)
            if side.is_table or side.is_named_window or side.is_aggregation:
                return
            wl, wr, mml, mmr, sel = self.state
            w = wl if from_left else wr
            mm = mml if from_left else mmr
            w2, mm2 = self._append_only(side, w, mm, batch, now)
            self.state = ((w2, wr, mm2, mmr, sel) if from_left
                          else (wl, w2, mml, mm2, sel))
            return
        self.state, out, dropped = step(self.state, batch, jnp.int64(now),
                                        tstate)
        # accumulate on device; sync only at checkpoints (an int() every
        # batch would serialize the async dispatch pipeline)
        self._dropped_dev = (dropped if self._dropped_dev is None
                             else self._dropped_dev + dropped)
        self._drop_checks += 1
        if not self._drop_warned and self._drop_checks % 64 == 0:
            if int(self._dropped_dev) > 0:
                import warnings
                warnings.warn(
                    f"join {self.name!r}: {int(self._dropped_dev)} matched "
                    "pairs exceeded the per-step pair block or the per-probe "
                    "candidate walk and were dropped — raise "
                    "config.join_pair_cap_factor / config.join_max_matches",
                    stacklevel=2)
                self._drop_warned = True
        self._distribute(out, now)

    def _append_only(self, side, wstate, mmstate, batch, now):
        if not hasattr(side, "_append_fn"):
            filters = side.filters

            def fn(w, mm, b, n):
                scope = Scope()
                scope.add_frame(side.ref, b.cols, b.ts, b.valid, default=True)
                scope.extras["now"] = n
                mask = b.valid
                for f in filters:
                    mask = mask & f(scope)
                b = dataclasses.replace(b, valid=mask)
                w2, _chunk = side.window.step(w, b, n)
                if side._mm_buckets is not None:
                    live = mask & (b.types == EventType.CURRENT)
                    hashes = _hash_exprs(side._mm_build_keys, scope)
                    mm = multimap_append(mm, hashes, live, w.appended)
                return w2, mm

            side._append_fn = jax.jit(fn)
        return side._append_fn(wstate, mmstate, batch, jnp.int64(now))

    def _selector_state(self):
        return self.state[4]

    def _distribute(self, out: EventBatch, now: int) -> None:
        from .query_runtime import QueryRuntime
        QueryRuntime._distribute(self, out, now)

    def _select_event_type(self, out, etype):
        from .query_runtime import QueryRuntime
        return QueryRuntime._select_event_type(out, etype)

    def add_callback(self, cb: QueryCallback) -> None:
        self.callbacks.append(cb)


class _JoinSideReceiver(Receiver):
    def __init__(self, runtime: JoinQueryRuntime, from_left: bool):
        self.runtime = runtime
        self.from_left = from_left

    def on_batch(self, batch: EventBatch, now: int) -> None:
        t0 = time.perf_counter_ns()
        self.runtime.on_side_batch(self.from_left, batch, now)
        tele = getattr(self.runtime.ctx, "telemetry", None)
        if tele is not None and tele.on:
            tele.record_query(self.runtime.name, time.perf_counter_ns() - t0)
