"""Error store — persist-and-replay for events whose processing failed.

Reference: core/util/error/handler/ — ErrorStore SPI:46, ErroneousEvent /
ErrorEntry model, ErrorStoreHelper; wired from the junction's @OnError STORE
action (StreamJunction.java:371-463) and replayed by the user via
SiddhiManager's error store accessors.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ErrorEntry:
    """Reference: core/util/error/handler/ErrorEntry.java."""

    id: int
    timestamp: int
    app_name: str
    stream_name: str
    events: list  # original (event_timestamp, row) pairs
    cause: str
    #: origin of the entry — "error" (processing/@OnError), "sink"
    #: (dead-letter), "breaker" (circuit-breaker divert), "overflow"
    #: (bounded-ingress fault policy), "late" (@app:eventTime rows behind
    #: the watermark), "unowned" (front-tier frames whose shard has no
    #: live owner host) — so operators replay selectively
    kind: str = "error"


class ErrorStore:
    """SPI (reference: ErrorStore.java:46)."""

    def save(self, app_name: str, stream_name: str, events: list,
             cause: str, kind: str = "error") -> ErrorEntry:
        """`events` is a list of (event_timestamp, row) pairs."""
        raise NotImplementedError

    def load(self, app_name: str, stream_name: Optional[str] = None,
             kind: Optional[str] = None) -> list:
        raise NotImplementedError

    def discard(self, entry_id: int) -> None:
        raise NotImplementedError

    def replay(self, entry: ErrorEntry, app_runtime) -> None:
        """Re-send a stored entry's rows into its original stream — with
        their ORIGINAL timestamps, so windows/aggregations bucket them
        correctly — and drop it (reference: replay via
        ReplayableTableRecord). All rows go in ONE batched staging call and
        the entry is discarded only after every row was accepted: an
        exception mid-replay leaves the whole entry in the store instead of
        half-losing it. Base-class behavior — store backends only override
        the persistence primitives above."""
        handler = app_runtime.get_input_handler(entry.stream_name)
        tss = [ts for ts, _row in entry.events]
        rows = [row for _ts, row in entry.events]
        if entry.kind == "late":
            # late-arrival side output: re-admission must SKIP the lateness
            # check (the rows are behind the watermark by definition — a
            # plain resend would divert them right back) and must flush
            # inside the bypass window, because the gate classifies at
            # flush time, not at send time. Downstream windows fold the
            # rows in under their max-seen watermark: the resulting
            # emissions are the corrections (upsert semantics).
            j = handler.junction._resolve_redirect()
            gate = getattr(j, "_et", None)
            if gate is not None:
                with gate.bypass():
                    handler.send_batch(rows, timestamps=tss)
                    j.flush()
                self.discard(entry.id)
                return
        handler.send_batch(rows, timestamps=tss)
        self.discard(entry.id)


class InMemoryErrorStore(ErrorStore):
    """Bounded in-memory store: `max_entries` caps host memory (an @OnError
    STORE storm must not OOM the controller) with drop-OLDEST eviction; the
    per-app eviction count surfaces as `dropped_error_entries` in
    statistics_report()."""

    def __init__(self, max_entries: int = 10_000) -> None:
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self._entries: dict[int, ErrorEntry] = {}
        self._ids = itertools.count(1)
        self.max_entries = max_entries
        #: app name -> entries evicted before the user could replay them
        self.dropped: dict[str, int] = {}

    def save(self, app_name, stream_name, events, cause,
             kind="error") -> ErrorEntry:
        entry = ErrorEntry(
            id=next(self._ids), timestamp=int(time.time() * 1000),
            app_name=app_name, stream_name=stream_name,
            events=list(events), cause=cause, kind=kind)
        self._entries[entry.id] = entry
        while len(self._entries) > self.max_entries:
            # dict preserves insertion order: the first key is the oldest
            oldest = self._entries.pop(next(iter(self._entries)))
            self.dropped[oldest.app_name] = \
                self.dropped.get(oldest.app_name, 0) + 1
        return entry

    def dropped_count(self, app_name: str) -> int:
        return self.dropped.get(app_name, 0)

    def load(self, app_name, stream_name=None, kind=None) -> list:
        return [e for e in self._entries.values()
                if e.app_name == app_name
                and (stream_name is None or e.stream_name == stream_name)
                and (kind is None or e.kind == kind)]

    def discard(self, entry_id) -> None:
        self._entries.pop(entry_id, None)
