"""Error store — persist-and-replay for events whose processing failed.

Reference: core/util/error/handler/ — ErrorStore SPI:46, ErroneousEvent /
ErrorEntry model, ErrorStoreHelper; wired from the junction's @OnError STORE
action (StreamJunction.java:371-463) and replayed by the user via
SiddhiManager's error store accessors.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ErrorEntry:
    """Reference: core/util/error/handler/ErrorEntry.java."""

    id: int
    timestamp: int
    app_name: str
    stream_name: str
    events: list  # original (event_timestamp, row) pairs
    cause: str


class ErrorStore:
    """SPI (reference: ErrorStore.java:46)."""

    def save(self, app_name: str, stream_name: str, events: list,
             cause: str) -> ErrorEntry:
        """`events` is a list of (event_timestamp, row) pairs."""
        raise NotImplementedError

    def load(self, app_name: str, stream_name: Optional[str] = None) -> list:
        raise NotImplementedError

    def discard(self, entry_id: int) -> None:
        raise NotImplementedError


class InMemoryErrorStore(ErrorStore):
    def __init__(self) -> None:
        self._entries: dict[int, ErrorEntry] = {}
        self._ids = itertools.count(1)

    def save(self, app_name, stream_name, events, cause) -> ErrorEntry:
        entry = ErrorEntry(
            id=next(self._ids), timestamp=int(time.time() * 1000),
            app_name=app_name, stream_name=stream_name,
            events=list(events), cause=cause)
        self._entries[entry.id] = entry
        return entry

    def load(self, app_name, stream_name=None) -> list:
        return [e for e in self._entries.values()
                if e.app_name == app_name
                and (stream_name is None or e.stream_name == stream_name)]

    def discard(self, entry_id) -> None:
        self._entries.pop(entry_id, None)

    def replay(self, entry: ErrorEntry, app_runtime) -> None:
        """Re-send a stored entry's rows into its original stream — with their
        ORIGINAL timestamps, so windows/aggregations bucket them correctly —
        and drop it (reference: replay via ReplayableTableRecord)."""
        handler = app_runtime.get_input_handler(entry.stream_name)
        for ts, row in entry.events:
            handler.send(row, timestamp=ts)
        self.discard(entry.id)
