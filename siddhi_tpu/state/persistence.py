"""Checkpoint / restore + persistence stores.

Reference: core/util/snapshot/SnapshotService.java:51 walks every StateHolder
under a world-stopping ThreadBarrier, serializes with ByteSerializer, and hands
bytes to a PersistenceStore (core/util/persistence/ — InMemory, FileSystem,
IncrementalFileSystem) keyed by app name + revision
(SiddhiAppRuntimeImpl.persist:686, SiddhiManager.persist:291,
restoreLastRevision:302-320).

Compatibility: a revision restores only into the SAME state layout — a
framework upgrade that changes a runtime's state pytree structure (new
counters, aggregator state redesigns) fails restore LOUDLY with
CannotRestoreStateError rather than silently misassigning leaves; durable
aggregation stores (@store duration tables) are the cross-version path.

TPU design: every runtime's state is a **pytree of device arrays** plus a few
host scalars, so a full snapshot is one `jax.device_get` per runtime — no
barrier needed (execution is single-controller synchronous; there is nothing
in flight between flushes). Revisions are `<ts>_<app>` like the reference's
`<time>_<app>` naming. Serialization is pickle over numpy arrays (the
reference uses Java serialization).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

import jax
import numpy as np

from ..errors import CannotRestoreStateError


def _to_host(pytree):
    # prestart every device->host copy, then one tree fetch: per-leaf
    # synchronous np.asarray costs a full tunnel round trip EACH
    for leaf in jax.tree_util.tree_leaves(pytree):
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # pragma: no cover — prestart is advisory
                break
    return jax.tree_util.tree_map(lambda x: np.asarray(x), pytree)


def _to_device(host_tree, like):
    """Device-put host arrays, casting to the dtypes of the template tree."""
    import jax.numpy as jnp

    # version tolerance: a state NamedTuple that gained a defaulted field
    # (e.g. PatternState.armed0_ts r4, PendingTable.origin r5) unpickles
    # from older snapshots with None in that slot — backfill every
    # None-valued field from the freshly built template of the SAME type
    # (for armed0_ts this re-arms the leading-absent rule at restore time).
    # Recurses because the NamedTuples nest (PatternState holds
    # PendingTables); mismatched types fall through to tree_map's structure
    # error, wrapped by the caller.
    def backfill(h, l):
        if isinstance(h, tuple) and hasattr(h, "_fields") \
                and type(l) is type(h):
            return h._replace(**{
                f: (getattr(l, f) if v is None
                    else backfill(v, getattr(l, f)))
                for f, v in zip(h._fields, h)})
        if isinstance(h, tuple) and type(l) is tuple is type(h) \
                and len(h) == len(l):
            return tuple(backfill(a, b) for a, b in zip(h, l))
        return h

    host_tree = backfill(host_tree, like)

    def put(h, l):
        arr = jnp.asarray(h)
        if hasattr(l, "dtype") and arr.dtype != l.dtype:
            arr = arr.astype(l.dtype)
        return arr

    return jax.tree_util.tree_map(put, host_tree, like)


class PersistenceStore:
    """SPI (reference: core/util/persistence/PersistenceStore.java)."""

    def save(self, app_name: str, revision: str, snapshot: bytes) -> None:
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    """Reference: InMemoryPersistenceStore.java."""

    def __init__(self) -> None:
        self._store: dict[str, dict[str, bytes]] = {}

    def save(self, app_name, revision, snapshot) -> None:
        self._store.setdefault(app_name, {})[revision] = snapshot

    def load(self, app_name, revision):
        return self._store.get(app_name, {}).get(revision)

    def get_last_revision(self, app_name):
        revs = self._store.get(app_name)
        if not revs:
            return None
        return max(revs)  # revisions sort by leading timestamp

    def clear_all_revisions(self, app_name) -> None:
        self._store.pop(app_name, None)


class FileSystemPersistenceStore(PersistenceStore):
    """Reference: FileSystemPersistenceStore.java:33 (save:40, load:89) —
    one file per revision under <base>/<app>/<revision>."""

    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir

    def _dir(self, app_name: str) -> str:
        return os.path.join(self.base_dir, app_name)

    def save(self, app_name, revision, snapshot) -> None:
        d = self._dir(app_name)
        os.makedirs(d, exist_ok=True)
        # crash-consistent: fsync the tmp BEFORE the rename (otherwise the
        # rename can land while the data is still page-cache-only and a
        # power cut leaves a whole-looking but torn revision), then fsync
        # the directory so the rename itself is durable. get_last_revision
        # skips dot-prefixed files, so an abandoned tmp is never picked.
        tmp = os.path.join(d, f".{revision}.tmp")
        with open(tmp, "wb") as f:
            f.write(snapshot)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(d, revision))
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover — platform without dir fsync
            pass

    def load(self, app_name, revision):
        path = os.path.join(self._dir(app_name), revision)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def get_last_revision(self, app_name):
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return None
        revs = [f for f in os.listdir(d) if not f.startswith(".")]
        return max(revs) if revs else None

    def clear_all_revisions(self, app_name) -> None:
        d = self._dir(app_name)
        if os.path.isdir(d):
            for f in os.listdir(d):
                os.remove(os.path.join(d, f))


class IncrementalFileSystemPersistenceStore(FileSystemPersistenceStore):
    """Delta persistence (reference: IncrementalFileSystemPersistenceStore.java:37
    + the incremental-snapshot protocol of SnapshotService.java:189).

    The reference collects per-element operation change-logs; here the unit of
    change is the device ARRAY: a revision stores only the pytree leaves whose
    content hash changed since the previous revision. Loading walks back to the
    nearest full snapshot and replays deltas forward. Periodically (every
    `full_every` saves) a full snapshot re-bases the chain so restore cost
    stays bounded. Directory layout / revision naming / atomic writes come
    from FileSystemPersistenceStore; chain order is the lexicographic revision
    order (revisions are strictly-increasing timestamps — SiddhiAppRuntime
    guarantees uniqueness)."""

    def __init__(self, base_dir: str, full_every: int = 16) -> None:
        super().__init__(base_dir)
        self.full_every = full_every
        self._last_hashes: dict[str, dict] = {}  # app -> {path: digest}
        self._saves: dict[str, int] = {}

    @staticmethod
    def _flatten(tree):
        """snapshot → ({path: leaf}, canonical path order, treedef)."""
        with_path, structure = jax.tree_util.tree_flatten_with_path(tree)
        keystr = jax.tree_util.keystr
        flat = {keystr(p): leaf for p, leaf in with_path}
        order = [keystr(p) for p, _ in with_path]
        return flat, order, structure

    @staticmethod
    def _digest(leaf) -> str:
        import hashlib
        h = hashlib.blake2b(digest_size=12)
        if isinstance(leaf, np.ndarray):
            h.update(leaf.tobytes())
            h.update(str(leaf.dtype).encode())
            h.update(str(leaf.shape).encode())
        else:
            h.update(repr(leaf).encode())
        return h.hexdigest()

    def save(self, app_name, revision, snapshot) -> None:
        snap = pickle.loads(snapshot)
        flat, order, structure = self._flatten(snap)
        hashes = {k: self._digest(v) for k, v in flat.items()}
        prev = self._last_hashes.get(app_name)
        n = self._saves.get(app_name, 0)
        full = prev is None or n % self.full_every == 0
        if full:
            payload = {"kind": "full", "leaves": flat}
        else:
            changed = {k: v for k, v in flat.items()
                       if hashes.get(k) != prev.get(k)}
            dropped = [k for k in prev if k not in hashes]
            payload = {"kind": "delta", "leaves": changed, "dropped": dropped}
        # shape + canonical leaf order ride every revision so restore can
        # rebuild the nested snapshot
        payload["structure"] = structure
        payload["order"] = order
        super().save(app_name, revision,
                     pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL))
        self._last_hashes[app_name] = hashes
        self._saves[app_name] = n + 1

    def _read_payload(self, app_name: str, rev: str) -> dict:
        with open(os.path.join(self._dir(app_name), rev), "rb") as f:
            payload = pickle.load(f)
        if not isinstance(payload, dict) or "kind" not in payload:
            raise CannotRestoreStateError(
                f"revision {rev!r} is not an incremental revision (was it "
                "written by a different persistence store?)")
        return payload

    def load(self, app_name, revision):
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return None
        revs = sorted(f for f in os.listdir(d) if not f.startswith("."))
        if revision not in revs:
            return None
        # walk back from `revision` to the nearest full snapshot
        chain = []
        for r in reversed(revs[: revs.index(revision) + 1]):
            payload = self._read_payload(app_name, r)
            chain.append(payload)
            if payload["kind"] == "full":
                break
        if not chain or chain[-1]["kind"] != "full":
            raise CannotRestoreStateError(
                f"no full base found for revision {revision!r} "
                "(older revisions pruned?)")
        leaves: dict = {}
        for payload in reversed(chain):  # base first, then deltas
            for k in payload.get("dropped", ()):
                leaves.pop(k, None)
            leaves.update(payload["leaves"])
        target = chain[0]  # the requested revision carries shape + order
        snap = jax.tree_util.tree_unflatten(
            target["structure"], [leaves[k] for k in target["order"]])
        return pickle.dumps(snap, protocol=pickle.HIGHEST_PROTOCOL)

    def clear_all_revisions(self, app_name) -> None:
        super().clear_all_revisions(app_name)
        self._last_hashes.pop(app_name, None)
        self._saves.pop(app_name, None)


class SnapshotService:
    """Collects/restores all stateful elements of one app runtime
    (reference: SnapshotService.java fullSnapshot:90 / restore:333)."""

    def __init__(self, app_runtime) -> None:
        self.rt = app_runtime
        #: device-delta fetch memo: section key -> (state object, host tree).
        #: Every jitted step REPLACES its state pytree (donated buffers,
        #: functional updates), so object identity is a precise change
        #: detector: `state is cached` means not one batch touched this
        #: runtime since the last snapshot — reuse the cached host copy and
        #: skip the device readback entirely. An idle app persists with
        #: ZERO device->host transfers (the reference's change-log
        #: equivalent, SnapshotableStreamEventQueue.java:44-47, at runtime
        #: granularity).
        self._memo: dict = {}

    def full_snapshot(self) -> bytes:
        rt = self.rt
        rt.flush()  # drain staged rows so the snapshot is a clean cut
        # entries untouched by THIS pass (e.g. @purge-removed partition
        # instances) drop with the memo swap — no per-key host leak
        new_memo: dict = {}

        def fetch(key: str, state):
            hit = self._memo.get(key)
            if hit is not None and hit[0] is state:
                new_memo[key] = hit
                return hit[1]
            host = _to_host(state)
            new_memo[key] = (state, host)
            return host

        snap = {
            "app": rt.app.name,
            "fingerprint": self._fingerprint(),
            "queries": {name: fetch(f"q:{name}", qr.state)
                        for name, qr in rt.query_runtimes.items()
                        if not getattr(qr, "_partitioned", False)},
            # record (@store) tables are external authorities: their rows
            # live in the store, not in device state — skip them (the cache
            # rebuilds from the store/policy on use)
            "tables": {tid: fetch(f"t:{tid}", t.state)
                       for tid, t in rt.tables.items()
                       if not hasattr(t, "store")},
            "windows": {wid: fetch(f"w:{wid}", w.state)
                        for wid, w in getattr(rt, "windows", {}).items()},
            "aggregations": {aid: fetch(f"a:{aid}", a.state)
                             for aid, a in getattr(rt, "aggregations", {}).items()},
            "partitions": {pname: p.snapshot_states(fetch=fetch,
                                                    prefix=f"p:{pname}:")
                           for pname, p in getattr(rt, "partitions", {}).items()},
            "strings": rt.ctx.global_strings.snapshot(),
            "last_event_ts": rt.ctx.timestamp_generator._last_event_ts,
        }
        self._memo = new_memo
        return pickle.dumps(snap, protocol=pickle.HIGHEST_PROTOCOL)

    def _fingerprint(self) -> Optional[str]:
        """App-structure fingerprint stamped into every revision (memoized —
        the app object never changes after creation). Best-effort: a
        lowering failure must never block persist."""
        fp = getattr(self, "_fp_memo", False)
        if fp is False:
            try:
                from ..analysis.plan import plan_fingerprint
                fp = plan_fingerprint(self.rt.app)
            except Exception:  # pragma: no cover — fingerprint is advisory
                fp = None
            self._fp_memo = fp
        return fp

    def restore(self, blob: bytes, *,
                elements: Optional[dict[str, set[str]]] = None) -> None:
        """Restore a snapshot. `elements` (section name -> element-name set)
        limits which stateful sections restore — the state-migratable
        upgrade path feeds it UpgradeDiff.restore_elements(); None restores
        everything (and then a fingerprint mismatch is refused)."""
        rt = self.rt
        try:
            snap = pickle.loads(blob)
        except Exception as e:  # noqa: BLE001
            raise CannotRestoreStateError(str(e)) from e
        if snap.get("app") != rt.app.name:
            raise CannotRestoreStateError(
                f"snapshot belongs to app {snap.get('app')!r}, "
                f"not {rt.app.name!r}")
        # structural gate: refuse a full restore of a snapshot taken under a
        # different app structure instead of corrupting state leaf-by-leaf.
        # Pre-fingerprint snapshots (no stamp) stay loadable; element-mapped
        # restores skip the gate — the caller already diffed the plans.
        snap_fp = snap.get("fingerprint")
        if elements is None and snap_fp is not None:
            own_fp = self._fingerprint()
            if own_fp is not None and snap_fp != own_fp:
                raise CannotRestoreStateError(
                    f"snapshot fingerprint {snap_fp} does not match the "
                    f"current app structure {own_fp} for {rt.app.name!r} — "
                    "the app definition changed since this revision was "
                    "taken; use the upgrade path (element-mapped restore) "
                    "or clear old revisions")

        def wanted(section: str, name: str) -> bool:
            return elements is None or name in elements.get(section, ())

        try:
            for name, qr in rt.query_runtimes.items():
                if name in snap["queries"] and wanted("queries", name) \
                        and not getattr(qr, "_partitioned", False):
                    qr.state = _to_device(snap["queries"][name], qr.state)
            for tid, t in rt.tables.items():
                if tid in snap["tables"] and wanted("tables", tid) \
                        and not hasattr(t, "store"):
                    t.state = _to_device(snap["tables"][tid], t.state)
            for wid, w in getattr(rt, "windows", {}).items():
                if wid in snap.get("windows", {}) and wanted("windows", wid):
                    w.state = _to_device(snap["windows"][wid], w.state)
            for aid, a in getattr(rt, "aggregations", {}).items():
                if aid in snap.get("aggregations", {}) \
                        and wanted("aggregations", aid):
                    a.state = _to_device(snap["aggregations"][aid], a.state)
            for pname, p in getattr(rt, "partitions", {}).items():
                if pname in snap.get("partitions", {}) \
                        and wanted("partitions", pname):
                    p.restore_states(snap["partitions"][pname])
        except (ValueError, KeyError) as e:
            raise CannotRestoreStateError(
                f"snapshot structure mismatch (app definition changed?): {e}"
            ) from e
        rt.ctx.global_strings.restore(snap["strings"])
        if snap.get("last_event_ts") is not None:
            rt.ctx.timestamp_generator._last_event_ts = snap["last_event_ts"]
