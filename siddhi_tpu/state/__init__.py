"""State plane: checkpoint/restore stores, the error store, and the
write-ahead event journal."""

from .error_store import ErrorEntry, ErrorStore, InMemoryErrorStore  # noqa: F401
from .persistence import (  # noqa: F401
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
    IncrementalFileSystemPersistenceStore,
    PersistenceStore,
    SnapshotService,
)
from .wal import WriteAheadLog  # noqa: F401
