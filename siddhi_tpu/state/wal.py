"""Write-ahead event journal — crash-consistent ingress durability.

Reference analogue: the Java engine has no ingress journal (durability there
comes from replayable transports like Kafka); the TPU build is fed through
`InputHandler`, so a `kill -9` between persists would lose every event since
the last snapshot. The WAL closes that hole: every row accepted by an ingress
junction is appended to a revision-tagged segment BEFORE it enters the staging
buffers, and `SiddhiAppRuntime.recover()` = restore_last_revision() + replay
of the surviving segments with the events' ORIGINAL timestamps — at-least-once
restart semantics (exactly-once for the common crash points: `persist()`
flushes all staged rows into the snapshot and then rotates the journal, so the
replayed set is exactly the post-snapshot suffix unless the crash lands inside
persist() itself).

Format: one append-only segment file at a time, named `<seq>_<tag>.wal` where
`seq` is a monotonically increasing integer and `tag` is the persistence
revision the segment FOLLOWS ("0" before any persist). Each record is

    <u32 payload_len> <u32 crc32(payload)> <payload = pickle>

with payload one of
    ("rows", stream_id, [ts, ...], [row_tuple, ...])
    ("cols", stream_id, [ts, ...], {attr: numpy_host_array})
    (other,  stream_id, [ts, ...], data)   — generic records via
        append_record(): non-event journal marks (e.g. the shard host's
        per-frame "mark" seq records, the front tier's spooled frames).
        replay() skips kinds it does not understand, so a journal carrying
        marks stays replayable by any engine version

A torn tail (crash mid-append) fails the length/CRC check and cleanly ends
replay at the last whole record; re-opening a torn segment truncates it back
to its last whole record before appending. Columnar records journal the
ORIGINAL (pre-interning) column values: dictionary string codes are
process-local and would not survive a restart.

Durability knob: `fsync=True` (default) fsyncs after every append call (one
call may carry a whole batch — `send_batch`/`send_columns` amortize it);
`fsync=False` (or SIDDHI_WAL_FSYNC=0) leaves records in the OS page cache,
which still survives `kill -9` but not power loss.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import zlib
from typing import Optional

from ..util.locks import named_rlock, note_blocking

log = logging.getLogger("siddhi_tpu")

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)


class WriteAheadLog:
    """One app's ingress journal under `<base_dir>/<app_name>/`."""

    def __init__(self, base_dir: str, app_name: str,
                 fsync: Optional[bool] = None) -> None:
        self.dir = os.path.join(base_dir, app_name)
        os.makedirs(self.dir, exist_ok=True)
        if fsync is None:
            fsync = os.environ.get("SIDDHI_WAL_FSYNC", "1") != "0"
        self.fsync = fsync
        # one lock serializes appends/rotation; producers on arbitrary
        # threads (async sources, user threads) share the journal
        self._lock = named_rlock("wal.journal")
        #: lifetime records appended / events journaled (statistics_report)
        self.appended_records = 0
        self.appended_events = 0
        self.replayed_events = 0
        self.skipped_events = 0
        self._file = None
        segs = self._segments()
        if segs:
            seq, tag, path = segs[-1]
            self._seq, self._tag = seq, tag
            self._resume_segment(path)
        else:
            self._seq, self._tag = 0, "0"
            self._open_segment()

    # ------------------------------------------------------------- segments

    def _segments(self) -> list:
        """[(seq, tag, path)] sorted by seq."""
        out = []
        for f in os.listdir(self.dir):
            if not f.endswith(".wal") or f.startswith("."):
                continue
            seq_s, _, tag = f[:-4].partition("_")
            try:
                out.append((int(seq_s), tag, os.path.join(self.dir, f)))
            except ValueError:
                log.warning("ignoring unrecognized WAL file %r", f)
        out.sort()
        return out

    def _path(self) -> str:
        return os.path.join(self.dir, f"{self._seq:08d}_{self._tag}.wal")

    def _open_segment(self) -> None:
        self._file = open(self._path(), "ab")

    def _resume_segment(self, path: str) -> None:
        """Re-open an existing segment for append, truncating a torn tail
        first so new records stay reachable by replay."""
        good = 0
        with open(path, "rb") as f:
            for _payload, end in self._iter_payloads(f, path):
                good = end
        self._file = open(path, "ab")
        if self._file.tell() != good:
            log.warning("WAL %s: truncating torn tail (%d -> %d bytes)",
                        path, self._file.tell(), good)
            self._file.truncate(good)
            self._file.seek(good)

    # --------------------------------------------------------------- append

    def _append(self, payload_obj) -> None:
        payload = pickle.dumps(payload_obj, protocol=pickle.HIGHEST_PROTOCOL)
        rec = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._file is None:  # closed (shutdown): drop loudly
                log.error("WAL append after close; record dropped")
                return
            self._file.write(rec)
            self._file.flush()
            if self.fsync:
                # front_tier.shard_dispatch: the router spool fsyncs under
                # the per-shard dispatch lock on purpose — spool order ==
                # arrival order is the replay-ordering contract
                note_blocking("wal.fsync",
                              allow=("wal.journal", "app.controller",
                                     "front_tier.shard_dispatch"))
                # fsync under the journal lock IS the durability
                # contract: append order == disk order
                os.fsync(self._file.fileno())  # noqa: SL404
            self.appended_records += 1

    def append_rows(self, stream_id: str, tss, rows) -> None:
        """Journal one batch of host rows (ts-parallel lists)."""
        self._append(("rows", stream_id, [int(t) for t in tss],
                      [tuple(r) for r in rows]))
        self.appended_events += len(rows)

    def append_columns(self, stream_id: str, tss, cols: dict) -> None:
        """Journal one columnar batch with its ORIGINAL column values."""
        self._append(("cols", stream_id, [int(t) for t in tss], dict(cols)))
        self.appended_events += len(tss)

    def append_record(self, kind: str, stream_id: str, tss, data) -> None:
        """Journal one generic (non-event) record — e.g. the shard host's
        per-frame `"mark"` seq records or the front tier's `"frame"` spool
        entries. Not counted as events; `replay()` skips kinds other than
        rows/cols, so marked journals stay replayable everywhere."""
        if kind in ("rows", "cols"):
            raise ValueError(
                "append_record is for generic kinds; use append_rows/"
                "append_columns for event records")
        self._append((kind, stream_id, [int(t) for t in tss], data))

    # --------------------------------------------------------------- rotate

    def rotate(self, revision: str) -> None:
        """Start a fresh segment tagged `revision` and delete the older
        segments — persist() flushed every journaled row into the snapshot
        that `revision` names, so they are subsumed. Called AFTER the store
        accepted the snapshot (save-then-rotate = at-least-once: a crash
        between the two replays a suffix twice, never loses it)."""
        with self._lock:
            old = [p for _s, _t, p in self._segments()]
            if self._file is not None:
                self._file.close()
            self._seq += 1
            self._tag = revision
            self._open_segment()
            for p in old:
                try:
                    os.remove(p)
                except OSError:  # pragma: no cover — concurrent cleanup
                    pass

    # --------------------------------------------------------------- replay

    @staticmethod
    def _iter_payloads(f, path: str):
        """Yield (payload_bytes, end_offset) for every WHOLE record; stop at
        the first torn/corrupt one."""
        pos = 0
        while True:
            head = f.read(_HEADER.size)
            if len(head) < _HEADER.size:
                if head:
                    log.warning("WAL %s: torn record header at %d; "
                                "replay stops here", path, pos)
                return
            length, crc = _HEADER.unpack(head)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                log.warning("WAL %s: torn/corrupt record at %d; "
                            "replay stops here", path, pos)
                return
            pos += _HEADER.size + length
            yield payload, pos

    def records(self) -> list:
        """All whole records across segments, in append order (for tests
        and inspection)."""
        out = []
        with self._lock:
            segs = self._segments()
            if self._file is not None:
                self._file.flush()
        for _seq, _tag, path in segs:
            with open(path, "rb") as f:
                for payload, _end in self._iter_payloads(f, path):
                    out.append(pickle.loads(payload))
        return out

    def replay(self, runtime) -> int:
        """Re-send every journaled event into `runtime` with its original
        timestamp. The journal first rotates to a fresh segment so the
        replayed sends re-journal themselves (they are state not yet covered
        by any snapshot — a crash DURING recovery must still recover); the
        consumed segments are deleted only after the replay fully succeeds.
        Streams the target runtime does not define (a tail recorded under a
        different app version) are skipped and counted, never fatal.
        Returns the number of events replayed."""
        import numpy as np

        from ..errors import DefinitionNotExistError
        with self._lock:
            old = self._segments()
            if self._file is not None:
                self._file.close()
            self._seq = (old[-1][0] if old else self._seq) + 1
            self._open_segment()
        n = 0
        unknown: set = set()
        for _seq, _tag, path in old:
            with open(path, "rb") as f:
                for payload, _end in self._iter_payloads(f, path):
                    kind, sid, tss, data = pickle.loads(payload)
                    if kind not in ("rows", "cols"):
                        continue  # generic marks are not events
                    try:
                        handler = runtime.get_input_handler(sid)
                    except DefinitionNotExistError:
                        if sid not in unknown:
                            unknown.add(sid)
                            log.warning(
                                "WAL replay: stream %r is not defined on "
                                "%s; its journaled events are skipped",
                                sid, runtime.app.name)
                        self.skipped_events += len(tss)
                        continue
                    if kind == "rows":
                        handler.send_batch(data, timestamps=tss)
                        n += len(data)
                    else:  # "cols"
                        handler.send_columns(
                            data, timestamps=np.asarray(tss, dtype=np.int64))
                        n += len(tss)
        runtime.flush()
        with self._lock:
            for _seq, _tag, path in old:
                try:
                    os.remove(path)
                except OSError:  # pragma: no cover
                    pass
        self.replayed_events += n
        return n

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if self.fsync:
                    note_blocking("wal.fsync",
                                  allow=("wal.journal", "app.controller",
                                         "front_tier.shard_dispatch"))
                    os.fsync(self._file.fileno())  # noqa: SL404 — close() drains
                self._file.close()
                self._file = None


def list_segments(base_dir: str, app_name: Optional[str] = None) -> list:
    """[(seq, tag, path)] for an app's WAL directory, WITHOUT opening the
    journal for append. `base_dir` is the wal.dir root when `app_name` is
    given, else directly the segment directory."""
    d = os.path.join(base_dir, app_name) if app_name else base_dir
    out = []
    if not os.path.isdir(d):
        return out
    for f in os.listdir(d):
        if not f.endswith(".wal") or f.startswith("."):
            continue
        seq_s, _, tag = f[:-4].partition("_")
        try:
            out.append((int(seq_s), tag, os.path.join(d, f)))
        except ValueError:
            log.warning("ignoring unrecognized WAL file %r", f)
    out.sort()
    return out


def read_records(base_dir: str, app_name: Optional[str] = None):
    """Yield every whole journal record ``(kind, stream_id, tss, data)`` in
    append order, read-only (no truncation, no rotation, no append handle):
    the historical-replay path reads a LIVE app's journal without disturbing
    it, or a dead app's journal without adopting it."""
    for _seq, _tag, path in list_segments(base_dir, app_name):
        with open(path, "rb") as f:
            for payload, _end in WriteAheadLog._iter_payloads(f, path):
                yield pickle.loads(payload)
