"""SL4xx — concurrency lint for the engine's OWN Python source.

The SL1xx/SL2xx/SL3xx catalogs certify *user queries* before execution;
this module points the same machinery at the runtime itself, so the
locking discipline util/locks.py enforces dynamically is also checked
statically on every commit (`python -m siddhi_tpu.lint --self`).

Rules:

  SL401  ERROR  raw threading.Lock()/RLock()/Condition() constructed
                outside the util/locks.py factory — the lock is invisible
                to lockdep and has no catalog name
  SL402  WARN   instance attribute assigned from >= 2 thread entry points
                (methods used as Thread(target=...) plus the public
                caller-thread API) with no common guarding lock
  SL403  ERROR  two named locks nested in inconsistent order in different
                places (the static shadow of lockdep's cycle detection)
  SL404  WARN   blocking call (time.sleep, os.fsync, socket ops, bare
                .join(), queue .put()) lexically under a held lock
  SL405  WARN   mutable module-level container mutated inside a function
                with no lock held

Suppression uses source comments (these are Python files, not SiddhiQL,
so `@suppress.lint` annotations don't exist): a trailing
``# noqa: SL40x`` on the offending line drops that finding, matching
the per-rule suppression contract of the SiddhiQL CLI.

Everything reports through the shared Diagnostic/LintReport shapes, so
JSON output, severity filters, and exit codes are identical to the
SiddhiQL linter's.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .diagnostics import Diagnostic, LintReport, Severity

#: modules whose job is constructing raw primitives / spawning threads
_FACTORY_MODULES = ("util/locks.py",)

_RAW_PRIMITIVES = ("Lock", "RLock", "Condition")
_FACTORY_FUNCS = ("named_lock", "named_rlock", "named_condition")

#: callables treated as blocking for SL404 (name or dotted suffix)
_BLOCKING_NAMES = {"time.sleep", "os.fsync", "select.select"}
_BLOCKING_METHODS = {"recv", "accept", "connect", "sendall", "put"}

_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Z0-9, ]+)")


def _noqa_rules(lines: list, lineno: int) -> set:
    """Rule ids suppressed by a `# noqa: SL4xx` comment on this line."""
    if not (1 <= lineno <= len(lines)):
        return set()
    m = _NOQA_RE.search(lines[lineno - 1])
    if not m:
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('self.ctx.lock' ...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _lock_literal(call: ast.Call) -> Optional[str]:
    """The name argument when `call` is named_lock/rlock/condition(...)."""
    fn = call.func
    fname = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    if fname not in _FACTORY_FUNCS:
        return None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


class _ModuleFacts(ast.NodeVisitor):
    """Single pass over one module collecting everything the rules need."""

    def __init__(self, path: str, tree: ast.Module) -> None:
        self.path = path
        self.raw_locks: list = []          # (lineno, col, primitive)
        self.lock_keys: dict = {}          # attr/var key -> lock name
        self.nestings: list = []           # (outer, inner, lineno)
        self.blocking: list = []           # (lineno, col, desc, [held keys])
        self.classes: list = []            # ast.ClassDef nodes
        self.globals_mut: dict = {}        # name -> lineno (module level)
        self.global_writes: list = []      # (name, lineno, held?)
        self._with_stack: list = []        # lock keys currently entered
        self._threading_aliases = {"threading"}
        self.visit(tree)

    # ------------------------------------------------------------ helpers

    def _lock_key(self, node: ast.AST) -> Optional[str]:
        """Canonical key for a lock-valued expression: the final attribute
        or variable name ('_submit_lock', 'controller_lock', ...)."""
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    # ------------------------------------------------------------ visitors

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "threading":
                self._threading_aliases.add(alias.asname or "threading")
        self.generic_visit(node)

    def visit_Module(self, node: ast.Module) -> None:
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                if self._is_mutable_literal(stmt.value):
                    self.globals_mut[stmt.targets[0].id] = stmt.lineno
        self.generic_visit(node)

    @staticmethod
    def _is_mutable_literal(node: ast.AST) -> bool:
        if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            return name in ("dict", "list", "set", "deque", "defaultdict",
                            "OrderedDict")
        return False

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.classes.append(node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        # SL401: raw primitive construction
        if isinstance(fn, ast.Attribute) and fn.attr in _RAW_PRIMITIVES \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id in self._threading_aliases:
            self.raw_locks.append((node.lineno, node.col_offset, fn.attr))
        # blocking-call detection for SL404 (only meaningful under a lock)
        if self._with_stack:
            desc = self._blocking_desc(node)
            if desc:
                self.blocking.append((node.lineno, node.col_offset, desc,
                                      list(self._with_stack)))
        self.generic_visit(node)

    def _blocking_desc(self, node: ast.Call) -> Optional[str]:
        dotted = _dotted(node.func)
        for b in _BLOCKING_NAMES:
            if dotted == b or dotted.endswith("." + b):
                return b
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth == "join" and not node.args:
                # zero-arg .join() is a thread/queue join; str.join always
                # carries its iterable positionally
                return ".join()"
            if meth in _BLOCKING_METHODS and meth != "put":
                return f".{meth}()"
            if meth == "put":
                recv = _dotted(node.func.value)
                # only queue-ish receivers: dicts have no .put
                if recv.rsplit(".", 1)[-1].lstrip("_").startswith("q"):
                    return ".put()"
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            lock_name = _lock_literal(node.value)
            if lock_name is not None:
                for tgt in node.targets:
                    key = self._lock_key(tgt)
                    if key:
                        self.lock_keys[key] = lock_name
        if self._with_stack:
            for name, line in self._global_targets(node):
                self.global_writes.append((name, line, True))
        else:
            for name, line in self._global_targets(node):
                self.global_writes.append((name, line, False))
        self.generic_visit(node)

    def _global_targets(self, node: ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                base = tgt.value
                if isinstance(base, ast.Name) \
                        and base.id in self.globals_mut:
                    yield base.id, node.lineno

    def visit_With(self, node: ast.With) -> None:
        keys = []
        for item in node.items:
            key = self._lock_key(item.context_expr)
            # treat anything lock-ish as a guard: named keys, *lock*, *cv*
            if key and (key in self.lock_keys or "lock" in key.lower()
                        or key.lstrip("_").startswith("cv")
                        or key.lstrip("_").endswith("cv")):
                keys.append(key)
        for key in keys:
            for outer in self._with_stack:
                if outer != key:
                    self.nestings.append((outer, key, node.lineno))
        self._with_stack.extend(keys)
        self.generic_visit(node)
        if keys:
            del self._with_stack[-len(keys):]


def _class_entry_points(cls: ast.ClassDef) -> tuple:
    """(entry_method_names, methods) — entry points are Thread targets
    plus every public method (the caller's thread enters there)."""
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    entries = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if fname != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute):
                    if isinstance(kw.value.value, ast.Name) \
                            and kw.value.value.id == "self":
                        entries.add(kw.value.attr)
    return entries, methods


def _method_attr_stores(meth: ast.AST, lock_keys: dict) -> dict:
    """attr -> set of guard keys for each `self.attr = ...` store in the
    method ('' marks an unguarded store)."""
    stores: dict = {}

    def walk(node, guards):
        if isinstance(node, ast.With):
            keys = []
            for item in node.items:
                if isinstance(item.context_expr, (ast.Attribute, ast.Name)):
                    k = (item.context_expr.attr
                         if isinstance(item.context_expr, ast.Attribute)
                         else item.context_expr.id)
                    if k in lock_keys or "lock" in k.lower() \
                            or k.lstrip("_").startswith("cv") \
                            or k.lstrip("_").endswith("cv"):
                        keys.append(k)
            guards = guards | set(keys)
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign,)):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                cell = stores.setdefault(tgt.attr, set())
                cell.update(guards or {""})
        for child in ast.iter_child_nodes(node):
            walk(child, guards)

    walk(meth, frozenset())
    return stores


def lint_python_source(text: str, name: str = "<module>",
                       report: Optional[LintReport] = None,
                       shared_nestings: Optional[list] = None
                       ) -> LintReport:
    """Run every SL40x rule over one Python source file. When
    ``shared_nestings`` is given, SL403 pairs are accumulated there for a
    later cross-module pass instead of being judged per-file."""
    if report is None:
        report = LintReport(app_name=name)
    lines = text.split("\n")

    def emit(rule: str, sev: Severity, msg: str, lineno: int,
             col: int = 0) -> None:
        if rule in _noqa_rules(lines, lineno):
            return
        report.add(Diagnostic(rule, sev, msg, element=name,
                              loc=(lineno, col)))

    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        emit("SL000", Severity.ERROR, f"python parse error: {e.msg}",
             e.lineno or 1, e.offset or 0)
        return report

    facts = _ModuleFacts(name, tree)

    # SL401 — raw primitives outside the factory
    if not any(name.endswith(m) for m in _FACTORY_MODULES):
        for lineno, col, prim in facts.raw_locks:
            emit("SL401", Severity.ERROR,
                 f"raw threading.{prim}() constructed outside "
                 f"util/locks.py — use named_lock()/named_rlock()/"
                 f"named_condition() so lockdep can see it", lineno, col)

    # SL402 — shared attribute with no common guard
    for cls in facts.classes:
        entries, methods = _class_entry_points(cls)
        if not entries:
            continue
        per_attr: dict = {}
        for mname, meth in methods.items():
            if mname == "__init__":
                continue
            for attr, guards in _method_attr_stores(
                    meth, facts.lock_keys).items():
                per_attr.setdefault(attr, {})[mname] = guards
        for attr, writers in per_attr.items():
            if len(writers) < 2:
                continue
            if not any(m in entries for m in writers):
                continue
            common = None
            for guards in writers.values():
                g = {x for x in guards if x}
                common = g if common is None else (common & g)
            if common:
                continue
            lineno = cls.lineno
            for meth in methods.values():
                if meth.name in writers:
                    lineno = meth.lineno
                    break
            emit("SL402", Severity.WARN,
                 f"attribute self.{attr} is assigned from "
                 f"{len(writers)} methods of {cls.name} including thread "
                 f"entry point(s) {sorted(set(writers) & entries)} with no "
                 f"common guarding lock", lineno)

    # SL403 — inconsistent nesting (cross-module when shared_nestings)
    resolved = []
    for outer, inner, lineno in facts.nestings:
        a = facts.lock_keys.get(outer, outer)
        b = facts.lock_keys.get(inner, inner)
        if a != b:
            resolved.append((a, b, name, lineno))
    if shared_nestings is not None:
        shared_nestings.extend(resolved)
    else:
        _judge_nestings(resolved, report, lines)

    # SL404 — blocking call under a held lock
    for lineno, col, desc, held in facts.blocking:
        held_names = [facts.lock_keys.get(k, k) for k in held]
        emit("SL404", Severity.WARN,
             f"blocking call {desc} while holding lock(s) "
             f"{held_names}", lineno, col)

    # SL405 — module-level mutable state written without a lock
    seen = set()
    for gname, lineno, guarded in facts.global_writes:
        if guarded or (gname, lineno) in seen:
            continue
        seen.add((gname, lineno))
        emit("SL405", Severity.WARN,
             f"module-level mutable {gname!r} (defined line "
             f"{facts.globals_mut[gname]}) written without a lock held",
             lineno)

    return report


def _judge_nestings(nestings: list, report: LintReport,
                    lines_by_file: Optional[dict] = None) -> None:
    """SL403: flag (A,B) pairs that also occur as (B,A) somewhere."""
    by_pair: dict = {}
    for a, b, fname, lineno in nestings:
        by_pair.setdefault((a, b), []).append((fname, lineno))
    flagged = set()
    for (a, b), sites in by_pair.items():
        if (b, a) not in by_pair or (b, a) in flagged or (a, b) in flagged:
            continue
        flagged.add((a, b))
        flagged.add((b, a))
        rev = by_pair[(b, a)]
        for fname, lineno in sites:
            report.add(Diagnostic(
                "SL403", Severity.ERROR,
                f"inconsistent lock order: {a!r} -> {b!r} here but "
                f"{b!r} -> {a!r} at {rev[0][0]}:{rev[0][1]} — a thread in "
                f"each order can deadlock", element=fname,
                loc=(lineno, 0)))


def package_root() -> Path:
    return Path(__file__).resolve().parent.parent


def lint_package(root: Optional[Path] = None) -> LintReport:
    """Run the SL40x catalog over every module of the installed package
    (what `python -m siddhi_tpu.lint --self` and the CI gate execute)."""
    root = Path(root) if root is not None else package_root()
    report = LintReport(app_name=f"self:{root.name}")
    nestings: list = []
    for path in sorted(root.rglob("*.py")):
        if "_native_build" in path.parts:
            continue
        rel = path.relative_to(root.parent).as_posix()
        try:
            text = path.read_text()
        except OSError as e:  # pragma: no cover — unreadable tree
            report.add(Diagnostic("SL000", Severity.ERROR,
                                  f"cannot read: {e}", element=rel))
            continue
        lint_python_source(text, name=rel, report=report,
                           shared_nestings=nestings)
    # cross-module SL403 judgement over the union of nesting pairs,
    # honouring per-line noqa comments at each flagged site
    sub = LintReport(app_name=report.app_name)
    _judge_nestings(nestings, sub)
    for d in sub.diagnostics:
        if d.element and d.loc:
            try:
                text = (root.parent / d.element).read_text()
                if "SL403" in _noqa_rules(text.split("\n"), d.loc[0]):
                    continue
            except OSError:  # pragma: no cover
                pass
        report.add(d)
    return report
