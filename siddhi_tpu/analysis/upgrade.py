"""Upgrade compatibility analysis (SL3xx: plan-graph diff rules).

`diff_apps(old, new)` compares the structural fingerprints of two parsed
SiddhiApps (analysis/plan.py `element_fingerprints`) and classifies the
upgrade:

- **compatible** — every stateful element of v1 survives unchanged in v2
  (v2 may add elements); the whole v1 snapshot restores into v2.
- **state-migratable** — some stateful elements changed or disappeared;
  the unchanged ones migrate, the rest start empty.  The upgrade is still
  safe (no corruption) but loses state for the changed elements, so
  core/upgrade.py requires ``force=True`` to take it.
- **incompatible** — a change that would corrupt replayed state: the app
  was renamed, or a stream consumed by queries changed its schema (the WAL
  tail journals rows in the v1 schema; replaying them into a different
  column layout silently mis-assigns attributes).

The per-rule findings land in a LintReport exactly like the SL1xx catalog
so the REST surface and CLI render them identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..query_api import SiddhiApp
from .diagnostics import Diagnostic, LintReport, Severity
from .plan import element_fingerprints, plan_fingerprint

#: element-key prefix → snapshot section that holds its state
STATEFUL_SECTIONS = {
    "query": "queries",
    "table": "tables",
    "window": "windows",
    "aggregation": "aggregations",
    "partition": "partitions",
}

#: (rule_id, severity, one-line description) — docs/FAULT_TOLERANCE.md
#: mirrors this table
UPGRADE_RULES: list[tuple[str, Severity, str]] = [
    ("SL301", Severity.ERROR,
     "app rename: snapshots and WAL segments are keyed by app name"),
    ("SL302", Severity.ERROR,
     "input stream schema changed: WAL tail replay would mis-assign columns"),
    ("SL303", Severity.WARN,
     "stateful element changed: its state restarts empty after upgrade"),
    ("SL304", Severity.WARN,
     "stateful element removed: its state is dropped"),
    ("SL305", Severity.INFO,
     "element added: starts empty"),
]


@dataclass
class UpgradeDiff:
    """Outcome of diffing v1 against v2."""

    old_fingerprint: str
    new_fingerprint: str
    classification: str  # compatible | state-migratable | incompatible
    #: element keys (``query:<name>``, ``table:<id>``, ...) whose state
    #: carries over 1:1
    migratable: list[str] = field(default_factory=list)
    changed: list[str] = field(default_factory=list)
    removed: list[str] = field(default_factory=list)
    added: list[str] = field(default_factory=list)
    report: LintReport = field(default_factory=LintReport)

    @property
    def is_incompatible(self) -> bool:
        return self.classification == "incompatible"

    def restore_elements(self) -> dict[str, set[str]]:
        """Snapshot-section → element-name filter for the migratable set
        (feeds SnapshotService.restore(elements=...))."""
        out: dict[str, set[str]] = {}
        for key in self.migratable:
            kind, _, name = key.partition(":")
            section = STATEFUL_SECTIONS.get(kind)
            if section:
                out.setdefault(section, set()).add(name)
        return out

    def to_dict(self) -> dict:
        return {
            "classification": self.classification,
            "old_fingerprint": self.old_fingerprint,
            "new_fingerprint": self.new_fingerprint,
            "migratable": sorted(self.migratable),
            "changed": sorted(self.changed),
            "removed": sorted(self.removed),
            "added": sorted(self.added),
            "diagnostics": [d.to_dict() for d in self.report.sorted()],
        }


def _stream_schemas(app: SiddhiApp) -> dict[str, tuple]:
    return {
        sid: tuple((a.name, a.type.value) for a in d.attributes)
        for sid, d in app.stream_definitions.items()
    }


def diff_apps(old_app: SiddhiApp, new_app: SiddhiApp) -> UpgradeDiff:
    old_fps = element_fingerprints(old_app)
    new_fps = element_fingerprints(new_app)
    diff = UpgradeDiff(
        old_fingerprint=plan_fingerprint(old_app),
        new_fingerprint=plan_fingerprint(new_app),
        classification="compatible",
        report=LintReport(app_name=new_app.name),
    )
    rep = diff.report

    if old_app.name != new_app.name:
        rep.add(Diagnostic(
            "SL301", Severity.ERROR,
            f"app renamed {old_app.name!r} -> {new_app.name!r}: snapshots "
            f"and WAL segments are keyed by app name",
            element=new_app.name))

    # streams consumed by v2 must keep the v1 column layout: the WAL tail
    # journals original (pre-interning) rows positionally per stream id
    old_streams, new_streams = _stream_schemas(old_app), _stream_schemas(new_app)
    for sid, cols in old_streams.items():
        if sid in new_streams and new_streams[sid] != cols:
            rep.add(Diagnostic(
                "SL302", Severity.ERROR,
                f"stream {sid!r} schema changed "
                f"({cols!r} -> {new_streams[sid]!r}): the journaled WAL "
                f"tail replays rows positionally in the v1 layout",
                element=sid))

    for key, fp in sorted(old_fps.items()):
        kind, _, name = key.partition(":")
        if key not in new_fps:
            if kind in STATEFUL_SECTIONS:
                diff.removed.append(key)
                rep.add(Diagnostic(
                    "SL304", Severity.WARN,
                    f"{key} removed in v2: its state is dropped",
                    element=name))
            continue
        if new_fps[key] == fp:
            if kind in STATEFUL_SECTIONS:
                diff.migratable.append(key)
            continue
        if kind == "stream":
            continue  # already flagged (SL302) when consumed layouts differ
        diff.changed.append(key)
        if kind in STATEFUL_SECTIONS:
            rep.add(Diagnostic(
                "SL303", Severity.WARN,
                f"{key} changed in v2: its state restarts empty",
                element=name))

    for key in sorted(set(new_fps) - set(old_fps)):
        diff.added.append(key)
        rep.add(Diagnostic(
            "SL305", Severity.INFO, f"{key} added in v2: starts empty",
            element=key.partition(":")[2]))

    if rep.has_errors:
        diff.classification = "incompatible"
    elif diff.changed or diff.removed:
        diff.classification = "state-migratable"
    else:
        diff.classification = "compatible"
    return diff
