"""The lint rule catalog (SL1xx: static plan rules).

Each rule is a function over a PlanGraph that yields Diagnostics. Rules run
inside a guard — a crashing rule is dropped (and logged at debug), never
surfaced to app creation — and every finding passes the suppression filter
(`@suppress.lint('SL101', ...)` on the element or the app) before it lands
in the report.

Severity policy: ERROR marks defects that build fine but are wrong at
runtime (silent query shadowing, dead fault wiring) or that creation would
reject anyway; WARN marks unbounded-state and config hazards; INFO marks
performance footnotes (silent numeric promotion, pad-back copies).
"""

from __future__ import annotations

import logging
import re
from typing import Callable, Iterable, Optional

from ..query_api.definition import AttributeType
from ..query_api.execution import (
    EveryStateElement,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    CountStateElement,
    Query,
    StateInputStream,
)
from ..query_api.expression import (
    And,
    Compare,
    CompareOp,
    Constant,
    Expression,
    Not,
    Or,
)
from .diagnostics import Diagnostic, LintReport, Severity
from .plan import ExprTyper, PlanGraph, QueryNode, _frames_for, _output_schema

log = logging.getLogger("siddhi_tpu.lint")

#: (rule_id, severity, checker, one-line description) — docs/LINT.md mirrors
#: this table
RULES: list[tuple[str, Severity, Callable, str]] = []


def rule(rule_id: str, severity: Severity, description: str):
    def deco(fn):
        RULES.append((rule_id, severity, fn, description))
        return fn
    return deco


def run_rules(plan: PlanGraph, report: LintReport) -> None:
    for rule_id, severity, fn, _desc in RULES:
        try:
            findings = fn(plan) or ()
        except Exception:
            log.debug("lint rule %s crashed; skipped", rule_id, exc_info=True)
            continue
        for element, message, anchor, loc in findings:
            if plan.suppressions.is_suppressed(rule_id, anchor):
                continue
            report.add(Diagnostic(rule_id, severity, message,
                                  element=element, loc=loc))


def _q(node: QueryNode, message: str):
    """Finding anchored at a query."""
    return (node.name, message, node.query, node.loc)


def _d(name: str, defn, message: str):
    """Finding anchored at a definition."""
    return (name, message, defn, getattr(defn, "loc", None))


# ------------------------------------------------------------- SL101 / SL102


@rule("SL101", Severity.ERROR,
      "a query consumes a stream that is neither defined nor produced")
def undefined_stream(plan: PlanGraph) -> Iterable:
    for node in plan.queries:
        for c in node.consumed:
            if c.stream_id in plan.schemas:
                continue
            if c.is_fault:
                continue  # base-stream existence is SL111's concern
            kind = "partition inner stream" if c.is_inner else "stream"
            yield _q(node, f"{kind} {c.stream_id!r} is not defined and no "
                           "query inserts into it")


@rule("SL102", Severity.WARN,
      "a defined stream is fully disconnected (no producer, consumer, "
      "@source or @sink)")
def unused_stream(plan: PlanGraph) -> Iterable:
    if not plan.queries:
        return  # definition-only apps feed everything externally
    for sid, schema in plan.schemas.items():
        if schema.kind != "stream" or schema.defn is None:
            continue
        d = schema.defn
        if sid in plan.consumers or sid in plan.producers:
            continue
        if any(a.name.lower() in ("source", "sink", "export", "import")
               for a in d.annotations or ()):
            continue
        yield _d(sid, d, f"stream {sid!r} is never consumed or produced by "
                         "any query and has no @source/@sink")


# ------------------------------------------------- SL103 / SL104 / SL105


def _filter_exprs(node: QueryNode):
    for c in node.consumed:
        h = c.single.handlers
        for f in h.filters:
            yield f, (c.single.alias or c.stream_id)
        for f in h.post_window_filters:
            yield f, (c.single.alias or c.stream_id)


def _type_check(node: QueryNode, plan: PlanGraph):
    """One typing pass per query: returns (issues, promotions)."""
    frames = _frames_for(node, plan)
    typer = ExprTyper(frames)

    for f, _ref in _filter_exprs(node):
        t = typer.type_of(f)
        if t is not None and t != AttributeType.BOOL:
            typer.issues.append(
                ("SL104", f"filter expression must be bool, got {t.value}"))

    ins = node.query.input_stream
    if isinstance(ins, JoinInputStream) and ins.on is not None:
        t = typer.type_of(ins.on)
        if t is not None and t != AttributeType.BOOL:
            typer.issues.append(
                ("SL104", f"join `on` condition must be bool, got {t.value}"))

    sel = node.query.selector
    for attr in sel.attributes:
        typer.type_of(attr.expression)
    for v in sel.group_by:
        typer.type_of(v)

    # having / order by see the select list's output columns too
    out_attrs = _output_schema(node, plan)
    post_frames = dict(frames)
    post_frames["#out"] = out_attrs
    post = ExprTyper(post_frames)
    if sel.having is not None:
        t = post.type_of(sel.having)
        if t is not None and t != AttributeType.BOOL:
            post.issues.append(
                ("SL104", f"having condition must be bool, got {t.value}"))
    for ob in sel.order_by:
        post.type_of(ob.variable)

    # delete/update ... on <cond> additionally sees the target table
    out = node.query.output_stream
    if out.on_condition is not None and out.target_id:
        tbl = plan.schemas.get(out.target_id)
        cond_frames = dict(frames)
        cond_frames[out.target_id] = tbl.attrs if tbl else None
        ct = ExprTyper(cond_frames)
        t = ct.type_of(out.on_condition)
        if t is not None and t != AttributeType.BOOL:
            ct.issues.append(
                ("SL104", f"`on` condition must be bool, got {t.value}"))
        typer.issues.extend(ct.issues)
        typer.promotions.extend(ct.promotions)

    typer.issues.extend(post.issues)
    typer.promotions.extend(post.promotions)
    return typer.issues, typer.promotions


def _typing_findings(plan: PlanGraph, want_code: str, promotions: bool = False):
    for node in plan.queries:
        issues, promos = _type_check(node, plan)
        if promotions:
            for msg in promos:
                yield _q(node, msg)
        else:
            seen = set()
            for code, msg in issues:
                if code == want_code and msg not in seen:
                    seen.add(msg)
                    yield _q(node, msg)


@rule("SL103", Severity.ERROR,
      "an expression references an attribute its input streams do not define")
def undefined_attribute(plan: PlanGraph) -> Iterable:
    yield from _typing_findings(plan, "SL103")


@rule("SL104", Severity.ERROR,
      "expression dtype mismatch (non-bool filter, string arithmetic, "
      "string ordering, bool/numeric comparison)")
def type_mismatch(plan: PlanGraph) -> Iterable:
    yield from _typing_findings(plan, "SL104")


@rule("SL105", Severity.INFO,
      "silent numeric promotion: integral and floating operands mix, the "
      "integral side loses precision on device")
def silent_promotion(plan: PlanGraph) -> Iterable:
    for node in plan.queries:
        _issues, promos = _type_check(node, plan)
        for msg in dict.fromkeys(promos):
            yield _q(node, msg)


# --------------------------------------------------- SL106 / SL107 / SL108


@rule("SL106", Severity.WARN,
      "join over a raw (unwindowed) stream retains every event forever")
def unbounded_join(plan: PlanGraph) -> Iterable:
    for node in plan.queries:
        ins = node.query.input_stream
        if not isinstance(ins, JoinInputStream):
            continue
        for side, label in ((ins.left, "left"), (ins.right, "right")):
            schema = plan.schemas.get(side.stream_id)
            kind = schema.kind if schema else "stream"
            if kind in ("table", "window", "aggregation"):
                continue  # bounded by the store's own retention
            if side.handlers.window is None:
                yield _q(node, f"{label} join side {side.stream_id!r} has no "
                               "window: its join buffer grows without "
                               "eviction (add #window.time/length or join a "
                               "table)")


def _has_every(state) -> bool:
    if isinstance(state, EveryStateElement):
        return True
    if isinstance(state, NextStateElement):
        return _has_every(state.state) or _has_every(state.next)
    if isinstance(state, LogicalStateElement):
        return _has_every(state.left) or _has_every(state.right)
    if isinstance(state, CountStateElement):
        return _has_every(state.element)
    return False


@rule("SL107", Severity.WARN,
      "pattern with `every` but no `within`: partial matches re-arm and "
      "accumulate unboundedly")
def every_without_within(plan: PlanGraph) -> Iterable:
    for node in plan.queries:
        ins = node.query.input_stream
        if not isinstance(ins, StateInputStream):
            continue
        if ins.within_ms is None and _has_every(ins.state):
            yield _q(node, "`every` pattern has no `within` bound: every "
                           "arrival re-arms the NFA and partial matches are "
                           "never expired (add `within <time>`)")


@rule("SL108", Severity.WARN,
      "named window defined without a window spec never evicts")
def window_without_eviction(plan: PlanGraph) -> Iterable:
    for wid, d in plan.app.window_definitions.items():
        if d.window is None:
            yield _d(wid, d, f"define window {wid!r} carries no window "
                             "specification: nothing is ever evicted")


# --------------------------------------------------- SL109 / SL110 / SL111


@rule("SL109", Severity.ERROR,
      "two queries share an @info name: the later silently shadows the "
      "earlier in runtime addressing")
def shadowed_query(plan: PlanGraph) -> Iterable:
    by_name: dict[str, list[QueryNode]] = {}
    for node in plan.queries:
        if node.explicit_name:
            by_name.setdefault(node.name, []).append(node)
    for name, nodes in by_name.items():
        for later in nodes[1:]:
            yield _q(later, f"query name {name!r} is already used by an "
                            "earlier query; callbacks and statistics "
                            "addressed by name silently bind to only one "
                            "of them")


def _const_fold(expr: Expression):
    """Fold constant boolean expressions; None = not statically known."""
    if isinstance(expr, Constant):
        if expr.type_name == "bool":
            return bool(expr.value)
        return None
    if isinstance(expr, Not):
        inner = _const_fold(expr.expression)
        return None if inner is None else not inner
    if isinstance(expr, And):
        l, r = _const_fold(expr.left), _const_fold(expr.right)
        if l is False or r is False:
            return False
        if l is True and r is True:
            return True
        return None
    if isinstance(expr, Or):
        l, r = _const_fold(expr.left), _const_fold(expr.right)
        if l is True or r is True:
            return True
        if l is False and r is False:
            return False
        return None
    if isinstance(expr, Compare):
        lc, rc = expr.left, expr.right
        if not (isinstance(lc, Constant) and isinstance(rc, Constant)):
            return None
        lv, rv = lc.value, rc.value
        if isinstance(lv, bool) != isinstance(rv, bool):
            return None
        if isinstance(lv, str) != isinstance(rv, str):
            return None
        try:
            return {
                CompareOp.EQUAL: lv == rv,
                CompareOp.NOT_EQUAL: lv != rv,
                CompareOp.GREATER_THAN: lv > rv,
                CompareOp.GREATER_THAN_EQUAL: lv >= rv,
                CompareOp.LESS_THAN: lv < rv,
                CompareOp.LESS_THAN_EQUAL: lv <= rv,
            }[expr.op]
        except TypeError:
            return None
    return None


@rule("SL110", Severity.WARN,
      "a filter folds to constant false: the query can never emit")
def dead_query(plan: PlanGraph) -> Iterable:
    for node in plan.queries:
        for f, ref in _filter_exprs(node):
            if _const_fold(f) is False:
                yield _q(node, f"filter on {ref!r} is constant false — the "
                               "query is dead (no event can ever pass)")


@rule("SL111", Severity.ERROR,
      "fault-stream wiring (`!S`) without @OnError(action='STREAM') on S")
def fault_wiring(plan: PlanGraph) -> Iterable:
    def has_fault_stream(sid: str) -> bool:
        schema = plan.schemas.get(sid)
        d = schema.defn if schema else None
        if d is None or not getattr(d, "annotations", None):
            return False
        for ann in d.annotations:
            if ann.name.lower() == "onerror":
                action = (ann.element("action") or "log")
                return str(action).lower() == "stream"
        return False

    for node in plan.queries:
        for c in node.consumed:
            if c.is_fault and not has_fault_stream(c.stream_id):
                yield _q(node, f"`from !{c.stream_id}` consumes a fault "
                               f"stream, but {c.stream_id!r} does not "
                               "declare @OnError(action='STREAM') so no "
                               "fault stream exists")
        out = node.query.output_stream
        if node.produces_fault and not has_fault_stream(node.produces):
            yield _q(node, f"`insert into !{out.target_id}` targets a fault "
                           f"stream, but {out.target_id!r} does not declare "
                           "@OnError(action='STREAM')")


# ------------------------------------------------------------------- SL112


_TIME_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*"
                      r"(ms|milli\w*|sec\w*|min\w*|hour\w*|day\w*)?\s*$",
                      re.IGNORECASE)
_TIME_MS = {"ms": 1, "milli": 1, "sec": 1000, "min": 60_000,
            "hour": 3_600_000, "day": 86_400_000}


def _ann_time_ms(text: str) -> Optional[float]:
    m = _TIME_RE.match(str(text))
    if not m:
        return None
    value = float(m.group(1))
    unit = (m.group(2) or "ms").lower()
    for prefix, ms in _TIME_MS.items():
        if unit.startswith(prefix):
            return value * ms
    return value


@rule("SL112", Severity.ERROR,
      "nonsensical @Async/@breaker configuration (inverted watermarks, "
      "threshold < 1, max.staged < buffer.size, unknown overflow policy)")
def bad_backpressure_config(plan: PlanGraph) -> Iterable:
    for sid, schema in plan.schemas.items():
        d = schema.defn
        if d is None or not getattr(d, "annotations", None):
            continue
        ann = next((a for a in d.annotations
                    if a.name.lower() == "async"), None)
        if ann is None:
            continue

        def num(key):
            v = ann.element(key)
            try:
                return float(v) if v is not None else None
            except (TypeError, ValueError):
                return None

        buf = num("buffer.size")
        if buf is not None and buf <= 0:
            yield _d(sid, d, f"@Async on {sid!r}: buffer.size must be "
                             "positive")
        staged = num("max.staged")
        if buf is not None and staged is not None and staged < buf:
            yield _d(sid, d, f"@Async on {sid!r}: max.staged ({staged:g}) "
                             f"must be >= buffer.size ({buf:g})")
        pol = ann.element("overflow.policy")
        if pol is not None and str(pol).lower() not in (
                "block", "drop.new", "drop.old", "fault"):
            yield _d(sid, d, f"@Async on {sid!r}: unknown overflow.policy "
                             f"{pol!r} (block | drop.new | drop.old | fault)")
        hw = num("high.watermark")
        lw = num("low.watermark")
        hw_v = 0.8 if hw is None else hw
        lw_v = 0.2 if lw is None else lw
        if (hw is not None or lw is not None) and not (
                0.0 <= lw_v < hw_v <= 1.0):
            yield _d(sid, d, f"@Async on {sid!r}: watermarks must satisfy "
                             f"0 <= low.watermark ({lw_v:g}) < "
                             f"high.watermark ({hw_v:g}) <= 1")

    for node in plan.queries:
        ann = next((a for a in node.query.annotations
                    if a.name.lower() == "breaker"), None)
        if ann is None:
            continue
        thr = ann.element("threshold")
        if thr is not None:
            try:
                if int(str(thr)) < 1:
                    yield _q(node, f"@breaker threshold ({thr}) must be "
                                   ">= 1 — a breaker that trips on zero "
                                   "failures never closes")
            except ValueError:
                yield _q(node, f"@breaker threshold {thr!r} is not an "
                               "integer")
        for key in ("window", "cooldown"):
            v = ann.element(key)
            if v is None:
                continue
            ms = _ann_time_ms(v)
            if ms is None:
                yield _q(node, f"@breaker {key} {v!r} is not a time "
                               "literal (e.g. '30 sec')")
            elif ms <= 0:
                yield _q(node, f"@breaker {key} must be positive, got {v!r}")


# ------------------------------------------------------------------- SL113


#: window names whose device implementation consumes variable-lane batches
#: directly (ops/windows.py shape_polymorphic=True); every other window is
#: shape-baked: bucketed batches pad back to full capacity before the step
_SHAPE_POLYMORPHIC_WINDOWS = {"time"}


@rule("SL113", Severity.WARN,
      "shape buckets are enabled but the query's step is shape-baked: "
      "every partial batch pads back to full capacity")
def shape_bucket_padback(plan: PlanGraph) -> Iterable:
    from ..core import dtypes
    if not dtypes.config.shape_buckets:
        return
    for node in plan.queries:
        for c in node.consumed:
            if c.role != "single":
                continue  # joins/patterns are shape-baked by design
            w = c.single.handlers.window
            if w is None:
                continue  # pass-through is shape-polymorphic
            if w.name in _SHAPE_POLYMORPHIC_WINDOWS:
                continue
            if w.name == "batch" and not w.parameters:
                continue  # paramless batch lowers to pass-through
            yield _q(node, f"#window.{w.name} is shape-baked while shape "
                           "buckets are on: small batches pad back to the "
                           "full batch capacity each step (copies, no "
                           "per-bucket kernels); use #window.time for "
                           "shape-polymorphic steps or set "
                           "SIDDHI_SHAPE_BUCKETS=0")


# ------------------------------------------------------------------- SL114


@rule("SL114", Severity.INFO,
      "co-resident queries on one stream can share a compiled step "
      "(multi-query optimizer: @app:optimize / SIDDHI_OPTIMIZE=1)")
def shareable_work(plan: PlanGraph) -> Iterable:
    from .optimizer import analyze_sharing
    report = analyze_sharing(plan)
    verb = "fuses" if report.enabled else "would fuse (optimizer off)"
    for g in report.groups:
        anchor = g.nodes[0]
        yield _q(anchor,
                 f"stream {g.stream_id!r}: optimizer {verb} "
                 f"{len(g.members)} queries ({', '.join(g.members)}) into "
                 f"one compiled step — {g.shared_subexpressions} shared "
                 f"subexpression(s), {g.pushdowns} pushable predicate(s), "
                 f"{g.pane_candidates} span-correlated window(s); saves "
                 f"{g.steps_saved} step dispatch(es)/compile(s) per batch")
    for node, reason in report.declined_nodes:
        yield _q(node, "optimizer declines to fuse this query even though "
                       f"its stream hosts shareable work: {reason}")


# ------------------------------------------------------------------- SL116


_EXTERNAL_TIME_WINDOWS = {"externaltime", "externaltimebatch"}


@rule("SL116", Severity.ERROR,
      "externalTime window fed from an @Async(workers>1) multi-producer "
      "stream with no @app:eventTime lateness declared: racing producers "
      "make the max-seen watermark nondeterministic")
def racing_external_time(plan: PlanGraph) -> Iterable:
    # N ingress workers race each other into the columnar ring, so the order
    # the window sees — and therefore every max-seen watermark advance and
    # pane close — varies run to run. @app:eventTime(allowed.lateness=...)
    # is the fix: the ingress gate re-sorts arrivals by event time (bounded
    # by the lateness budget) before the device ever sees them.
    et_ann = plan.app.annotation("app:eventTime")
    if et_ann is not None and et_ann.element("allowed.lateness"):
        return

    def workers(sid: str) -> int:
        schema = plan.schemas.get(sid)
        d = schema.defn if schema else None
        if d is None or not getattr(d, "annotations", None):
            return 0
        ann = next((a for a in d.annotations
                    if a.name.lower() == "async"), None)
        if ann is None:
            return 0
        try:
            return int(ann.element("workers") or 0)
        except (TypeError, ValueError):
            return 0

    for node in plan.queries:
        for c in node.consumed:
            w = c.single.handlers.window
            if w is None or w.name.lower() not in _EXTERNAL_TIME_WINDOWS:
                continue
            n = workers(c.stream_id)
            if n > 1:
                fix = ("declare @app:eventTime(timestamp='<attr>', "
                       "allowed.lateness='...') so arrivals sort before "
                       "the window" if et_ann is None else
                       "add allowed.lateness to @app:eventTime")
                yield _q(node, f"#window.{w.name} consumes "
                               f"{c.stream_id!r} which @Async(workers={n}) "
                               "fills from racing producers: the max-seen "
                               "event-time watermark (and every pane close) "
                               f"becomes nondeterministic — {fix}")


# ------------------------------------------------------------------- SL5xx
# capacity certification: the static cost model (analysis/cost.py) priced
# against the configured budget (@app:budget / SIDDHI_STATE_BUDGET).
# docs/COST.md documents the per-operator formulas; tools/cost_calibrate.py
# holds predictions within 2x of live telemetry.


def _query_by_index(plan: PlanGraph, index) -> Optional[QueryNode]:
    return next((n for n in plan.queries if n.index == index), None)


def _cost_anchor(plan: PlanGraph, rep) -> Optional[QueryNode]:
    """Anchor app-level cost findings at the dominant element's query when
    it has one, else the first query (definitions lack a natural anchor)."""
    if rep.dominant is not None and rep.dominant.node_index is not None:
        node = _query_by_index(plan, rep.dominant.node_index)
        if node is not None:
            return node
    return plan.queries[0] if plan.queries else None


@rule("SL501", Severity.ERROR,
      "predicted device state / compile ladder exceeds the configured "
      "budget (@app:budget / SIDDHI_STATE_BUDGET / SIDDHI_COMPILE_BUDGET)")
def over_budget(plan: PlanGraph) -> Iterable:
    from .cost import app_budget, cost_for_plan, format_size
    budget = app_budget(plan.app)
    if budget is None:
        return
    rep = cost_for_plan(plan)
    anchor = _cost_anchor(plan, rep)
    if anchor is None:
        return
    if budget.state_bytes is not None and rep.state_bytes > budget.state_bytes:
        dom = ""
        if rep.dominant is not None:
            dom = (f" — dominant element {rep.dominant.element!r} holds "
                   f"{format_size(rep.dominant.state_bytes)}")
        yield _q(anchor,
                 f"predicted device state {format_size(rep.state_bytes)} "
                 f"exceeds the configured budget "
                 f"{format_size(budget.state_bytes)} "
                 f"({budget.source}){dom}; shrink window/table/group "
                 "capacities or raise the budget (admission control: "
                 "creation refuses or queues this app)")
    if budget.compiles is not None and rep.compile_ladder > budget.compiles:
        yield _q(anchor,
                 f"predicted compile ladder ({rep.compile_ladder} "
                 f"executables) exceeds the configured compile budget "
                 f"({budget.compiles}, {budget.source}); fuse queries "
                 "(@app:optimize) or disable shape buckets for this app")


@rule("SL502", Severity.ERROR,
      "statically unbounded state growth while a state budget is "
      "configured: the budget cannot be certified")
def unbounded_state_growth(plan: PlanGraph) -> Iterable:
    from .cost import app_budget
    budget = app_budget(plan.app)
    if budget is None or budget.state_bytes is None:
        return
    for node in plan.queries:
        ins = node.query.input_stream
        if isinstance(ins, JoinInputStream):
            for side in (ins.left, ins.right):
                schema = plan.schemas.get(side.stream_id)
                kind = schema.kind if schema is not None else None
                if kind in ("table", "window", "aggregation"):
                    continue  # store-backed sides have their own bounds
                if side.handlers.window is None:
                    yield _q(node,
                             f"join side {side.stream_id!r} has no "
                             "retention window: its state demand is "
                             "statically unbounded, so the configured "
                             "state budget cannot be certified — add "
                             "#window.time/#window.length to the side")
        frames = _frames_for(node, plan)
        typer = ExprTyper(frames)
        for g in node.query.selector.group_by:
            if typer.type_of(g) == AttributeType.STRING:
                yield _q(node,
                         "group by over a raw string key: the host intern "
                         "table grows with key cardinality without bound, "
                         "so the configured state budget cannot be "
                         "certified — bound the key domain or group by an "
                         "integer key")
    for sid, schema in plan.schemas.items():
        if schema.kind != "window" or schema.defn is None:
            continue
        if getattr(schema.defn, "window", None) is None:
            yield _d(sid, schema.defn,
                     f"named window {sid!r} declares no retention spec: "
                     "its contents contract is unbounded in the reference "
                     "semantics, so the configured state budget cannot be "
                     "certified — declare an explicit window spec")


@rule("SL503", Severity.WARN,
      "compile-ladder explosion: predicted executable count exceeds the "
      "threshold (budget compiles / SIDDHI_COMPILE_LADDER_WARN, default 64)")
def compile_ladder_explosion(plan: PlanGraph) -> Iterable:
    import os
    from .cost import app_budget, cost_for_plan
    budget = app_budget(plan.app)
    if budget is not None and budget.compiles is not None:
        threshold = budget.compiles
    else:
        try:
            threshold = int(
                os.environ.get("SIDDHI_COMPILE_LADDER_WARN", "") or 64)
        except ValueError:
            threshold = 64
    rep = cost_for_plan(plan)
    if rep.compile_ladder <= threshold or not plan.queries:
        return
    yield _q(plan.queries[0],
             f"predicted compile ladder: {rep.compile_ladder} executables "
             f"(> {threshold}) across shape buckets x queries x steps — "
             "expect a long warmup and a large executable cache; fuse "
             "co-resident queries (@app:optimize), reduce query count, or "
             "set SIDDHI_SHAPE_BUCKETS=0")


@rule("SL504", Severity.WARN,
      "dispatch-heavy plan: a host callback rides every micro-batch "
      "(today only the SIDDHI_RADIX_CALLBACK=1 legacy escape hatch)")
def host_hop_per_batch(plan: PlanGraph) -> Iterable:
    from .cost import cost_for_plan, superstep_k
    if (superstep_k(plan.app) > 1
            and _superstep_ineligibility(plan,
                                         include_dispatch=False) is None):
        # the plan rides K-batch supersteps (or, when the hop itself is
        # the only blocker, SL506 names the callback as the decline
        # reason): don't double-report the same dispatch
        return
    rep = cost_for_plan(plan)
    for e in rep.elements:
        if e.dispatch != "host" or e.node_index is None:
            continue
        node = _query_by_index(plan, e.node_index)
        if node is None:
            continue
        detail = next((n for n in e.notes if "host" in n), "")
        yield _q(node,
                 "this step takes a host-callback hop every micro-batch"
                 + (f": {detail}" if detail else "")
                 + " — pjit's C++ fastpath is vetoed for the whole "
                 "executable (tools/fastpath_gate.py tracks these)")


@rule("SL505", Severity.INFO,
      "cost-dominant element: one element holds >50% of the app's "
      "predicted device state")
def cost_dominant_element(plan: PlanGraph) -> Iterable:
    import os
    from .cost import cost_for_plan, format_size, parse_size
    try:
        floor = parse_size(
            os.environ.get("SIDDHI_COST_NOTE_MIN", "") or "64MiB")
    except ValueError:
        floor = 64 << 20
    rep = cost_for_plan(plan)
    if rep.state_bytes < floor or rep.dominant is None:
        return
    e = rep.dominant
    msg = (f"element {e.element!r} holds {format_size(e.state_bytes)} of "
           f"{format_size(rep.state_bytes)} predicted device state "
           f"({rep.dominant_share:.0%}) — the first target for capacity "
           "tuning (docs/COST.md)")
    if e.node_index is not None:
        node = _query_by_index(plan, e.node_index)
        if node is not None:
            yield _q(node, msg)
            return
    schema = plan.schemas.get(e.element)
    if schema is not None and schema.defn is not None:
        yield _d(e.element, schema.defn, msg)


def _superstep_ineligibility(plan: PlanGraph, *,
                             include_dispatch: bool = True):
    """First STATIC reason the superstep scan would decline this plan, as
    (reason, anchor-node-or-None) — or None when nothing statically rules
    it out. A lightweight mirror of core/superstep.py's runtime decline
    taxonomy: only the facts visible in the AST/plan are checked (the
    runtime additionally declines on breakers, tables, sinks, callbacks
    registered after creation, ...)."""
    import os
    app = plan.app
    try:
        env_workers = int(os.environ.get("SIDDHI_INGRESS_WORKERS", "0") or 0)
    except ValueError:
        env_workers = 0
    async_sids = []
    for sid, schema in plan.schemas.items():
        d = schema.defn
        if schema.kind != "stream" or d is None or not d.annotations:
            continue
        ann = d.annotation("async")
        if ann is None:
            continue
        try:
            w = ann.element("workers")
            workers = int(w) if w else env_workers
        except ValueError:
            workers = env_workers
        if workers > 0:
            async_sids.append(sid)
    if not async_sids:
        return ("no @Async(workers=) stream: the ingress pipeline — and "
                "with it the superstep feeder — never engages", None)
    if app is not None and app.annotation("app:playback") is not None:
        return ("@app:playback drives virtual time per delivered batch, "
                "but a superstep samples `now` once per K batches", None)
    for sid in async_sids:
        schema = plan.schemas[sid]
        if any(a.type == AttributeType.OBJECT
               for a in schema.defn.attributes):
            return (f"stream {sid!r} carries OBJECT columns, which stay "
                    "host-side", None)
        for node in plan.queries:
            if all(c.stream_id != sid for c in node.consumed):
                continue
            if node.partition is not None:
                return ("a partitioned query consumes the @Async stream "
                        f"{sid!r}: per-key instances dispatch host-side",
                        node)
            if isinstance(node.query.input_stream, StateInputStream):
                return ("a pattern/sequence query consumes the @Async "
                        f"stream {sid!r}: NFA steps are not scannable "
                        "receivers", node)
    if include_dispatch:
        from .cost import cost_for_plan
        rep = cost_for_plan(plan)
        for e in rep.elements:
            if e.dispatch == "host":
                node = (None if e.node_index is None
                        else _query_by_index(plan, e.node_index))
                return (f"step {e.element!r} takes a host-callback hop "
                        "(SIDDHI_RADIX_CALLBACK=1 legacy radix sort)",
                        node)
    return None


@rule("SL506", Severity.INFO,
      "superstep requested (@app:superstep k>1) but the plan is statically "
      "ineligible: the ingress feeder will fall back to per-batch dispatch")
def superstep_ineligible(plan: PlanGraph) -> Iterable:
    from .cost import superstep_k
    k = superstep_k(plan.app)
    if k <= 1 or not plan.queries:
        return
    found = _superstep_ineligibility(plan)
    if found is None:
        return
    reason, node = found
    anchor = node if node is not None else plan.queries[0]
    yield _q(anchor,
             f"@app:superstep(k={k}) cannot engage: {reason} — the "
             "ingress feeder falls back to per-batch (K=1) dispatch at "
             "runtime, loudly, with the reason in stats_snapshot()"
             "['superstep_decline'] (core/superstep.py decline taxonomy, "
             "docs/PERFORMANCE.md)")


@rule("SL601", Severity.ERROR,
      "shard-ineligible element under @app:shards: a global operator "
      "(count window, unkeyed aggregate, pattern, named window, trigger, "
      "non-key join) would be silently wrong when sharded")
def shard_ineligible(plan: PlanGraph) -> Iterable:
    from .sharding import shard_config, shard_violations
    cfg = shard_config(plan.app)
    if cfg is None:
        return
    for v in shard_violations(plan, cfg.key):
        msg = (f"not shard-eligible under partition key {cfg.key!r}: "
               f"{v.reason} — the shard plane will refuse this app "
               "(docs/SHARDING.md)")
        if v.node is not None:
            yield _q(v.node, msg)
        else:
            yield _d(v.element, v.defn, msg)


@rule("SL602", Severity.WARN,
      "skewed shard routing: a filter pins the partition key to one "
      "literal, so every matching row hashes to a single shard")
def skewed_shard_key(plan: PlanGraph) -> Iterable:
    from ..query_api.expression import Variable
    from .sharding import _conjuncts, shard_config
    cfg = shard_config(plan.app)
    if cfg is None:
        return
    for node in plan.queries:
        for c in node.consumed:
            chain = c.single.handlers
            for f in tuple(chain.filters) + tuple(chain.post_window_filters):
                for conj in _conjuncts(f):
                    if not (isinstance(conj, Compare)
                            and conj.op is CompareOp.EQUAL):
                        continue
                    sides = (conj.left, conj.right)
                    var = next((s for s in sides
                                if isinstance(s, Variable)
                                and s.attribute == cfg.key), None)
                    lit = next((s for s in sides
                                if isinstance(s, Constant)), None)
                    if var is None or lit is None:
                        continue
                    yield _q(node,
                             f"filter pins partition key {cfg.key!r} to "
                             f"literal {lit.value!r}: every matching row "
                             f"hashes to ONE of the {cfg.n} shards, so "
                             "this query's traffic cannot scale past one "
                             "replica — shard by a higher-cardinality "
                             "key, or drop @app:shards for this app "
                             "(docs/SHARDING.md)")


def check_query(query: Query) -> None:
    """Hook for future per-query API use; kept minimal."""
    _ = query
