"""Plan graph: a typed DAG lowered from a parsed SiddhiApp.

The graph is the analysis-side analogue of what SiddhiAppRuntime._build wires
at creation time — stream/table/window/trigger/aggregation schemas as nodes,
queries as edges — but built WITHOUT planning any device state, so linting an
app costs milliseconds and can never allocate or compile anything.

Schemas are permissive on purpose: any element the analyzer cannot type
statically (stream functions rewriting columns, script functions, unknown
extensions) degrades to an *open* schema that downstream rules skip, so the
linter under-reports instead of false-positiving (the zero-FP sweep in
tests/test_lint.py holds the line).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..query_api import SiddhiApp
from ..query_api.definition import AttributeType
from ..query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    Partition,
    Query,
    SingleInputStream,
    StateInputStream,
    StreamStateElement,
)
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    In,
    IsNull,
    MathExpression,
    Not,
    Or,
    Variable,
)
from .diagnostics import Suppressions

#: attrs dict value for "exists but statically untypeable"
UNKNOWN = None

_CONST_TYPES = {
    "int": AttributeType.INT, "long": AttributeType.LONG,
    "float": AttributeType.FLOAT, "double": AttributeType.DOUBLE,
    "bool": AttributeType.BOOL, "string": AttributeType.STRING,
    "time": AttributeType.LONG,
}

_NUMERIC = {AttributeType.INT, AttributeType.LONG,
            AttributeType.FLOAT, AttributeType.DOUBLE}
_INTEGRAL = {AttributeType.INT, AttributeType.LONG}
_FLOATING = {AttributeType.FLOAT, AttributeType.DOUBLE}
_RANK = {AttributeType.INT: 0, AttributeType.LONG: 1,
         AttributeType.FLOAT: 2, AttributeType.DOUBLE: 3}


def _promote(a: AttributeType, b: AttributeType) -> Optional[AttributeType]:
    if a not in _NUMERIC or b not in _NUMERIC:
        return None
    return a if _RANK[a] >= _RANK[b] else b


@dataclass
class StreamSchema:
    """One named node: kind + attribute types. `attrs=None` means the schema
    is open (unknown columns); a present attr mapped to UNKNOWN means the
    column exists but its type could not be inferred."""

    name: str
    kind: str  # stream | table | window | trigger | aggregation | derived | fault
    attrs: Optional[dict[str, Optional[AttributeType]]] = None
    defn: object = None  # declaring definition, when one exists

    @property
    def is_open(self) -> bool:
        return self.attrs is None


@dataclass
class ConsumedStream:
    """One stream reference consumed by a query's FROM clause."""

    stream_id: str
    single: SingleInputStream
    role: str  # single | join-left | join-right | pattern
    is_fault: bool = False
    is_inner: bool = False


@dataclass
class QueryNode:
    query: Query
    name: str
    explicit_name: bool
    index: int
    partition: Optional[Partition] = None
    consumed: list[ConsumedStream] = field(default_factory=list)
    #: insert-target stream id (None for table writes / RETURN)
    produces: Optional[str] = None
    produces_fault: bool = False

    @property
    def loc(self):
        return self.query.loc


@dataclass
class PlanGraph:
    app: SiddhiApp
    schemas: dict[str, StreamSchema] = field(default_factory=dict)
    queries: list[QueryNode] = field(default_factory=list)
    producers: dict[str, list[QueryNode]] = field(default_factory=dict)
    consumers: dict[str, list[QueryNode]] = field(default_factory=dict)
    suppressions: Optional[Suppressions] = None
    #: (rule_code, message, query_node) tuples collected while typing
    #: expressions during the build; rules.py turns them into diagnostics
    expr_issues: list[tuple] = field(default_factory=list)

    def schema(self, name: str) -> Optional[StreamSchema]:
        return self.schemas.get(name)


def _leaf_streams(state) -> list[SingleInputStream]:
    """Flatten a pattern/sequence state tree to its stream leaves."""
    if isinstance(state, StreamStateElement):
        return [state.stream]
    if isinstance(state, AbsentStreamStateElement):
        return [state.stream]
    if isinstance(state, CountStateElement):
        return _leaf_streams(state.element)
    if isinstance(state, (EveryStateElement,)):
        return _leaf_streams(state.state)
    if isinstance(state, LogicalStateElement):
        return _leaf_streams(state.left) + _leaf_streams(state.right)
    if isinstance(state, NextStateElement):
        return _leaf_streams(state.state) + _leaf_streams(state.next)
    return []


def consumed_streams(query: Query) -> list[ConsumedStream]:
    ins = query.input_stream
    out: list[ConsumedStream] = []
    if isinstance(ins, SingleInputStream):
        out.append(ConsumedStream(ins.stream_id, ins, "single",
                                  is_fault=ins.is_fault, is_inner=ins.is_inner))
    elif isinstance(ins, JoinInputStream):
        out.append(ConsumedStream(ins.left.stream_id, ins.left, "join-left",
                                  is_fault=ins.left.is_fault,
                                  is_inner=ins.left.is_inner))
        out.append(ConsumedStream(ins.right.stream_id, ins.right, "join-right",
                                  is_fault=ins.right.is_fault,
                                  is_inner=ins.right.is_inner))
    elif isinstance(ins, StateInputStream):
        for s in _leaf_streams(ins.state):
            out.append(ConsumedStream(s.stream_id, s, "pattern",
                                      is_fault=s.is_fault, is_inner=s.is_inner))
    return out


# -------------------------------------------------------------- expr typing


class ExprTyper:
    """Static mirror of ops/expr_compile.py's type rules. `frames` maps a
    stream reference (alias or id) to its attrs dict (None = open frame).
    Typing NEVER raises; confident violations are appended to `issues` as
    (code, message) and everything uncertain types as UNKNOWN."""

    def __init__(self, frames: dict[str, Optional[dict]],
                 default_frame: Optional[str] = None) -> None:
        self.frames = frames
        self.default = default_frame
        self.issues: list[tuple[str, str]] = []
        self.promotions: list[str] = []
        self.any_open = any(v is None for v in frames.values())

    # -- resolution

    def _resolve(self, v: Variable) -> Optional[AttributeType]:
        if v.stream_id is not None:
            frame = self.frames.get(v.stream_id)
            if frame is None:
                # unknown frame name or open frame: runtime resolution owns it
                return UNKNOWN
            if v.attribute not in frame:
                self.issues.append((
                    "SL103",
                    f"attribute {v.attribute!r} is not defined on "
                    f"{v.stream_id!r} (has: {', '.join(sorted(frame))})"))
                return UNKNOWN
            return frame[v.attribute]
        hits = [frame[v.attribute] for frame in self.frames.values()
                if frame is not None and v.attribute in frame]
        if not hits:
            if self.any_open:
                return UNKNOWN  # could live on an open frame
            self.issues.append((
                "SL103",
                f"attribute {v.attribute!r} is not defined on any input "
                f"stream ({', '.join(sorted(self.frames))})"))
            return UNKNOWN
        if len(hits) > 1:
            return UNKNOWN  # ambiguity is a creation-time error; not re-flagged
        return hits[0]

    # -- typing

    def type_of(self, expr: Expression) -> Optional[AttributeType]:
        if isinstance(expr, Constant):
            return _CONST_TYPES.get(expr.type_name, UNKNOWN)
        if isinstance(expr, Variable):
            return self._resolve(expr)
        if isinstance(expr, (And, Or)):
            lt, rt = self.type_of(expr.left), self.type_of(expr.right)
            for t in (lt, rt):
                if t is not UNKNOWN and t != AttributeType.BOOL:
                    self.issues.append((
                        "SL104",
                        f"logical operator requires bool operands, got "
                        f"{t.value}"))
            return AttributeType.BOOL
        if isinstance(expr, Not):
            t = self.type_of(expr.expression)
            if t is not UNKNOWN and t != AttributeType.BOOL:
                self.issues.append((
                    "SL104",
                    f"`not` requires a bool operand, got {t.value}"))
            return AttributeType.BOOL
        if isinstance(expr, Compare):
            return self._type_compare(expr)
        if isinstance(expr, MathExpression):
            return self._type_math(expr)
        if isinstance(expr, IsNull):
            if expr.expression is not None and expr.stream_id is None:
                self.type_of(expr.expression)
            return AttributeType.BOOL
        if isinstance(expr, In):
            self.type_of(expr.expression)
            return AttributeType.BOOL
        if isinstance(expr, AttributeFunction):
            return self._type_function(expr)
        return UNKNOWN

    def _type_compare(self, expr: Compare) -> AttributeType:
        lt, rt = self.type_of(expr.left), self.type_of(expr.right)
        if lt is UNKNOWN or rt is UNKNOWN:
            return AttributeType.BOOL
        ordered = expr.op not in (CompareOp.EQUAL, CompareOp.NOT_EQUAL)
        if lt == AttributeType.STRING and rt == AttributeType.STRING:
            if ordered:
                self.issues.append((
                    "SL104",
                    "string ordering comparisons are unsupported on device "
                    "(dictionary codes are unordered); only ==/!= work"))
            return AttributeType.BOOL
        if AttributeType.STRING in (lt, rt):
            self.issues.append((
                "SL104",
                f"cannot compare {lt.value} with {rt.value}"))
            return AttributeType.BOOL
        if AttributeType.BOOL in (lt, rt):
            if lt != rt:
                self.issues.append((
                    "SL104", f"cannot compare {lt.value} with {rt.value}"))
            return AttributeType.BOOL
        if not (isinstance(expr.left, Constant)
                or isinstance(expr.right, Constant)):
            # literals adopt the column dtype (weak typing): only flag
            # column-vs-column mixing
            self._note_promotion(lt, rt, "comparison")
        return AttributeType.BOOL

    def _type_math(self, expr: MathExpression) -> Optional[AttributeType]:
        lt, rt = self.type_of(expr.left), self.type_of(expr.right)
        if lt is UNKNOWN or rt is UNKNOWN:
            return UNKNOWN
        if lt not in _NUMERIC or rt not in _NUMERIC:
            self.issues.append((
                "SL104",
                f"cannot apply arithmetic ({expr.op.value}) to "
                f"{lt.value}/{rt.value}"))
            return UNKNOWN
        if not (isinstance(expr.left, Constant)
                or isinstance(expr.right, Constant)):
            self._note_promotion(lt, rt, f"arithmetic ({expr.op.value})")
        return _promote(lt, rt)

    def _note_promotion(self, lt, rt, ctx: str) -> None:
        """Integral/floating mixing silently promotes: long→float32/float64
        loses precision above 2^24/2^53 (and DOUBLE itself maps to float32
        on device by default — core/dtypes.py)."""
        if (lt in _INTEGRAL) != (rt in _INTEGRAL):
            big, small = (lt, rt) if _RANK[lt] >= _RANK[rt] else (rt, lt)
            self.promotions.append(
                f"{ctx} mixes {small.value} with {big.value}: the "
                f"{'long' if AttributeType.LONG in (lt, rt) else 'int'} side "
                f"silently promotes to {big.value} "
                f"(float32 on device unless config.double_dtype is widened)")

    def _type_function(self, expr: AttributeFunction) -> Optional[AttributeType]:
        arg_types = [self.type_of(p) for p in expr.parameters]
        name = expr.name
        full = expr.full_name.lower()
        if full in ("eventtimestamp", "currenttimemillis", "count",
                    "distinctcount", "hll:distinctcount", "sizeofset"):
            return AttributeType.LONG
        if full in ("avg", "stddev", "math:sqrt", "math:log", "math:exp",
                    "math:sin", "math:cos", "math:power"):
            return AttributeType.DOUBLE
        if full == "uuid":
            return AttributeType.STRING
        if full.startswith("instanceof"):
            return AttributeType.BOOL
        if full in ("and", "or"):
            return AttributeType.BOOL
        if full == "sum":
            if arg_types and arg_types[0] in _INTEGRAL:
                return AttributeType.LONG
            if arg_types and arg_types[0] in _FLOATING:
                return AttributeType.DOUBLE
            return UNKNOWN
        if full in ("min", "max", "minforever", "maxforever", "math:abs",
                    "math:floor", "math:ceil", "math:round"):
            return arg_types[0] if arg_types else UNKNOWN
        if full in ("maximum", "minimum"):
            out = arg_types[0] if arg_types else UNKNOWN
            for t in arg_types[1:]:
                out = _promote(out, t) if (out and t) else UNKNOWN
            return out
        if full in ("convert", "cast") and len(expr.parameters) >= 2:
            target = expr.parameters[1]
            if isinstance(target, Constant) and isinstance(target.value, str):
                try:
                    return AttributeType.parse(target.value)
                except ValueError:
                    return UNKNOWN
            return UNKNOWN
        if full == "ifthenelse" and len(arg_types) == 3:
            cond = arg_types[0]
            if cond is not UNKNOWN and cond != AttributeType.BOOL:
                self.issues.append((
                    "SL104",
                    f"ifThenElse condition must be bool, got {cond.value}"))
            a, b = arg_types[1], arg_types[2]
            if a is UNKNOWN or b is UNKNOWN:
                return UNKNOWN
            return a if a == b else _promote(a, b)
        if full == "coalesce":
            return arg_types[0] if arg_types else UNKNOWN
        _ = name
        return UNKNOWN  # extension/script function: stay open


# ---------------------------------------------------------------- the build


def _declared_schemas(app: SiddhiApp) -> dict[str, StreamSchema]:
    schemas: dict[str, StreamSchema] = {}

    def attrs_of(defn) -> dict:
        return {a.name: a.type for a in defn.attributes}

    for sid, d in app.stream_definitions.items():
        schemas[sid] = StreamSchema(sid, "stream", attrs_of(d), d)
    for tid, d in app.table_definitions.items():
        schemas[tid] = StreamSchema(tid, "table", attrs_of(d), d)
    for wid, d in app.window_definitions.items():
        schemas[wid] = StreamSchema(wid, "window", attrs_of(d), d)
    for gid, d in app.trigger_definitions.items():
        # a trigger IS a stream of (triggered_time long) — core/trigger.py
        schemas[gid] = StreamSchema(
            gid, "trigger", {"triggered_time": AttributeType.LONG}, d)
    for aid, d in app.aggregation_definitions.items():
        schemas[aid] = StreamSchema(aid, "aggregation", None, d)
    return schemas


def _frames_for(node: QueryNode, plan: PlanGraph) -> dict[str, Optional[dict]]:
    """Reference-id → attrs frames for one query's expressions."""
    frames: dict[str, Optional[dict]] = {}
    for c in node.consumed:
        schema = plan.schemas.get(c.stream_id)
        attrs = None if schema is None else schema.attrs
        # stream functions (#fn) may rewrite the column set → open frame
        h = c.single.handlers
        if h.pre_window_functions or h.post_window_functions:
            attrs = None
        frames[c.single.alias or c.stream_id] = attrs
    # join `on` clauses may also address the underlying ids
    for c in node.consumed:
        if c.single.alias and c.stream_id not in frames:
            schema = plan.schemas.get(c.stream_id)
            frames[c.stream_id] = None if schema is None else schema.attrs
    return frames


def _output_schema(node: QueryNode, plan: PlanGraph) -> Optional[dict]:
    """Static select-list schema for an INSERT target (None = open)."""
    sel = node.query.selector
    frames = _frames_for(node, plan)
    if sel.is_select_all:
        if len(node.consumed) == 1:
            return frames.get(node.consumed[0].single.alias
                              or node.consumed[0].stream_id)
        return None  # join/pattern select *: runtime concatenation order
    typer = ExprTyper(frames)
    out: dict[str, Optional[AttributeType]] = {}
    for attr in sel.attributes:
        out[attr.rename] = typer.type_of(attr.expression)
    return out


def build_plan(app: SiddhiApp) -> PlanGraph:
    plan = PlanGraph(app=app, suppressions=Suppressions(app))
    plan.schemas = _declared_schemas(app)

    # collect queries (partition inners included) in source order
    idx = 0
    for element in app.execution_elements:
        if isinstance(element, Query):
            qs = [(element, None)]
        elif isinstance(element, Partition):
            qs = [(q, element) for q in element.queries]
        else:
            qs = []
        for q, part in qs:
            name = q.name or f"query_{idx}"
            plan.queries.append(QueryNode(
                query=q, name=name, explicit_name=q.name is not None,
                index=idx, partition=part, consumed=consumed_streams(q)))
            idx += 1

    # producer edges + derived schemas, iterated to a fixpoint so queries
    # may consume streams produced further down the file
    for node in plan.queries:
        out = node.query.output_stream
        if out.action.value == "insert" and out.target_id:
            node.produces = out.target_id
            node.produces_fault = out.is_fault
            plan.producers.setdefault(out.target_id, []).append(node)
        for c in node.consumed:
            plan.consumers.setdefault(c.stream_id, []).append(node)

    for _ in range(max(len(plan.queries), 1)):
        changed = False
        for node in plan.queries:
            target = node.produces
            if (not target or node.produces_fault
                    or target in plan.schemas):
                continue
            attrs = _output_schema(node, plan)
            plan.schemas[target] = StreamSchema(target, "derived", attrs)
            changed = True
        if not changed:
            break

    return plan


# ------------------------------------------------------------- fingerprints
#
# Structural fingerprints of the typed plan graph.  persistence.py stamps
# every revision with plan_fingerprint(app) so restore() can refuse a
# snapshot taken under a structurally different app, and core/upgrade.py
# uses element_fingerprints() to decide which state sections can migrate
# across an app version bump.  Element keys use the RUNTIME naming scheme
# (query{i+1}/partition{i+1} over app.queries/app.partitions — see
# SiddhiAppRuntime._build), not the analysis-side query_{idx} default, so
# the keys line up with the sections of a state snapshot.

import hashlib as _hashlib
from dataclasses import fields as _dc_fields, is_dataclass as _is_dataclass
from enum import Enum as _Enum


def _canon(obj) -> str:
    """Deterministic structural string for a query_api node. Source
    locations and annotations are excluded: moving a query down a line or
    adding @info must not change its identity."""
    if obj is None:
        return "~"
    if _is_dataclass(obj) and not isinstance(obj, type):
        parts = [type(obj).__name__]
        for f in _dc_fields(obj):
            if f.name in ("loc", "annotations"):
                continue
            parts.append(f"{f.name}={_canon(getattr(obj, f.name))}")
        return "(" + ",".join(parts) + ")"
    if isinstance(obj, _Enum):
        return f"E:{obj.value}"
    if isinstance(obj, dict):
        return "{" + ",".join(
            f"{_canon(k)}:{_canon(v)}" for k, v in sorted(
                obj.items(), key=lambda kv: str(kv[0]))) + "}"
    if isinstance(obj, (list, tuple)):
        return "[" + ",".join(_canon(x) for x in obj) + "]"
    if isinstance(obj, (str, int, float, bool, bytes)):
        return repr(obj)
    return repr(obj)


def _digest(text: str) -> str:
    return _hashlib.blake2b(text.encode("utf-8"), digest_size=12).hexdigest()


def element_fingerprints(app: SiddhiApp) -> dict[str, str]:
    """Per-element structural digests keyed the way runtime state snapshots
    key their sections: ``stream:<id>``, ``table:<id>``, ``window:<id>``,
    ``aggregation:<id>``, ``query:<name>`` (runtime default ``query{i+1}``),
    ``partition:partition{i+1}``."""
    fps: dict[str, str] = {}
    for sid, d in app.stream_definitions.items():
        attrs = tuple((a.name, a.type.value) for a in d.attributes)
        fps[f"stream:{sid}"] = _digest(f"{sid}|{attrs!r}")
    for tid, d in app.table_definitions.items():
        fps[f"table:{tid}"] = _digest(_canon(d))
    for wid, d in app.window_definitions.items():
        fps[f"window:{wid}"] = _digest(_canon(d))
    for aid, d in app.aggregation_definitions.items():
        fps[f"aggregation:{aid}"] = _digest(_canon(d))
    for i, q in enumerate(app.queries):
        qname = q.name or f"query{i + 1}"
        fps[f"query:{qname}"] = _digest(_canon(q))
    for i, p in enumerate(app.partitions):
        fps[f"partition:partition{i + 1}"] = _digest(_canon(p))
    return fps


def plan_fingerprint(app: SiddhiApp) -> str:
    """Whole-app structural fingerprint: folds every element digest plus the
    derived schemas of the typed plan graph, so any change that could alter
    state layout or query semantics produces a different value."""
    parts = [f"{k}={v}" for k, v in sorted(element_fingerprints(app).items())]
    try:
        plan = build_plan(app)
        for name in sorted(plan.schemas):
            s = plan.schemas[name]
            if s.attrs is None:
                parts.append(f"schema:{name}|{s.kind}|open")
            else:
                cols = tuple(
                    (a, t.value if t is not None else "?")
                    for a, t in s.attrs.items())
                parts.append(f"schema:{name}|{s.kind}|{cols!r}")
    except Exception:  # pragma: no cover - lowering must never block persist
        pass
    return _digest("\n".join(parts))
