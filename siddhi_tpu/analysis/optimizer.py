"""Multi-query shared-execution optimizer — the plan-level pass.

ROADMAP open item #1 (TiLT, arXiv 2301.12030; Factor Windows, arXiv
2008.12379): when many tenant queries sit on the same input stream, the
per-query cost model — one jitted step, one XLA compile ladder, one junction
delivery each — makes query count a linear cost. This module is the ANALYSIS
half of the fix: it decides, from the typed plan graph alone (no device
state, no tracing), which co-resident queries can share one compiled step,
which subexpressions they have in common, which predicates can be pushed
ahead of their windows, and which window aggregates are span-correlated.

The EXECUTION half lives in core/shared.py (`SharedStepGroup`,
`build_shared_groups`): member queries are traced together inside ONE
`jax.jit`, so XLA's own CSE realizes the shared scan / common-subexpression
rewrites this pass detects, while every member keeps its own state tuple,
callbacks, output wiring, and snapshot section — optimizer-on output is
bit-identical to optimizer-off (tests/test_optimizer_parity.py).

Both halves use the same decline taxonomy: a query that cannot join a
shared group (under @breaker, inside a partition, OBJECT-typed input,
table-dependent, ...) is declined LOUDLY — surfaced through lint rule SL114
and `statistics_report()["optimizer"]["declined"]` — never silently fused
with different isolation semantics.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Union

from ..query_api import SiddhiApp
from ..query_api.definition import AttributeType
from ..query_api.execution import Query, SingleInputStream
from .plan import PlanGraph, QueryNode, _canon, build_plan

#: window names whose ops/windows.py implementation consumes variable-lane
#: batches directly (shape_polymorphic=True) — mirrors rules.py SL113
_SHAPE_POLYMORPHIC_WINDOWS = {"time"}

#: decline reasons (shared taxonomy between the static pass and the runtime
#: group builder in core/shared.py)
DECLINE_BREAKER = "@breaker isolation: fusing would share failure fate"
DECLINE_PARTITION = "runs inside a partition (per-key isolation)"
DECLINE_OBJECT = "input stream carries OBJECT-typed attributes"
DECLINE_JOIN_PATTERN = "join/pattern input (multi-stream state machine)"
DECLINE_FAULT = "consumes a fault stream (!S)"
DECLINE_TABLE = "`in Table` dependency: table state is a step argument"
DECLINE_CUSTOM_AGG = ("custom aggregator state (distinctCount pair table) "
                      "needs host-side compaction between steps")

#: splice-specific decline reasons (core/shared.py splice_in): a query can
#: be fusion-eligible in general yet unspliceable into one concrete group
SPLICE_DECLINE_NO_GROUP = ("no live fused group on the input stream to "
                           "splice into")
SPLICE_DECLINE_SHAPE = ("batch capacity differs from the group's traced "
                        "shape (would force a full ladder rebuild)")
SPLICE_DECLINE_CAP = "group already at SIDDHI_OPTIMIZE_GROUP_CAP members"


def optimizer_enabled(app: SiddhiApp,
                      override: Optional[bool] = None) -> bool:
    """Opt-in gate: `@app:optimize` on the app (element 'false'/'0'
    disables), the SIDDHI_OPTIMIZE env var, or an explicit runtime kwarg
    (which wins over both)."""
    if override is not None:
        return bool(override)
    ann = app.annotation("app:optimize")
    if ann is not None:
        val = str(ann.element() or "true").strip().lower()
        return val not in ("false", "0", "off")
    return os.environ.get("SIDDHI_OPTIMIZE", "") not in ("", "0")


@dataclass
class FusionGroup:
    """One set of co-resident queries that can share a compiled step."""

    stream_id: str
    #: runtime-style query names (query{i+1} / @info name), source order
    members: list[str]
    #: plan nodes for lint anchoring (parallel to `members`)
    nodes: list[QueryNode] = field(default_factory=list)
    #: number of duplicated filter/projection/group-key subexpressions the
    #: members share (each computed once per batch under fusion)
    shared_subexpressions: int = 0
    #: post-window filters provably safe to evaluate ahead of the window
    pushdowns: int = 0
    #: span-correlated window aggregates (same stream + group key, different
    #: window parameters) whose scans collapse into the one traced step
    pane_candidates: int = 0
    #: True when every member's step is shape-polymorphic (the fused step
    #: compiles once per lane bucket instead of once per member per bucket)
    shape_polymorphic: bool = True

    @property
    def steps_saved(self) -> int:
        """Junction deliveries (and compiles, per shape bucket) saved per
        batch: N member dispatches become one."""
        return max(len(self.members) - 1, 0)


@dataclass
class OptimizerReport:
    """What the pass found (or would find, when the optimizer is off)."""

    enabled: bool = False
    groups: list[FusionGroup] = field(default_factory=list)
    #: runtime-style query name -> decline reason (only for queries whose
    #: stream hosts other fusable work — a lone query declines nothing)
    declined: dict[str, str] = field(default_factory=dict)
    #: decline reasons for lint anchoring: (node, reason)
    declined_nodes: list[tuple] = field(default_factory=list)

    @property
    def queries_fused(self) -> int:
        return sum(len(g.members) for g in self.groups)

    @property
    def cse_hits(self) -> int:
        return sum(g.shared_subexpressions for g in self.groups)

    @property
    def pushdowns(self) -> int:
        return sum(g.pushdowns for g in self.groups)

    @property
    def pane_candidates(self) -> int:
        return sum(g.pane_candidates for g in self.groups)

    def summary(self) -> dict:
        return {
            "enabled": self.enabled,
            "groups": len(self.groups),
            "queries_fused": self.queries_fused,
            "cse_hits": self.cse_hits,
            "pushdowns": self.pushdowns,
            "pane_candidates": self.pane_candidates,
            "declined": dict(self.declined),
            "group_members": {g.stream_id: list(g.members)
                              for g in self.groups},
        }


# ------------------------------------------------------------- eligibility


def _has_annotation(query: Query, name: str) -> bool:
    return any(a.name.lower() == name for a in query.annotations or ())


def decline_reason(node: QueryNode, plan: PlanGraph) -> Optional[str]:
    """Why this query cannot join a shared group (None = eligible). The
    runtime builder re-checks the runtime-only facts (custom aggregator
    state, table fallbacks); everything statically decidable is here so
    SL114 reports the same reasons `statistics_report()` will."""
    if node.partition is not None:
        return DECLINE_PARTITION
    ins = node.query.input_stream
    if not isinstance(ins, SingleInputStream):
        return DECLINE_JOIN_PATTERN
    if ins.is_fault:
        return DECLINE_FAULT
    if _has_annotation(node.query, "breaker"):
        return DECLINE_BREAKER
    schema = plan.schemas.get(ins.stream_id)
    if schema is not None and schema.attrs is not None and any(
            t == AttributeType.OBJECT for t in schema.attrs.values()):
        return DECLINE_OBJECT
    from ..core.query_runtime import _collect_in_sources
    tables = set(plan.app.table_definitions)
    if _collect_in_sources(node.query) & tables:
        return DECLINE_TABLE
    return None


def _shape_polymorphic(node: QueryNode) -> bool:
    """Static mirror of QueryRuntime._bucket_ok (window side only — the
    extrema-plan check is runtime-only and re-applied by core/shared.py)."""
    w = node.query.input_stream.handlers.window
    if w is None:
        return True
    if w.name in _SHAPE_POLYMORPHIC_WINDOWS:
        return True
    return w.name == "batch" and not w.parameters


def _runtime_names(plan: PlanGraph) -> dict[int, str]:
    """node.index -> the RUNTIME query name (query{i+1} over app.queries,
    matching SiddhiAppRuntime._build / element_fingerprints)."""
    names: dict[int, str] = {}
    top = 0
    for node in plan.queries:
        if node.partition is not None:
            names[node.index] = node.query.name or node.name
            continue
        top += 1
        names[node.index] = node.query.name or f"query{top}"
    return names


# ----------------------------------------------------------------- analysis


def _member_expr_canons(node: QueryNode) -> list[str]:
    """Canonical forms of the subexpressions a fused step would evaluate per
    batch: filters, post-window filters, select projections, group keys."""
    out: list[str] = []
    h = node.query.input_stream.handlers
    for f in (*h.filters, *h.post_window_filters):
        out.append(_canon(f))
    sel = node.query.selector
    for a in sel.attributes:
        out.append(_canon(a.expression))
    for v in sel.group_by:
        out.append(_canon(v))
    return out


def _count_pushdowns(node: QueryNode) -> int:
    """Post-window filters that are provably pushable: the query's window
    lowers to pass-through (none, or paramless #window.batch — every
    surviving arrival is emitted as CURRENT, so filtering after equals
    filtering before) and there are no stream functions whose computed
    columns the filter could read. This is the rewrite core/shared.py
    applies in place."""
    h = node.query.input_stream.handlers
    w = h.window
    passthrough = w is None or (not w.namespace and w.name == "batch"
                                and not w.parameters)
    if not passthrough:
        return 0
    if h.pre_window_functions or h.post_window_functions:
        return 0
    return len(h.post_window_filters)


def _count_pane_candidates(nodes: list[QueryNode]) -> int:
    """Span-correlated window aggregates: members whose windows differ only
    in parameters (e.g. time(1 min) / time(5 min) / time(1 hour)) over the
    same group key. Under trace-together fusion their scans run in one
    compiled step; true factor-pane state sharing is declined for float
    aggregates (non-associative addition breaks bit-parity — see
    docs/OPTIMIZER.md)."""
    sigs: dict[tuple, int] = {}
    for node in nodes:
        w = node.query.input_stream.handlers.window
        if w is None:
            continue
        sel = node.query.selector
        key = (w.namespace, w.name,
               tuple(sorted(_canon(v) for v in sel.group_by)))
        sigs[key] = sigs.get(key, 0) + 1
    return sum(n for n in sigs.values() if n >= 2)


def analyze_sharing(app_or_plan: Union[SiddhiApp, PlanGraph],
                    enabled: Optional[bool] = None) -> OptimizerReport:
    """The full static pass: group co-resident eligible queries per input
    stream, count shared subexpressions (via plan.py's structural _canon),
    pushdown opportunities, and span-correlated windows. Pure analysis —
    costs microseconds, never builds device state."""
    plan = (app_or_plan if isinstance(app_or_plan, PlanGraph)
            else build_plan(app_or_plan))
    report = OptimizerReport(
        enabled=optimizer_enabled(plan.app) if enabled is None else enabled)
    names = _runtime_names(plan)

    by_stream: dict[str, list[QueryNode]] = {}
    declined: list[tuple[QueryNode, str]] = []
    consumers: dict[str, int] = {}
    for node in plan.queries:
        ins = node.query.input_stream
        sid = getattr(ins, "stream_id", None) if isinstance(
            ins, SingleInputStream) else None
        if sid is not None:
            consumers[sid] = consumers.get(sid, 0) + 1
        reason = decline_reason(node, plan)
        if reason is not None:
            declined.append((node, reason))
            continue
        by_stream.setdefault(sid, []).append(node)

    for sid, nodes in by_stream.items():
        if len(nodes) < 2:
            continue
        canons: dict[str, int] = {}
        for node in nodes:
            for c in _member_expr_canons(node):
                canons[c] = canons.get(c, 0) + 1
        group = FusionGroup(
            stream_id=sid,
            members=[names[n.index] for n in nodes],
            nodes=list(nodes),
            shared_subexpressions=sum(
                n - 1 for n in canons.values() if n > 1),
            pushdowns=sum(_count_pushdowns(n) for n in nodes),
            pane_candidates=_count_pane_candidates(nodes),
            # mixed groups pad to full capacity (the shape-baked members'
            # own dispatch behavior); all-polymorphic groups keep buckets
            shape_polymorphic=all(_shape_polymorphic(n) for n in nodes),
        )
        report.groups.append(group)

    # a decline is only worth reporting when sharing was actually forgone:
    # the declined query's stream hosts at least one other consumer
    for node, reason in declined:
        ins = node.query.input_stream
        sid = getattr(ins, "stream_id", None)
        consumed = [c.stream_id for c in node.consumed]
        if any(consumers.get(s, 0) >= 2 for s in ([sid] if sid else consumed)):
            report.declined[names[node.index]] = reason
            report.declined_nodes.append((node, reason))
    return report
