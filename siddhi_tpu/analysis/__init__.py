"""siddhi_tpu.analysis — static query-plan analyzer + jaxpr hazard linter.

`analyze(app)` lowers a SiddhiApp (or SiddhiQL text) into a typed plan graph
(plan.py) and runs the SL1xx rule catalog (rules.py) over it — no device
state is planned, so the static pass costs milliseconds. With `jaxpr=True`
it additionally builds a sandbox runtime and walks each compiled step's
jaxpr for host-sync / dtype hazards (jaxpr_pass.py, SL2xx).

Surfaces: `SiddhiManager.validate(app)`, the SIDDHI_LINT startup gate,
`python -m siddhi_tpu.lint`, and REST `POST /siddhi-apps/validate` all call
`analyze()`; docs/LINT.md is the user-facing rule reference.
"""

from __future__ import annotations

import logging
from typing import Optional, Union

from .concurrency import lint_package, lint_python_source
from .cost import (
    Budget,
    CostReport,
    ElementCost,
    app_budget,
    compute_cost,
    cost_for_plan,
    format_size,
    measure_runtime_state_bytes,
    parse_size,
)
from .diagnostics import Diagnostic, LintReport, Severity, Suppressions
from .optimizer import OptimizerReport, analyze_sharing, optimizer_enabled
from .plan import PlanGraph, build_plan, element_fingerprints, plan_fingerprint
from .rules import RULES, run_rules
from .sharding import (
    ShardClass,
    ShardConfig,
    check_shardable,
    classify_plan,
    shard_config,
    shard_violations,
)
from .upgrade import UPGRADE_RULES, UpgradeDiff, diff_apps

log = logging.getLogger("siddhi_tpu.lint")

__all__ = [
    "Diagnostic", "LintReport", "Severity", "Suppressions",
    "PlanGraph", "build_plan", "RULES", "analyze", "lint_mode",
    "element_fingerprints", "plan_fingerprint",
    "UPGRADE_RULES", "UpgradeDiff", "diff_apps",
    "OptimizerReport", "analyze_sharing", "optimizer_enabled",
    "lint_package", "lint_python_source",
    "Budget", "CostReport", "ElementCost", "app_budget", "compute_cost",
    "cost_for_plan", "format_size", "measure_runtime_state_bytes",
    "parse_size",
    "ShardClass", "ShardConfig", "check_shardable", "classify_plan",
    "shard_config", "shard_violations",
]


def analyze(app: Union[str, "object"], *, jaxpr: bool = False,
            name: Optional[str] = None) -> LintReport:
    """Lint one app. `app` is a SiddhiApp or SiddhiQL source text (parse
    errors propagate as SiddhiParserError — callers that need them as
    diagnostics catch and wrap, see siddhi_tpu/lint.py).

    The static pass never raises; the optional jaxpr pass is best-effort
    (queries it cannot trace are skipped)."""
    if isinstance(app, str):
        from ..compiler import SiddhiCompiler
        app = SiddhiCompiler.parse(app)
    report = LintReport(app_name=name or getattr(app, "name", None)
                        or "SiddhiApp")
    plan = build_plan(app)
    run_rules(plan, report)
    try:
        report.cost = cost_for_plan(plan).to_dict()
    except Exception:  # the cost pass is advisory — never fail a lint on it
        log.debug("cost pass crashed", exc_info=True)
    if jaxpr:
        from .jaxpr_pass import run_jaxpr_pass
        run_jaxpr_pass(app, report, plan.suppressions)
    return report


def lint_mode() -> str:
    """The SIDDHI_LINT startup gate: 'error' | 'warn' (default) | 'off'."""
    import os

    mode = os.environ.get("SIDDHI_LINT", "warn").strip().lower()
    if mode not in ("error", "warn", "off"):
        return "warn"
    return mode
