"""Static capacity cost model (the SL5xx certification substrate).

Walks the plan graph (analysis/plan.py) and predicts, per element and per
app, WITHOUT building a runtime or allocating any device state:

- **state bytes** — the device-resident footprint each element's
  ``init_state()`` would allocate: window ring packs (ops/windows.py), join
  stores + hash multimaps (core/join_runtime.py), NFA pending tables
  (core/pattern_runtime.py), group-by/aggregation tables, rate-limiter
  rings. The prediction is byte-exact where the schema is closed: the model
  constructs the SAME operator objects the runtime would (window factories,
  CompiledSelector, rate limiters — all allocation-free constructors) and
  sizes their state under ``jax.eval_shape``, so formula drift is
  structurally impossible.
- **compile-ladder size** — executables XLA would compile across shape
  buckets x queries x steps (join directions, pattern per-stream steps +
  heartbeat), respecting SharedStepGroup fusion (analysis/optimizer.py)
  when the multi-query optimizer is enabled.
- **dispatch class** — whether the per-batch step stays on device
  (``device``), amortizes its dispatch over a K-batch superstep scan
  (``superstep``, core/superstep.py: dispatches-per-event divided by K),
  or takes a host callback hop (``host`` — today only the deprecated
  ``SIDDHI_RADIX_CALLBACK=1`` escape hatch; the packed-key device sort
  retired the CPU radix pure_callback, ops/search.py).

Enforcement rides on top: `app_budget` reads ``@app:budget(state=,
compiles=)`` / ``SIDDHI_STATE_BUDGET`` / ``SIDDHI_COMPILE_BUDGET`` and
`SiddhiManager.create_siddhi_app_runtime` refuses (or, with
``SIDDHI_BUDGET_MODE=queue``, defers) over-budget apps before any device
state exists. `tools/cost_calibrate.py` holds predictions within a 2x band
of live telemetry. Rules SL501-SL505 (analysis/rules.py) surface the model
through lint; docs/COST.md documents the formulas.

The model is deliberately conservative about what it cannot see: open
schemas (stream functions, untypeable columns) and host-side structures
(record-table stores, event-time reorder buffers) degrade to notes with
``exact=False`` instead of guesses, so the budget gate under-reports
rather than refusing working apps (the zero-FP sweep holds the line).
"""

from __future__ import annotations

import math
import os
import re
from dataclasses import dataclass, field
from typing import Optional, Union

from ..query_api import SiddhiApp
from ..query_api.definition import AttributeType
from ..query_api.execution import (
    JoinInputStream,
    OutputEventType,
    OutputRateType,
    StateInputStream,
)
from .plan import ExprTyper, PlanGraph, QueryNode, _frames_for, build_plan

__all__ = [
    "Budget", "CostReport", "ElementCost", "app_budget", "compute_cost",
    "cost_for_plan", "format_size", "measure_runtime_state_bytes",
    "parse_size", "price_splice", "superstep_k",
]

_SIZE_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)\s*(b|kb|kib|mb|mib|gb|gib|tb|tib)?\s*$", re.I)
_SIZE_UNITS = {
    None: 1, "b": 1,
    "kb": 1024, "kib": 1024,
    "mb": 1024 ** 2, "mib": 1024 ** 2,
    "gb": 1024 ** 3, "gib": 1024 ** 3,
    "tb": 1024 ** 4, "tib": 1024 ** 4,
}


def parse_size(text: Union[str, int]) -> int:
    """'512MB' / '1.5GiB' / '65536' -> bytes (power-of-two units)."""
    if isinstance(text, int):
        return text
    m = _SIZE_RE.match(str(text))
    if not m:
        raise ValueError(f"unparseable size {text!r} (try '512MB', '2GiB')")
    val, unit = m.groups()
    return int(float(val) * _SIZE_UNITS[unit.lower() if unit else None])


def format_size(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"  # pragma: no cover


@dataclass
class Budget:
    """Resolved capacity budget for one app (annotation and/or env)."""

    state_bytes: Optional[int] = None
    compiles: Optional[int] = None
    #: "error" refuses over-budget apps at creation; "queue" defers them to
    #: SiddhiManager.pending_apps for later admission
    mode: str = "error"
    source: str = "env"

    def to_dict(self) -> dict:
        return {"state_bytes": self.state_bytes, "compiles": self.compiles,
                "mode": self.mode, "source": self.source}


def app_budget(app: Optional[SiddhiApp]) -> Optional[Budget]:
    """``@app:budget(state='512MB', compiles='64')`` merged over the
    ``SIDDHI_STATE_BUDGET`` / ``SIDDHI_COMPILE_BUDGET`` env (annotation
    wins per field). Returns None when no budget is configured anywhere."""
    state = compiles = None
    sources = []
    env_state = os.environ.get("SIDDHI_STATE_BUDGET", "").strip()
    env_compiles = os.environ.get("SIDDHI_COMPILE_BUDGET", "").strip()
    if env_state:
        state = parse_size(env_state)
        sources.append("env")
    if env_compiles:
        compiles = int(env_compiles)
        if "env" not in sources:
            sources.append("env")
    ann = app.annotation("app:budget") if app is not None else None
    if ann is not None:
        s = ann.element("state")
        c = ann.element("compiles")
        if s:
            state = parse_size(s)
        if c:
            compiles = int(c)
        sources.insert(0, "annotation")
    if state is None and compiles is None:
        return None
    mode = os.environ.get("SIDDHI_BUDGET_MODE", "error").strip().lower()
    if mode not in ("error", "queue"):
        mode = "error"
    return Budget(state_bytes=state, compiles=compiles, mode=mode,
                  source="+".join(sources) or "env")


@dataclass
class ElementCost:
    """Predicted footprint of ONE runtime element (query or definition)."""

    element: str
    kind: str  # query | join | pattern | window | table | aggregation
    state_bytes: int = 0
    compiles: int = 0
    dispatch: str = "device"  # device | host
    #: byte-exact (closed schema, operator-mirrored) vs degraded estimate
    exact: bool = True
    notes: list = field(default_factory=list)
    #: plan node index for lint anchoring (queries only)
    node_index: Optional[int] = None
    #: mirrors QueryRuntime._bucket_ok (fusion-group ladder math)
    bucket_ok: bool = False

    def to_dict(self) -> dict:
        return {"element": self.element, "kind": self.kind,
                "state_bytes": self.state_bytes, "compiles": self.compiles,
                "dispatch": self.dispatch, "exact": self.exact,
                "notes": list(self.notes)}


@dataclass
class CostReport:
    """Whole-app prediction: per-element costs + the admission totals."""

    app_name: str
    state_bytes: int = 0
    compile_ladder: int = 0
    elements: list = field(default_factory=list)
    dominant: Optional[ElementCost] = None
    budget: Optional[Budget] = None
    exact: bool = True
    notes: list = field(default_factory=list)
    #: fused-group ladder summary when the optimizer is enabled:
    #: [{"stream": sid, "members": [...], "compiles": rungs}]
    fusion: list = field(default_factory=list)
    #: resolved @app:superstep(k=) / SIDDHI_SUPERSTEP_K depth (1 = per-batch)
    superstep_k: int = 1

    @property
    def dominant_share(self) -> float:
        if self.dominant is None or self.state_bytes <= 0:
            return 0.0
        return self.dominant.state_bytes / self.state_bytes

    def to_dict(self) -> dict:
        return {
            "app": self.app_name,
            "predicted_state_bytes": self.state_bytes,
            "predicted_compiles": self.compile_ladder,
            "exact": self.exact,
            "dominant": (None if self.dominant is None else {
                "element": self.dominant.element,
                "state_bytes": self.dominant.state_bytes,
                "share": round(self.dominant_share, 4)}),
            "budget": None if self.budget is None else self.budget.to_dict(),
            "elements": [e.to_dict() for e in self.elements],
            "fusion": list(self.fusion),
            "superstep_k": self.superstep_k,
            "notes": list(self.notes),
        }


# --------------------------------------------------------------------------
# sizing primitives
# --------------------------------------------------------------------------


def _tree_bytes(tree) -> int:
    """Bytes across a pytree of arrays / ShapeDtypeStructs."""
    import jax
    import numpy as np
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(math.prod(shape)) * np.dtype(dtype).itemsize
    return total


def _eval_state_bytes(fn) -> int:
    """Size ``fn()``'s pytree WITHOUT allocating: abstract evaluation only.

    Every operator ``init_state`` in this tree is pure jnp.zeros/full
    construction, so eval_shape sees the exact arrays a real call returns.
    """
    import jax
    return _tree_bytes(jax.eval_shape(fn))


def _itemsize(t: AttributeType) -> int:
    import numpy as np
    from ..core import dtypes
    return np.dtype(dtypes.device_dtype(t)).itemsize


def _radix_min() -> int:
    from ..ops.search import _radix_min_lanes
    return _radix_min_lanes()


def _legacy_radix_callback() -> bool:
    from ..ops.search import _legacy_callback_enabled
    return _legacy_callback_enabled()


def superstep_k(app: Optional[SiddhiApp]) -> int:
    """Resolved superstep depth for an app: ``@app:superstep(k=)`` with the
    ``SIDDHI_SUPERSTEP_K`` env overriding (same precedence the runtime
    applies in core/app_runtime.py). 1 = per-batch dispatch."""
    k = 1
    ann = app.annotation("app:superstep") if app is not None else None
    if ann is not None:
        v = ann.element("k") or ann.element()
        try:
            k = int(v) if v else 1
        except ValueError:
            k = 1
    env_k = os.environ.get("SIDDHI_SUPERSTEP_K", "").strip()
    if env_k:
        try:
            k = int(env_k)
        except ValueError:
            pass
    return max(1, k)


def _closed(attrs: Optional[dict]) -> Optional[dict]:
    """A frame usable for byte-exact construction: present, no untypeable
    columns, no host-only OBJECT columns left after filtering."""
    if attrs is None or any(t is None for t in attrs.values()):
        return None
    return {n: t for n, t in attrs.items() if t != AttributeType.OBJECT}


def _ladder_rungs(batch_cap: int) -> int:
    from ..core import dtypes
    return len(dtypes.bucket_ladder(batch_cap))


def _make_window(handlers_window, layout, batch_cap: int, expired_on: bool,
                 registry):
    """Mirror of the runtime window construction (allocation-free)."""
    from ..core.query_runtime import eval_constant
    from ..extension.registry import ExtensionKind
    from ..ops.window_factories import WindowFactory
    from ..ops.windows import PassThroughWindow
    if handlers_window is None:
        return PassThroughWindow(layout, batch_cap)
    factory = registry.require(ExtensionKind.WINDOW, handlers_window.namespace,
                               handlers_window.name)
    assert isinstance(factory, WindowFactory)
    params = [eval_constant(p) for p in handlers_window.parameters]
    registry.validate_params(ExtensionKind.WINDOW, handlers_window.namespace,
                             handlers_window.name, params, what="window")
    return factory.make(layout, batch_cap, params, expired_on)


# --------------------------------------------------------------------------
# per-element models
# --------------------------------------------------------------------------


def _single_query_cost(node: QueryNode, plan: PlanGraph, registry,
                       batch_cap: int, group_cap: int,
                       name: str) -> ElementCost:
    from ..core import dtypes
    from ..core.query_runtime import _selects_aggregates
    from ..ops.expr_compile import TypeResolver
    from ..ops.ratelimit import make_rate_limiter
    from ..ops.selector import CompiledSelector
    from ..ops.windows import (LengthBatchWindow, PassThroughWindow,
                               SlidingWindow, TimeBatchWindow, WindowOp,
                               make_layout)

    ec = ElementCost(name, "query", node_index=node.index)
    c = node.consumed[0]
    frames = _frames_for(node, plan)
    frame_ref = c.single.alias or c.stream_id
    attrs = _closed(frames.get(frame_ref))
    if attrs is None:
        ec.exact = False
        ec.notes.append("open schema (stream functions or untypeable "
                        "columns): state not statically derivable")
        ec.compiles = 1
        return ec

    query = node.query
    layout = make_layout(attrs)
    expired_on = query.output_stream.event_type != OutputEventType.CURRENT
    selects_aggs = _selects_aggregates(query.selector, registry)
    snapshot_full = (query.output_rate is not None
                     and query.output_rate.type == OutputRateType.SNAPSHOT
                     and not selects_aggs)
    if snapshot_full:
        expired_on = True
    window = _make_window(c.single.handlers.window, layout, batch_cap,
                          expired_on, registry)
    is_sliding = c.single.handlers.window is not None and \
        type(window).__name__ in ("SlidingWindow", "ExpressionWindow",
                                  "GeneralExpressionWindow")

    resolver = TypeResolver(
        {r: f for r, f in frames.items() if _closed(f) is not None},
        frame_ref)
    select_all = list(attrs.items())
    selector = CompiledSelector(
        query.selector, resolver, registry, group_cap, frame_ref,
        select_all_attrs=select_all, sliding_window=is_sliding)

    out_layout = {n: dtypes.device_dtype(t)
                  for n, t in selector.out_types.items()
                  if t != AttributeType.OBJECT}
    fifo = isinstance(window,
                      (SlidingWindow, LengthBatchWindow, TimeBatchWindow))
    findable = type(window).contents is not WindowOp.contents \
        and not isinstance(window, PassThroughWindow)
    limiter = make_rate_limiter(
        query.output_rate, out_layout, window.chunk_width,
        grouped=bool(query.selector.group_by),
        group_capacity=group_cap,
        fifo_window=fifo and snapshot_full,
        has_aggregates=selects_aggs,
        window_capacity=getattr(window, "C", 0),
        contents_window=findable and snapshot_full)

    ec.state_bytes = _eval_state_bytes(
        lambda: (window.init_state(), selector.init_state(),
                 limiter.init_state()))
    ec.bucket_ok = bool(window.shape_polymorphic
                        and not selector.extrema_plan)
    ec.compiles = (_ladder_rungs(batch_cap)
                   if ec.bucket_ok and dtypes.config.shape_buckets else 1)
    grouped_or_custom = bool(selector.group_vars) or any(
        spec.custom_scan is not None for _, spec, _ in selector.agg_specs)
    if (selector.has_aggregators and grouped_or_custom
            and window.chunk_width >= _radix_min()
            and _legacy_radix_callback()):
        ec.dispatch = "host"
        ec.notes.append(
            f"group-key radix argsort over {window.chunk_width} lanes runs "
            "as a host callback (SIDDHI_RADIX_CALLBACK=1 legacy escape "
            "hatch; pjit fastpath veto, ops/search.py)")
    return ec


def _join_query_cost(node: QueryNode, plan: PlanGraph, registry,
                     batch_cap: int, group_cap: int,
                     name: str) -> ElementCost:
    from ..ops.expr_compile import TypeResolver
    from ..ops.join import multimap_buckets, plan_join
    from ..ops.selector import CompiledSelector
    from ..ops.windows import SlidingWindow, make_layout
    from ..query_api.execution import EventTrigger

    ec = ElementCost(name, "join", node_index=node.index)
    jis: JoinInputStream = node.query.input_stream

    sides = []  # (ins, ref, kind, attrs, window-or-None)
    for ins in (jis.left, jis.right):
        ref = ins.alias or ins.stream_id
        schema = plan.schemas.get(ins.stream_id)
        kind = schema.kind if schema is not None else "stream"
        attrs = _closed(schema.attrs) if schema is not None else None
        if attrs is None:
            ec.exact = False
            ec.notes.append(f"side {ins.stream_id!r}: open schema")
            sides.append((ins, ref, kind, None, None))
            continue
        window = None
        if kind not in ("table", "window", "aggregation"):
            # stream side: its own ring; store-backed sides are priced
            # under their OWN elements (shared state, counted once)
            layout = make_layout(attrs)
            window = _make_window(ins.handlers.window, layout, batch_cap,
                                  True, registry)
        sides.append((ins, ref, kind, attrs, window))

    (lins, lref, lkind, lattrs, lwin), (rins, rref, rkind, rattrs, rwin) = sides
    frames = {ref: attrs for _, ref, _, attrs, _ in sides
              if attrs is not None}
    resolver = TypeResolver(frames, lref)

    state_parts = []
    mm_specs = []  # (C, H) per hashable build side
    if lattrs is not None and rattrs is not None and jis.on is not None:
        plan_from_left = plan_join(jis.on, lref, rref, resolver, registry)
        plan_from_right = plan_join(jis.on, rref, lref, resolver, registry)
        for win, plan_as_build in ((lwin, plan_from_right),
                                   (rwin, plan_from_left)):
            if isinstance(win, SlidingWindow) and plan_as_build.probe_keys:
                mm_specs.append((win.C, multimap_buckets(win.C)))
        probe_keys = bool(plan_from_left.probe_keys
                          or plan_from_right.probe_keys)
    else:
        plan_from_left = plan_from_right = None
        probe_keys = False

    for win in (lwin, rwin):
        if win is not None:
            state_parts.append(win.init_state)
    if lattrs is not None and rattrs is not None:
        select_all = list(lattrs.items())
        for n, t in rattrs.items():
            if n not in dict(select_all):
                select_all.append((n, t))
        selector = CompiledSelector(
            node.query.selector, resolver, registry, group_cap, lref,
            select_all_attrs=select_all)
        state_parts.append(selector.init_state)
    else:
        selector = None

    def build_state():
        from ..ops.join import multimap_init
        parts = [p() for p in state_parts]
        for cap, buckets in mm_specs:
            parts.append(multimap_init(cap, buckets))
        return tuple(parts)

    ec.state_bytes = _eval_state_bytes(build_state)

    # compiles: one executable per triggering junction-fed probe direction
    # (join steps always run at full batch capacity — no ladder)
    for side_kind, from_left in ((lkind, True), (rkind, False)):
        if side_kind in ("table", "aggregation"):
            continue  # no junction feeds this direction
        triggers = (jis.trigger == EventTrigger.ALL
                    or (jis.trigger == EventTrigger.LEFT and from_left)
                    or (jis.trigger == EventTrigger.RIGHT and not from_left))
        if triggers:
            ec.compiles += 1

    build_caps = [getattr(w, "C", 0) for w in (lwin, rwin) if w is not None]
    if (probe_keys and build_caps and max(build_caps) >= _radix_min()
            and _legacy_radix_callback()):
        ec.dispatch = "host"
        ec.notes.append(
            "equi-join build-side indexing radix-sorts "
            f"{max(build_caps)} ring lanes via a host callback "
            "(SIDDHI_RADIX_CALLBACK=1 legacy escape hatch)")
    return ec


def _pattern_query_cost(node: QueryNode, plan: PlanGraph, registry,
                        batch_cap: int, group_cap: int,
                        name: str) -> ElementCost:
    import dataclasses as dc

    from ..core import dtypes
    from ..core.pattern_runtime import _PatternPlan, _RefRewriter
    from ..ops.expr_compile import TypeResolver
    from ..ops.selector import CompiledSelector

    ec = ElementCost(name, "pattern", node_index=node.index)
    sis: StateInputStream = node.query.input_stream
    pplan = _PatternPlan(sis, None)
    P = dtypes.config.pattern_pending_capacity

    ref_types: dict[str, dict] = {}
    for pos in pplan.positions:
        for leg in pos.legs:
            schema = plan.schemas.get(leg.stream_id)
            attrs = _closed(schema.attrs) if schema is not None else None
            if attrs is None:
                ec.exact = False
                ec.notes.append(f"leg {leg.stream_id!r}: open schema")
                ec.compiles = 1
                return ec
            ref_types[leg.ref] = attrs

    # --- pending tables (mirror of PatternQueryRuntime._empty_pending) ---
    def captured_refs(pos_index: int) -> list:
        refs = []
        for pos in pplan.positions[:pos_index]:
            for leg in pos.legs:
                refs.append(leg.ref)
        pos = pplan.positions[pos_index]
        if pos.kind == "logical" or (pos.kind == "notand"
                                     and pos.wait_ms is not None):
            for leg in pos.legs:
                refs.append(leg.ref)
        return refs

    total = 0
    for pos_index in range(1, len(pplan.positions)):
        for ref in captured_refs(pos_index):
            total += sum(P * _itemsize(t) for t in ref_types[ref].values())
            total += P * (1 + 8)  # frame_valid + frame_ts
        # start_ts/last_seq/armed_ts (int64) + valid + leg_done[P,2] + origin
        total += P * (8 + 8 + 8 + 1 + 2 + 4)
    total += 1 + 8 + 8 + 8 + 8  # active0/seq/dropped/armed0_ts/gate0_seq
    ec.state_bytes = total

    # --- selector over captured frames (rewritten refs, like the runtime) --
    frames = dict(ref_types)
    sid_count: dict[str, int] = {}
    for pos in pplan.positions:
        for leg in pos.legs:
            sid_count[leg.stream_id] = sid_count.get(leg.stream_id, 0) + 1
    for pos in pplan.positions:
        for leg in pos.legs:
            if sid_count[leg.stream_id] == 1 and leg.stream_id not in frames:
                frames[leg.stream_id] = ref_types[leg.ref]
    first_ref = pplan.positions[0].legs[0].ref
    resolver = TypeResolver(frames, first_ref)
    rewriter = _RefRewriter(pplan.count_groups)
    sel = node.query.selector
    sel = dc.replace(
        sel,
        attributes=tuple(
            dc.replace(a, expression=rewriter.rewrite(a.expression))
            for a in sel.attributes),
        having=rewriter.rewrite(sel.having),
        group_by=tuple(rewriter.rewrite(g) for g in sel.group_by))
    select_all, seen = [], set()
    for pos in pplan.positions:
        for leg in pos.legs:
            for n, t in ref_types[leg.ref].items():
                if n not in seen:
                    seen.add(n)
                    select_all.append((n, t))
    selector = CompiledSelector(sel, resolver, registry, group_cap,
                                first_ref, select_all_attrs=select_all)
    ec.state_bytes += _eval_state_bytes(selector.init_state)

    # --- compiles: per-junction steps + the timed heartbeat ---
    sids = {leg.stream_id for pos in pplan.positions for leg in pos.legs}
    merged = pplan.is_sequence and len(sids) > 1
    ec.compiles = 1 if merged else len(sids)
    timed = (pplan.within_ms is not None
             or (pplan.head_group is not None
                 and pplan.head_group.within_ms is not None)
             or any(p.kind == "absent"
                    or (p.kind == "notand" and p.wait_ms is not None)
                    for p in pplan.positions))
    if timed:
        ec.compiles += 1
    return ec


def _named_window_cost(name: str, defn, registry,
                       batch_cap: int) -> ElementCost:
    from ..ops.windows import make_layout

    ec = ElementCost(name, "window")
    attrs = _closed({a.name: a.type for a in defn.attributes})
    if attrs is None:
        ec.exact = False
        ec.notes.append("open schema")
        return ec
    layout = make_layout(attrs)
    window = _make_window(getattr(defn, "window", None), layout, batch_cap,
                          True, registry)
    ec.state_bytes = _eval_state_bytes(window.init_state)
    if getattr(defn, "window", None) is None:
        ec.notes.append("no window spec: pass-through emission, no "
                        "retained contents")
    ec.notes.append("append step compiles once (untracked jit)")
    return ec


def _table_cost(name: str, defn, group_cap: int) -> ElementCost:
    from ..core import dtypes

    ec = ElementCost(name, "table")
    if defn.annotations and defn.annotation("store") is not None:
        ec.exact = False
        ec.notes.append("@store record table: rows live host-side (only "
                        "the device cache would count; not modeled)")
        return ec
    cap_ann = defn.annotation("capacity") if defn.annotations else None
    cap = (int(cap_ann.element(None))
           if cap_ann is not None and cap_ann.element(None)
           else dtypes.config.default_table_capacity)
    attrs = {a.name: a.type for a in defn.attributes
             if a.type != AttributeType.OBJECT}
    if any(t is None for t in attrs.values()):
        ec.exact = False
        ec.notes.append("untypeable columns")
        return ec
    # TableState: cols + ts int64[C] + valid bool[C]  (core/table.py)
    ec.state_bytes = cap * (sum(_itemsize(t) for t in attrs.values()) + 8 + 1)
    return ec


def _aggregation_cost(name: str, defn, plan: PlanGraph, registry,
                      group_cap: int) -> ElementCost:
    from ..core import dtypes
    from ..extension.registry import ExtensionKind
    from ..ops.aggregators import AggregatorFactory
    from ..query_api.expression import AttributeFunction, Variable

    ec = ElementCost(name, "aggregation")
    in_schema = plan.schemas.get(defn.input_stream_id)
    in_attrs = _closed(in_schema.attrs) if in_schema is not None else None
    durations = tuple(getattr(defn, "durations", ()) or ())
    K = max(group_cap, 4096)
    if in_attrs is None or not durations:
        ec.exact = False
        ec.notes.append("open input schema or no durations: store size "
                        "not statically derivable")
        return ec

    group_attrs = []
    for g in getattr(defn, "group_by", None) or ():
        if isinstance(g, Variable) and g.attribute in in_attrs:
            group_attrs.append(g.attribute)
    typer = ExprTyper({"__in__": in_attrs})
    comp_sizes = []
    for oa in defn.selector.attributes:
        expr = oa.expression
        if isinstance(expr, Variable):
            continue  # group passthrough: stored once under group_cols
        if isinstance(expr, AttributeFunction):
            factory = registry.lookup(ExtensionKind.AGGREGATOR,
                                      expr.namespace, expr.name)
            if isinstance(factory, AggregatorFactory):
                try:
                    arg_types = [typer.type_of(p) or AttributeType.DOUBLE
                                 for p in expr.parameters]
                    spec = factory.make(arg_types)
                    import numpy as np
                    comp_sizes.extend(np.dtype(c.dtype).itemsize
                                      for c in spec.components)
                    continue
                except Exception:
                    pass
        ec.exact = False
        ec.notes.append(f"select item {oa.rename or '?'}: component "
                        "dtypes not statically derivable")
    # DurationStore: key_table(H=2K: int64+int32 +2 scalars) + bucket_ts
    # int64[K] + group_cols + comps + alive bool[K]  (core/aggregation.py)
    per_dur = (2 * K * (8 + 4) + 8
               + 8 * K
               + sum(K * _itemsize(in_attrs[g]) for g in group_attrs)
               + sum(K * s for s in comp_sizes)
               + K)
    ec.state_bytes = per_dur * len(durations)
    ec.notes.append(f"{len(durations)} duration store(s) x K={K} slots")
    return ec


# --------------------------------------------------------------------------
# the whole-app walk
# --------------------------------------------------------------------------


def compute_cost(app_or_plan, *, batch_size: int = 0,
                 group_capacity: int = 0) -> CostReport:
    """Predict the app's device state bytes, compile-ladder size, and
    dispatch classes WITHOUT building a runtime. Per-element failures
    degrade to inexact zero-byte entries (never raise)."""
    from ..core import dtypes
    from ..extension.registry import GLOBAL
    # built-in extension registration side effects (same set the manager
    # imports) — cost analysis must see every window/aggregator factory
    from ..ops import aggregators as _a  # noqa: F401
    from ..ops import builtin_functions as _b  # noqa: F401
    from ..ops import window_factories as _w  # noqa: F401
    from .optimizer import _runtime_names, analyze_sharing

    if isinstance(app_or_plan, PlanGraph):
        plan = app_or_plan
    elif isinstance(app_or_plan, str):
        from .. import compiler
        plan = build_plan(compiler.parse(app_or_plan))
    else:
        plan = build_plan(app_or_plan)
    app = plan.app
    registry = GLOBAL
    batch_cap = int(batch_size) or dtypes.config.default_batch_size
    group_cap = int(group_capacity) or dtypes.config.default_group_capacity

    report = CostReport(app_name=getattr(app, "name", "SiddhiApp"))
    names = _runtime_names(plan)

    # --- queries ---
    for node in plan.queries:
        name = names.get(node.index, node.name)
        ins = node.query.input_stream
        try:
            if isinstance(ins, JoinInputStream):
                ec = _join_query_cost(node, plan, registry, batch_cap,
                                      group_cap, name)
            elif isinstance(ins, StateInputStream):
                ec = _pattern_query_cost(node, plan, registry, batch_cap,
                                         group_cap, name)
            else:
                ec = _single_query_cost(node, plan, registry, batch_cap,
                                        group_cap, name)
        except Exception as e:  # degraded, never fatal
            ec = ElementCost(name, "query", exact=False, compiles=1,
                             node_index=node.index,
                             notes=[f"not statically derivable: {e}"])
        if node.partition is not None:
            ec.exact = False
            ec.notes.append("partitioned query: per-key instance "
                            "replication not modeled (lower bound)")
        report.elements.append(ec)

    # --- definitions with their own device state ---
    for sid, schema in plan.schemas.items():
        try:
            if schema.kind == "window" and schema.defn is not None:
                report.elements.append(
                    _named_window_cost(sid, schema.defn, registry, batch_cap))
            elif schema.kind == "table" and schema.defn is not None:
                report.elements.append(
                    _table_cost(sid, schema.defn, group_cap))
            elif schema.kind == "aggregation" and schema.defn is not None:
                report.elements.append(
                    _aggregation_cost(sid, schema.defn, plan, registry,
                                      group_cap))
        except Exception as e:
            report.elements.append(ElementCost(
                sid, schema.kind, exact=False,
                notes=[f"not statically derivable: {e}"]))

    # --- host-side structures: notes, not device bytes ---
    if app is not None and app.annotation("app:eventTime") is not None:
        report.notes.append("@app:eventTime reorder buffers are host-side "
                            "(bounded by allowed.lateness; not counted)")

    # --- totals + fusion-aware compile ladder ---
    report.state_bytes = sum(e.state_bytes for e in report.elements)
    report.compile_ladder = sum(e.compiles for e in report.elements)
    report.exact = all(e.exact for e in report.elements)

    try:
        opt = analyze_sharing(plan)
    except Exception:
        opt = None
    if opt is not None and opt.enabled and opt.groups:
        by_name = {e.element: e for e in report.elements}
        for g in opt.groups:
            members = [by_name[m] for m in g.members if m in by_name]
            if len(members) < 2:
                continue
            rungs = (_ladder_rungs(batch_cap)
                     if all(m.bucket_ok for m in members)
                     and dtypes.config.shape_buckets else 1)
            report.compile_ladder += rungs - sum(m.compiles for m in members)
            report.fusion.append({"stream": g.stream_id,
                                  "members": list(g.members),
                                  "compiles": rungs})
            for m in members:
                m.notes.append(f"fused into shared step on {g.stream_id!r}")

    # --- superstep dispatch class: with @app:superstep(k=K>1) the eligible
    # plan runs K batches per device dispatch (one lax.scan, one fetch), so
    # the per-event dispatch cost divides by K. Host-hop elements keep their
    # "host" class — a callback makes the plan superstep-ineligible
    # (core/superstep.py), which SL506 reports. ---
    k = superstep_k(app)
    report.superstep_k = k
    if k > 1:
        for e in report.elements:
            if e.dispatch == "device" and e.kind in ("query", "join"):
                e.dispatch = "superstep"
                e.notes.append(
                    f"superstep k={k}: one device dispatch per {k} "
                    f"micro-batches (per-event dispatch cost / {k}) when "
                    "the plan is eligible at runtime")
        report.notes.append(
            f"superstep k={k}: step dispatches-per-event divide by {k} "
            "for the eligible sub-plan (core/superstep.py)")

    # --- dominant element ---
    if report.state_bytes > 0:
        top = max(report.elements, key=lambda e: e.state_bytes)
        if top.state_bytes * 2 > report.state_bytes:
            report.dominant = top

    # --- shard fleet pricing: @app:shards runs n full pipeline replicas,
    # so the admission-relevant totals multiply by the shard count (shard
    # replica apps have the annotation stripped, so each replica still
    # prices singly and this never compounds) ---
    from .sharding import shard_config
    cfg = shard_config(app)
    if cfg is not None and cfg.n > 1:
        report.state_bytes *= cfg.n
        report.compile_ladder *= cfg.n
        report.notes.append(
            f"x{cfg.n} shard fleet ({cfg.source}): state and compile "
            "ladders price every replica")

    report.budget = app_budget(app)
    return report


def cost_for_plan(plan: PlanGraph) -> CostReport:
    """Per-plan cached cost report (the SL5xx rules all share one walk)."""
    rep = getattr(plan, "_cost_report", None)
    if rep is None:
        rep = compute_cost(plan)
        plan._cost_report = rep
    return rep


def price_splice(app, query, *, batch_size: int = 0,
                 group_capacity: int = 0) -> dict:
    """Incremental re-price for a single-query splice: cost of the app
    WITH `query` attached minus the app as it stands.  Admission control
    (SL501) gates each splice on the *delta* plus the post-splice totals,
    not a whole-app re-admission — a detach therefore frees exactly the
    bytes this predicted.  Returns::

        {"pre": <CostReport dict>, "post": <CostReport dict>,
         "delta_state_bytes": int, "delta_compiles": int}
    """
    import dataclasses as dc
    pre = compute_cost(app, batch_size=batch_size,
                       group_capacity=group_capacity)
    post_app = dc.replace(
        app, execution_elements=list(app.execution_elements) + [query])
    post = compute_cost(post_app, batch_size=batch_size,
                        group_capacity=group_capacity)
    return {
        "pre": pre.to_dict(),
        "post": post.to_dict(),
        "post_state_bytes": post.state_bytes,
        "post_compiles": post.compile_ladder,
        "delta_state_bytes": post.state_bytes - pre.state_bytes,
        "delta_compiles": post.compile_ladder - pre.compile_ladder,
    }


# --------------------------------------------------------------------------
# the live oracle (calibration / statistics deltas)
# --------------------------------------------------------------------------


def measure_runtime_state_bytes(rt) -> dict:
    """Live device-state bytes per element on a BUILT runtime — the oracle
    tools/cost_calibrate.py and statistics_report()['cost'] compare the
    static prediction against. Sums .nbytes over each element's state
    pytree (no device sync: nbytes is metadata)."""
    out: dict[str, int] = {}
    for qname, qr in getattr(rt, "query_runtimes", {}).items():
        out[qname] = _tree_bytes(qr.state)
    for wname, w in getattr(rt, "windows", {}).items():
        out[wname] = _tree_bytes(w.state)
    for tname, t in getattr(rt, "tables", {}).items():
        state = getattr(t, "state", None)
        if state is None:
            state = getattr(t, "_state", None)
        out[tname] = _tree_bytes(state)
    for aname, a in getattr(rt, "aggregations", {}).items():
        out[aname] = _tree_bytes(a.state)
    return out
