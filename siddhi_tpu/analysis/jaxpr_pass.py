"""Jaxpr hazard pass (SL2xx): trace each query's compiled step abstractly
and walk the jaxpr for device-hostile constructs.

The pass builds a *sandbox* runtime (sources/sinks/stores stripped, nothing
started) and runs `jax.make_jaxpr` over every step function with the same
abstract arguments the warmup path uses — so it sees exactly the program the
runtime would compile, at tracing cost only: no XLA compile, no device
allocation.

Hazards:
  SL201  host callbacks (`pure_callback`, `io_callback`, debug prints):
         every step invocation round-trips device→host→device, serializing
         the dispatch queue (e.g. #window.sort lowers through the bounded
         radix argsort callback in ops/search.py).
  SL202  float64 avals in the step: on TPU f64 is emulated (~10x slower);
         usually a leaked `jax_enable_x64` literal.
  SL203  widening `convert_element_type` ops: silent upcasts that double a
         column's HBM footprint mid-step.
  SL204  fastpath NOT certified: the step carries a veto — host callbacks
         or ordered jaxpr effects — that knocks pjit off its C++
         no-Python dispatch fastpath, re-paying interpreter overhead on
         every batch. `fastpath_certify(app)` returns the per-step
         verdicts; tools/fastpath_gate.py keeps the in-tree bench apps
         from regressing.

Never raises: a query whose step cannot be traced here is skipped (and the
skip is logged at debug), because the runtime build path owns those errors.
"""

from __future__ import annotations

import logging

from .diagnostics import Diagnostic, LintReport, Severity

log = logging.getLogger("siddhi_tpu.lint")

_CALLBACK_PRIMS = ("pure_callback", "io_callback", "callback",
                   "debug_callback", "outside_call")


def _sub_jaxprs(value):
    """Yield any jaxprs nested inside an eqn param value."""
    import jax.core as jcore
    closed = getattr(jcore, "ClosedJaxpr", None)
    jaxpr_t = getattr(jcore, "Jaxpr", None)
    if closed is not None and isinstance(value, closed):
        yield value.jaxpr
    elif jaxpr_t is not None and isinstance(value, jaxpr_t):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)


def _walk(jaxpr, visit) -> None:
    for eqn in jaxpr.eqns:
        visit(eqn)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _walk(sub, visit)


class _Hazards:
    """Hazard accumulator for one step function."""

    def __init__(self) -> None:
        self.callbacks: set[str] = set()
        self.f64: set[str] = set()
        self.upcasts: set[tuple[str, str]] = set()
        self.effects: set[str] = set()

    @property
    def fastpath_vetoes(self) -> list[str]:
        """Why pjit's C++ fastpath would reject this step (empty=certified)."""
        vetoes = []
        if self.callbacks:
            vetoes.append("host callback(s): "
                          + ", ".join(sorted(self.callbacks)))
        if self.effects:
            vetoes.append("ordered effect(s): "
                          + ", ".join(sorted(self.effects)))
        return vetoes

    def visit(self, eqn) -> None:
        import numpy as np

        prim = eqn.primitive.name
        if any(prim == c or prim.endswith("_" + c) for c in _CALLBACK_PRIMS):
            cb = eqn.params.get("callback")
            tag = getattr(cb, "__name__", None) or getattr(
                getattr(cb, "callback_func", None), "__name__", None) or prim
            self.callbacks.add(str(tag))
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and np.dtype(dt) == np.float64:
                self.f64.add(prim)
        if prim == "convert_element_type":
            new = np.dtype(eqn.params.get("new_dtype"))
            srcs = [getattr(getattr(v, "aval", None), "dtype", None)
                    for v in eqn.invars]
            for src in srcs:
                if src is None:
                    continue
                src = np.dtype(src)
                if (new.kind in "fiu" and src.kind in "fiu"
                        and new.itemsize > src.itemsize):
                    self.upcasts.add((src.name, new.name))

    def report(self, report: LintReport, qname: str, suppressions,
               anchor=None, loc=None) -> None:
        def add(rule_id, severity, message):
            if suppressions.is_suppressed(rule_id, anchor):
                return
            report.add(Diagnostic(rule_id, severity, message,
                                  element=qname, loc=loc))

        if self.callbacks:
            add("SL201", Severity.WARN,
                "compiled step calls back to the host every batch "
                f"({', '.join(sorted(self.callbacks))}): device→host→device "
                "round-trip serializes dispatch (e.g. #window.sort lowers "
                "through a host radix argsort)")
        if self.f64:
            add("SL202", Severity.WARN,
                "float64 values flow through the compiled step "
                f"(first seen in: {', '.join(sorted(self.f64))}); TPUs "
                "emulate f64 — keep jax_enable_x64 off or cast explicitly")
        for src, dst in sorted(self.upcasts):
            add("SL203", Severity.INFO,
                f"step silently widens {src} → {dst} "
                "(convert_element_type): doubles that column's footprint "
                "per batch")
        vetoes = self.fastpath_vetoes
        if vetoes:
            add("SL204", Severity.WARN,
                "step is NOT fastpath-certified: "
                + "; ".join(vetoes)
                + " — pjit falls back to Python dispatch every batch")


def _trace_hazards(step_fn, *args) -> _Hazards:
    import jax

    hazards = _Hazards()
    fn = getattr(step_fn, "__wrapped__", step_fn)
    jaxpr = jax.make_jaxpr(fn)(*args)
    for eff in getattr(jaxpr, "effects", ()) or ():
        hazards.effects.add(type(eff).__name__)
    _walk(jaxpr.jaxpr, hazards.visit)
    return hazards


def _steps_of(qr):
    """(tag, step_fn, args) triples for one runtime's jitted steps."""
    import jax.numpy as jnp

    from ..core.event import EventBatch

    now = jnp.int64(0)
    if hasattr(qr, "_step") and hasattr(qr, "_table_states"):
        batch = EventBatch.empty(qr.input_junction.definition, qr._batch_cap)
        yield "", qr._step, (qr.state, batch, now, qr._table_states())
    elif hasattr(qr, "_step_left"):  # join: step(state, batch, now, tstate)
        for from_left, tag in ((True, "/left"), (False, "/right")):
            side = qr.left if from_left else qr.right
            build = qr.right if from_left else qr.left
            if side.junction is None:
                continue
            if build.is_table:
                tstate = build.table.state
            elif build.is_named_window:
                tstate = build.named_window.state
            elif build.is_aggregation:
                tstate = build.agg_view.state
            else:
                tstate = None
            batch = EventBatch.empty(side.junction.definition,
                                     side.junction.batch_size)
            step = qr._step_left if from_left else qr._step_right
            yield tag, step, (qr.state, batch, now, tstate)
    elif hasattr(qr, "_steps") and hasattr(qr, "_feed_junction"):  # pattern
        for sid, step in qr._steps.items():
            junction = qr._feed_junction(sid)
            batch = EventBatch.empty(junction.definition,
                                     junction.batch_size)
            yield f"/{sid}", step, (qr.state, batch, now)


def fastpath_certify(app) -> dict:
    """Per-step fastpath verdicts for one app (SiddhiApp or SiddhiQL text):
    {step_name: {"certified": bool, "vetoes": [reason, ...]}}.

    A certified step carries no host callback and no ordered effect, so
    pjit's C++ no-Python dispatch can serve it. Steps that fail to trace
    are reported as {"certified": False, "vetoes": ["trace failed: ..."]}
    — an untraceable step cannot be certified."""
    from ..core.manager import SiddhiManager

    if isinstance(app, str):
        from ..compiler import SiddhiCompiler
        app = SiddhiCompiler.parse(app)
    out: dict = {}
    manager = SiddhiManager()
    manager._lint_enabled = False
    try:
        rt = manager.create_sandbox_siddhi_app_runtime(app)
        for name, qr in rt.query_runtimes.items():
            try:
                for tag, step, args in _steps_of(qr):
                    hazards = _trace_hazards(step, *args)
                    vetoes = hazards.fastpath_vetoes
                    out[f"{name}{tag}"] = {"certified": not vetoes,
                                           "vetoes": vetoes}
            except Exception as e:  # noqa: BLE001 — per-step best effort
                out[name] = {"certified": False,
                             "vetoes": [f"trace failed: {e}"]}
    finally:
        try:
            manager.shutdown()
        except Exception:
            log.debug("fastpath certify: manager shutdown failed",
                      exc_info=True)
    return out


def run_jaxpr_pass(app, report: LintReport, suppressions) -> None:
    """Trace every query step of `app` in a sandbox runtime and append
    SL201/SL202/SL203 findings to `report`. Best effort by design."""
    from ..core.manager import SiddhiManager

    manager = SiddhiManager()
    manager._lint_enabled = False
    try:
        try:
            rt = manager.create_sandbox_siddhi_app_runtime(app)
        except Exception:
            log.debug("jaxpr pass: sandbox build failed; pass skipped",
                      exc_info=True)
            return
        for name, qr in rt.query_runtimes.items():
            query = getattr(qr, "query", None)
            loc = getattr(query, "loc", None)
            try:
                for tag, step, args in _steps_of(qr):
                    hazards = _trace_hazards(step, *args)
                    hazards.report(report, f"{name}{tag}", suppressions,
                                   anchor=query, loc=loc)
            except Exception:
                log.debug("jaxpr pass: tracing %s failed; query skipped",
                          name, exc_info=True)
    finally:
        try:
            manager.shutdown()
        except Exception:
            log.debug("jaxpr pass: manager shutdown failed", exc_info=True)
