"""Shard-eligibility classification for the sharded execution plane.

`parallel/shard_plane.py` runs one replica of the whole pipeline per shard
and routes ingress rows by a partition-key hash. That is only CORRECT for
operators whose output is a function of one key's event subsequence —
"key-local" in the partitioned-stream semantics taxonomy (per-key ordering
is preserved by the router; cross-key interleaving is not). Everything
whose state or emission depends on the GLOBAL arrival sequence — unkeyed
windows, count-based window boundaries, patterns, non-equi joins — would
be silently wrong under sharding, so the classifier here refuses it loudly
(SL601 at lint time, `SiddhiAppCreationError` at creation time).

The taxonomy (docs/SHARDING.md mirrors this table):

key-local (shard-eligible)
    - stateless per-row queries (filters / projections / scalar functions)
    - windowless running aggregates whose GROUP BY contains the partition
      key (emission is per input row; state is per group)
    - time-driven windows (`time`, `timeBatch`, `externalTime*`, `session`,
      `delay`) aggregated with the partition key in GROUP BY — eviction
      depends on timestamps only, never on cross-key arrival counts
    - joins whose ON condition equates the partition key across both sides,
      each side windowless (tables) or time-driven
    - `partition with (key of Stream)` blocks keyed by the partition key

global (refused)
    - count-based windows (`length`, `lengthBatch`, `sort`, ...): the
      window boundary counts OTHER keys' arrivals
    - aggregates without the partition key in GROUP BY
    - patterns / sequences (cross-key ordered NFA matching)
    - named `define window` (state shared by reference across queries)
    - output rate limiting (wall-clock / count batching spans keys)
    - triggers (each shard's scheduler would fire its own copy)
    - `@source`-fed streams (each replica would connect the transport)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from ..query_api import SiddhiApp
from ..query_api.execution import (
    JoinInputStream,
    Selector,
    SingleInputStream,
    StateInputStream,
    ValuePartitionType,
)
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    IsNull,
    MathExpression,
    Not,
    Or,
    Variable,
)
from .plan import PlanGraph, QueryNode

#: windows whose eviction/emission boundary is a function of timestamps
#: only — per-shard replicas see the same boundary for a key's rows as the
#: serial engine does (count-based boundaries are NOT in this set: they
#: count other keys' arrivals)
TIME_DRIVEN_WINDOWS = frozenset({
    "time", "timebatch", "externaltime", "externaltimebatch", "session",
    "delay",
})

KEY_LOCAL = "key-local"
GLOBAL = "global"


@dataclass(frozen=True)
class ShardConfig:
    """Parsed `@app:shards(n=, key=)` (+ `SIDDHI_SHARDS` n override)."""

    n: int
    key: str
    source: str = "@app:shards"


def shard_config(app: Optional[SiddhiApp],
                 strict: bool = False) -> Optional[ShardConfig]:
    """The app's shard configuration, or None when it has no `@app:shards`
    annotation. `SIDDHI_SHARDS` overrides the annotation's `n` (so CI can
    sweep shard counts over one app text) but never turns sharding on by
    itself — an env var must not reshard every app on the host. With
    `strict` a malformed annotation raises `SiddhiAppCreationError`;
    otherwise (lint paths, which must never crash creation) it returns
    None."""
    if app is None:
        return None
    ann = app.annotation("app:shards")
    if ann is None:
        return None
    key = ann.element("key")
    n_s = ann.element("n") or ann.element()
    source = "@app:shards"
    env = os.environ.get("SIDDHI_SHARDS", "").strip()
    if env:
        n_s, source = env, "SIDDHI_SHARDS"

    def bad(msg: str):
        if strict:
            from ..errors import SiddhiAppCreationError
            raise SiddhiAppCreationError(
                f"@app:shards on {app.name!r}: {msg} "
                "(docs/SHARDING.md)")
        return None

    if not key:
        return bad("a partition key is required: "
                   "@app:shards(n='4', key='symbol')")
    try:
        n = int(n_s) if n_s else 0
    except ValueError:
        return bad(f"shard count {n_s!r} is not an integer")
    if n < 1:
        return bad(f"shard count must be >= 1, got {n}")
    return ShardConfig(n=n, key=key, source=source)


# --------------------------------------------------------------------------
# expression helpers
# --------------------------------------------------------------------------


def _walk(expr) -> list:
    """Flatten an expression tree to its nodes (pre-order)."""
    out, stack = [], [expr]
    while stack:
        e = stack.pop()
        if e is None or not isinstance(e, Expression):
            continue
        out.append(e)
        if isinstance(e, (And, Or)):
            stack += [e.left, e.right]
        elif isinstance(e, Not):
            stack.append(e.expression)
        elif isinstance(e, Compare):
            stack += [e.left, e.right]
        elif isinstance(e, MathExpression):
            stack += [e.left, e.right]
        elif isinstance(e, AttributeFunction):
            stack += list(e.parameters)
        elif isinstance(e, IsNull):
            stack.append(e.expression)
    return out


def _is_aggregator(fn: AttributeFunction) -> bool:
    from ..extension.registry import GLOBAL as REG
    from ..extension.registry import ExtensionKind
    try:
        return REG.lookup(ExtensionKind.AGGREGATOR, fn.namespace,
                          fn.name) is not None
    except Exception:
        return False


def _selector_has_aggregates(sel: Selector) -> bool:
    for attr in sel.attributes:
        for node in _walk(attr.expression):
            if isinstance(node, AttributeFunction) and _is_aggregator(node):
                return True
    if sel.having is not None:
        for node in _walk(sel.having):
            if isinstance(node, AttributeFunction) and _is_aggregator(node):
                return True
    return False


def _group_by_has_key(group_by, key: str) -> bool:
    return any(isinstance(v, Variable) and v.attribute == key
               for v in group_by)


def _conjuncts(expr) -> list:
    """Top-level AND-ed conjuncts of a condition."""
    if expr is None:
        return []
    if isinstance(expr, And):
        return _conjuncts(expr.left) + _conjuncts(expr.right)
    return [expr]


def _equates_key_across_sides(on, key: str, left_refs: set,
                              right_refs: set) -> bool:
    """True when some top-level conjunct of `on` is `l.key == r.key` with
    `l`/`r` referencing opposite join sides (bare variables count for
    either side)."""
    for c in _conjuncts(on):
        if not (isinstance(c, Compare) and c.op is CompareOp.EQUAL):
            continue
        lv, rv = c.left, c.right
        if not (isinstance(lv, Variable) and isinstance(rv, Variable)):
            continue
        if lv.attribute != key or rv.attribute != key:
            continue
        l_sid, r_sid = lv.stream_id, rv.stream_id
        l_left = l_sid is None or l_sid in left_refs
        l_right = l_sid is None or l_sid in right_refs
        r_left = r_sid is None or r_sid in left_refs
        r_right = r_sid is None or r_sid in right_refs
        if (l_left and r_right) or (l_right and r_left):
            return True
    return False


def _window_time_driven(single: SingleInputStream) -> Optional[bool]:
    """None = no window; True/False = window present and (not) time-driven."""
    w = single.handlers.window
    if w is None:
        return None
    return w.name.lower() in TIME_DRIVEN_WINDOWS


# --------------------------------------------------------------------------
# classification
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ShardClass:
    """One element's verdict: `cls` is KEY_LOCAL or GLOBAL; GLOBAL entries
    carry the reason sharding would be silently wrong."""

    element: str
    cls: str
    reason: str
    node: Optional[QueryNode] = None  # set for query verdicts
    defn: object = None  # set for definition-level verdicts


def _classify_query(node: QueryNode, plan: PlanGraph,
                    key: str) -> ShardClass:
    q = node.query
    sel = q.selector
    has_agg = _selector_has_aggregates(sel)
    gb_key = _group_by_has_key(sel.group_by, key)

    def verdict(cls, reason):
        return ShardClass(node.name, cls, reason, node=node)

    if q.output_rate is not None:
        return verdict(GLOBAL, "output rate limiting batches emissions on "
                               "a per-runtime clock/count that spans keys")
    if node.partition is not None:
        for pt in node.partition.partition_types:
            if not (isinstance(pt, ValuePartitionType)
                    and isinstance(pt.expression, Variable)
                    and pt.expression.attribute == key):
                return verdict(
                    GLOBAL,
                    f"partitioned by something other than the partition "
                    f"key {key!r} — per-shard instances would split one "
                    "partition group across shards")
        # partition keyed by the shard key: every inner element is per-key
        return verdict(KEY_LOCAL, f"partition with ({key} of ...)")
    istream = q.input_stream
    if isinstance(istream, StateInputStream):
        return verdict(GLOBAL, "pattern/sequence matching is ordered "
                               "across keys (cross-key NFA state)")
    if isinstance(istream, JoinInputStream):
        left_refs = {istream.left.reference_id, istream.left.stream_id}
        right_refs = {istream.right.reference_id, istream.right.stream_id}
        if not _equates_key_across_sides(istream.on, key, left_refs,
                                         right_refs):
            return verdict(
                GLOBAL,
                f"join does not equate the partition key {key!r} across "
                "both sides — matching pairs would land on different "
                "shards")
        for side, name in ((istream.left, "left"), (istream.right, "right")):
            td = _window_time_driven(side)
            if td is False:
                return verdict(
                    GLOBAL,
                    f"{name} join side uses count-based window "
                    f"#window.{side.handlers.window.name} — its eviction "
                    "boundary counts other keys' arrivals")
        return verdict(KEY_LOCAL, f"equi-join on partition key {key!r}")
    # single input stream
    single = node.consumed[0].single if node.consumed else istream
    td = _window_time_driven(single)
    if td is None:
        if has_agg and not gb_key:
            return verdict(
                GLOBAL,
                f"running aggregate without the partition key {key!r} in "
                "GROUP BY accumulates across keys")
        if has_agg:
            return verdict(KEY_LOCAL,
                           f"per-key running aggregate (group by {key})")
        return verdict(KEY_LOCAL, "stateless per-row query")
    if not td:
        return verdict(
            GLOBAL,
            f"count-based window #window.{single.handlers.window.name} — "
            "its boundary counts other keys' arrivals")
    if not (has_agg and gb_key):
        return verdict(
            GLOBAL,
            f"windowed query without the partition key {key!r} in GROUP "
            "BY — window contents span keys")
    return verdict(KEY_LOCAL,
                   f"time-driven window grouped by partition key {key!r}")


def classify_plan(plan: PlanGraph, key: str) -> list[ShardClass]:
    """Shard-eligibility verdict for every execution element plus the
    app-level hazards (key-less ingress streams, triggers, named windows,
    sources). Order: definition-level verdicts first, then queries in plan
    order."""
    app = plan.app
    out: list[ShardClass] = []
    consumed_ids = {c.stream_id for node in plan.queries
                    for c in node.consumed}
    for sid, sdef in app.stream_definitions.items():
        attrs = {a.name for a in sdef.attributes}
        if any(a.name.lower() in ("source",)
               for a in (sdef.annotations or ())):
            out.append(ShardClass(
                sid, GLOBAL,
                "@source-fed stream: every shard replica would connect "
                "the transport and double-ingest — feed sharded apps "
                "through the plane's input handlers / REST frames",
                defn=sdef))
            continue
        externally_fed = sid not in plan.producers or \
            not plan.producers.get(sid)
        if externally_fed and sid in consumed_ids and key not in attrs:
            out.append(ShardClass(
                sid, GLOBAL,
                f"externally-fed stream lacks the partition key {key!r} — "
                "its rows cannot be routed", defn=sdef))
    for tid, tdef in app.trigger_definitions.items():
        out.append(ShardClass(
            tid, GLOBAL,
            "trigger: each shard's scheduler would fire its own copy "
            "(n duplicates of every trigger event)", defn=tdef))
    for wid, wdef in app.window_definitions.items():
        out.append(ShardClass(
            wid, GLOBAL,
            "named window: state shared by reference across queries is "
            "not key-partitionable", defn=wdef))
    for aid, adef in app.aggregation_definitions.items():
        if _group_by_has_key(adef.group_by, key):
            out.append(ShardClass(
                aid, KEY_LOCAL,
                f"incremental aggregation grouped by partition key {key!r}",
                defn=adef))
        else:
            out.append(ShardClass(
                aid, GLOBAL,
                f"incremental aggregation without the partition key "
                f"{key!r} in GROUP BY accumulates across keys", defn=adef))
    for node in plan.queries:
        out.append(_classify_query(node, plan, key))
    return out


def shard_violations(plan: PlanGraph, key: str) -> list[ShardClass]:
    return [v for v in classify_plan(plan, key) if v.cls == GLOBAL]


def check_shardable(app: SiddhiApp, key: str) -> None:
    """Raise `SiddhiAppCreationError` (SL601) when any element of `app` is
    global under partition key `key` — the plane refuses loudly rather
    than running silently wrong."""
    from ..errors import SiddhiAppCreationError
    from .plan import build_plan

    plan = build_plan(app)
    bad = shard_violations(plan, key)
    if bad:
        lines = "\n".join(f"  [{v.element}] {v.reason}" for v in bad)
        raise SiddhiAppCreationError(
            f"SL601: app {app.name!r} is not shard-eligible under "
            f"partition key {key!r} — {len(bad)} global element(s):\n"
            f"{lines}\nRemove @app:shards or restructure the queries "
            "(docs/SHARDING.md has the eligibility taxonomy).")
