"""Lint diagnostics: severities, findings, reports, suppression.

Diagnostics reuse SiddhiParserError's " at line L:C" location format so every
tool in the stack (parser, linter, CLI, REST validate) reports positions
identically. A Diagnostic is pure data; rendering lives here too so the CLI
and the runtime log lines agree byte-for-byte.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Optional


class Severity(enum.Enum):
    ERROR = "error"
    WARN = "warn"
    INFO = "info"

    @property
    def rank(self) -> int:
        return {"error": 0, "warn": 1, "info": 2}[self.value]


@dataclass(frozen=True)
class Diagnostic:
    """One finding. `element` names the app element it anchors to (a query
    name, stream id, ...); `loc` is the element's (line, column) when the
    parser captured one."""

    rule_id: str
    severity: Severity
    message: str
    element: Optional[str] = None
    loc: Optional[tuple] = None

    @property
    def location(self) -> str:
        if not self.loc:
            return ""
        return f" at line {self.loc[0]}:{self.loc[1]}"

    def format(self) -> str:
        where = f" [{self.element}]" if self.element else ""
        return (f"{self.severity.value.upper():5s} {self.rule_id}{where} "
                f"{self.message}{self.location}")

    def to_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "severity": self.severity.value,
            "message": self.message,
            "element": self.element,
            "line": self.loc[0] if self.loc else None,
            "column": self.loc[1] if self.loc else None,
        }


@dataclass
class LintReport:
    """All diagnostics for one app, ordered most-severe first."""

    app_name: str = "SiddhiApp"
    diagnostics: list[Diagnostic] = field(default_factory=list)
    #: static cost section (analysis/cost.py CostReport.to_dict()); None
    #: when the cost pass was skipped or crashed — lint never fails on it
    cost: Optional[dict] = None

    def add(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def extend(self, other: "LintReport") -> None:
        self.diagnostics.extend(other.diagnostics)

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.ERROR]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.WARN]

    @property
    def has_errors(self) -> bool:
        return bool(self.errors)

    def rule_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for d in self.diagnostics:
            counts[d.rule_id] = counts.get(d.rule_id, 0) + 1
        return counts

    def sorted(self) -> list[Diagnostic]:
        return sorted(self.diagnostics,
                      key=lambda d: (d.severity.rank, d.rule_id,
                                     d.loc or (1 << 30, 0)))

    def format(self) -> str:
        lines = [d.format() for d in self.sorted()]
        n_err, n_warn = len(self.errors), len(self.warnings)
        n_info = len(self.diagnostics) - n_err - n_warn
        lines.append(f"{self.app_name}: {n_err} error(s), {n_warn} "
                     f"warning(s), {n_info} info")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        out = {
            "app": self.app_name,
            "valid": not self.has_errors,
            "counts": self.rule_counts(),
            "diagnostics": [d.to_dict() for d in self.sorted()],
        }
        if self.cost is not None:
            out["cost"] = self.cost
        return out

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)


# ----------------------------------------------------------------- suppression


def _suppressed_ids(annotations) -> set[str]:
    """Rule ids named by `@suppress.lint('SL101', ...)` annotations.

    The grammar accepts both `.` and `:` as the name separator (normalized
    to `:` by the transformer); an argument-less `@suppress.lint` suppresses
    every rule on that element."""
    ids: set[str] = set()
    for ann in annotations or ():
        if ann.name.lower().replace(":", ".") != "suppress.lint":
            continue
        if not ann.elements:
            return {"*"}
        for el in ann.elements:
            ids.add(str(el.value).strip().upper())
    return ids


class Suppressions:
    """App-level + per-element suppression lookup."""

    def __init__(self, app) -> None:
        self._app_level = _suppressed_ids(getattr(app, "annotations", ()))

    def is_suppressed(self, rule_id: str, element=None) -> bool:
        if "*" in self._app_level or rule_id in self._app_level:
            return True
        if element is not None:
            ids = _suppressed_ids(getattr(element, "annotations", ()))
            if "*" in ids or rule_id in ids:
                return True
        return False
