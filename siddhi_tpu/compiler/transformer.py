"""Lark parse tree → query_api AST (the TPU build's equivalent of the
reference's SiddhiQLBaseVisitorImpl.java, 3,080 LoC)."""

from __future__ import annotations

import dataclasses

from lark import Token, Transformer, v_args

from ..query_api import (
    AbsentStreamStateElement,
    AggregationDefinition,
    And,
    Annotation,
    Attribute,
    AttributeFunction,
    AttributeType,
    Compare,
    CompareOp,
    Constant,
    CountStateElement,
    Duration,
    Element,
    EventTrigger,
    EveryStateElement,
    Expression,
    FunctionDefinition,
    In,
    IsNull,
    JoinInputStream,
    JoinType,
    LogicalStateElement,
    MathExpression,
    MathOp,
    NextStateElement,
    Not,
    OnDemandQuery,
    Or,
    OrderByAttribute,
    OrderByOrder,
    OutputAction,
    OutputAttribute,
    OutputEventType,
    OutputRate,
    OutputRateType,
    OutputStream,
    Partition,
    Query,
    RangePartitionProperty,
    RangePartitionType,
    Selector,
    SiddhiApp,
    SingleInputStream,
    StateInputStream,
    StateType,
    StreamDefinition,
    StreamHandlerChain,
    StreamStateElement,
    TableDefinition,
    TriggerDefinition,
    UpdateSetAttribute,
    ValuePartitionType,
    Variable,
    WindowDefinition,
    WindowHandler,
)
from ..query_api.execution import StreamHandlerChain as HandlerChain


def _unquote(tok: str) -> str:
    s = str(tok)
    if s.startswith('"""') and s.endswith('"""'):
        return s[3:-3]
    return s[1:-1]


_TIME_UNIT_MS = {
    "year": 31_536_000_000, "month": 2_592_000_000, "week": 604_800_000,
    "day": 86_400_000, "hour": 3_600_000, "min": 60_000, "sec": 1_000,
    "milli": 1,
}


def _unit_ms(tok: Token) -> int:
    t = tok.type
    return {
        "YEARS": _TIME_UNIT_MS["year"], "MONTHS": _TIME_UNIT_MS["month"],
        "WEEKS": _TIME_UNIT_MS["week"], "DAYS": _TIME_UNIT_MS["day"],
        "HOURS": _TIME_UNIT_MS["hour"], "MINUTES": _TIME_UNIT_MS["min"],
        "SECONDS": _TIME_UNIT_MS["sec"], "MILLISECONDS": _TIME_UNIT_MS["milli"],
    }[t]


class _Filter:
    def __init__(self, expr: Expression):
        self.expr = expr


class _StreamFn:
    def __init__(self, handler: WindowHandler):
        self.handler = handler


class _Window:
    def __init__(self, handler: WindowHandler):
        self.handler = handler


def _loc(meta):
    """Tree meta → (line, column), or None when positions are unavailable
    (synthetic trees, or rules whose children were all inlined away)."""
    try:
        if meta is None or getattr(meta, "empty", True):
            return None
        return (meta.line, meta.column)
    except AttributeError:
        return None


#: methods that also want the rule's source position (lint diagnostics)
_with_meta = v_args(inline=True, meta=True)


def _build_chain(handlers: list) -> HandlerChain:
    filters, pre_fns, post_fns, post_filters = [], [], [], []
    window = None
    for h in handlers:
        if isinstance(h, _Filter):
            (post_filters if window else filters).append(h.expr)
        elif isinstance(h, _StreamFn):
            (post_fns if window else pre_fns).append(h.handler)
        elif isinstance(h, _Window):
            window = h.handler
    return HandlerChain(
        filters=tuple(filters),
        pre_window_functions=tuple(pre_fns),
        window=window,
        post_window_functions=tuple(post_fns),
        post_window_filters=tuple(post_filters),
    )


@v_args(inline=True)
class AstTransformer(Transformer):
    # ---------------- expressions ----------------

    def expression(self, e):
        return e

    def or_expr(self, first, *rest):
        out = first
        for item in rest:
            if isinstance(item, Token):  # OR token
                continue
            out = Or(out, item)
        return out

    def and_expr(self, first, *rest):
        out = first
        for item in rest:
            if isinstance(item, Token):
                continue
            out = And(out, item)
        return out

    def not_op(self, _not, e):
        return Not(e)

    def not_expr(self, e):
        return e

    def unary(self, e):
        return e

    def comparison(self, left, *rest):
        if not rest:
            return left
        op_tok, right = rest
        return Compare(left, CompareOp(str(op_tok)), right)

    def comp_op(self, tok):
        return tok

    def is_null_op(self, e, _is, _null):
        if isinstance(e, Variable) and e.stream_id is None and e.stream_index is None:
            # bare `e2 is null` in patterns refers to a stream ref; the planner
            # decides variable-vs-stream by name resolution. Keep both.
            return IsNull(expression=e, stream_id=e.attribute)
        return IsNull(expression=e)

    def in_op(self, e, _in, name):
        return In(e, str(name))

    def in_expr(self, e):
        return e

    def addsub(self, first, *rest):
        out = first
        for i in range(0, len(rest), 2):
            op, operand = rest[i], rest[i + 1]
            out = MathExpression(MathOp(str(op)), out, operand)
        return out

    def addsub_op(self, tok):
        return tok

    def muldiv(self, first, *rest):
        out = first
        for i in range(0, len(rest), 2):
            op, operand = rest[i], rest[i + 1]
            out = MathExpression(MathOp(str(op)), out, operand)
        return out

    def muldiv_op(self, tok):
        return tok

    def neg(self, _minus, e):
        if isinstance(e, Constant) and isinstance(e.value, (int, float)):
            return Constant(-e.value, e.type_name)
        return MathExpression(MathOp.SUBTRACT, Constant(0, "int"), e)

    def atom(self, e):
        return e

    def ns_function(self, ns, name, *args):
        params = args[0] if args else ()
        return AttributeFunction(str(ns), str(name), tuple(params))

    def plain_function(self, name, *args):
        params = args[0] if args else ()
        return AttributeFunction("", str(name), tuple(params))

    def expr_list(self, *exprs):
        return list(exprs)

    def indexed_variable(self, stream, index, attr):
        if isinstance(index, Token) and index.type == "LAST_KW":
            return Variable(str(attr), stream_id=str(stream), is_last=True)
        return Variable(str(attr), stream_id=str(stream), stream_index=int(index))

    def stream_index(self, tok):
        return tok

    def qualified_variable(self, stream, attr):
        return Variable(str(attr), stream_id=str(stream))

    def simple_variable(self, name):
        return Variable(str(name))

    def string_const(self, tok):
        return Constant(_unquote(tok), "string")

    def bool_const(self, tok):
        return Constant(str(tok).lower() == "true", "bool")

    def int_const(self, tok):
        return Constant(int(str(tok)), "int")

    def long_const(self, tok):
        return Constant(int(str(tok)[:-1]), "long")

    def float_const(self, tok):
        return Constant(float(str(tok)[:-1]), "float")

    def double_const(self, tok):
        s = str(tok)
        if s[-1] in "dD":
            s = s[:-1]
        return Constant(float(s), "double")

    def time_value(self, *parts):
        return Constant(int(sum(parts)), "time")

    def time_part(self, value, unit):
        return int(str(value)) * _unit_ms(unit)

    def time_unit(self, tok):
        return tok

    # ---------------- annotations ----------------

    def qualified_name(self, *names):
        return ":".join(str(n) for n in names)

    def annotation(self, name, *body):
        elements, nested = [], []
        if body:
            for item in body[0]:
                if isinstance(item, Annotation):
                    nested.append(item)
                else:
                    elements.append(item)
        return Annotation(str(name), tuple(elements), tuple(nested))

    def app_annotation(self, _app_kw, name, *body):
        elements, nested = [], []
        if body:
            for item in body[0]:
                if isinstance(item, Annotation):
                    nested.append(item)
                else:
                    elements.append(item)
        return Annotation(f"app:{name}", tuple(elements), tuple(nested))

    def annotation_body(self, *items):
        return list(items)

    def annotation_item(self, item):
        return item

    def keyed_element(self, *parts):
        *keys, value = parts
        return Element(".".join(str(k) for k in keys), value)

    def bare_element(self, value):
        return Element(None, value)

    def literal_value(self, tok):
        if tok.type == "STRING_LITERAL":
            return _unquote(tok)
        return str(tok)

    # ---------------- definitions ----------------

    def attr_type(self, tok):
        return AttributeType.parse(str(tok))

    def attr_def(self, name, type_):
        return Attribute(str(name), type_)

    def attr_list(self, *attrs):
        return tuple(attrs)

    def stream_id(self, tok):
        return str(tok)

    @_with_meta
    def define_stream(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        _define, _stream, name, attrs = rest
        return StreamDefinition(id=str(name), attributes=attrs, annotations=anns,
                                loc=_loc(meta))

    @_with_meta
    def define_table(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        _define, _table, name, attrs = rest
        return TableDefinition(id=str(name), attributes=attrs, annotations=anns,
                               loc=_loc(meta))

    def window_spec(self, name, *args):
        params = args[0] if args else ()
        return WindowHandler("", str(name), tuple(params))

    def output_event_kw(self, _out, etype, _events):
        return etype

    @_with_meta
    def define_window(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        _define, _window, name, attrs, *extra = rest
        window = None
        out_type = "all"
        for e in extra:
            if isinstance(e, WindowHandler):
                window = e
            elif isinstance(e, OutputEventType):
                out_type = e.name.lower()
        return WindowDefinition(id=str(name), attributes=attrs, annotations=anns,
                                window=window, output_event_type=out_type,
                                loc=_loc(meta))

    def trigger_every(self, _every, tv):
        return ("every", tv.value)

    def trigger_cron_or_start(self, tok):
        s = _unquote(tok)
        return ("start", None) if s.lower() == "start" else ("cron", s)

    @_with_meta
    def define_trigger(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        _define, _trigger, name, _at, at = rest
        kind, val = at
        return TriggerDefinition(
            id=str(name),
            at_every_ms=val if kind == "every" else None,
            at_cron=val if kind == "cron" else None,
            at_start=kind == "start",
            annotations=anns,
            loc=_loc(meta),
        )

    @_with_meta
    def define_function(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        _define, _function, name, lang, _ret, rtype, body = rest
        return FunctionDefinition(id=str(name), language=str(lang),
                                  return_type=rtype, body=str(body)[1:-1].strip(),
                                  loc=_loc(meta))

    def duration_name(self, tok):
        return Duration.parse(str(tok))

    def duration_dots(self, lo, *rest):
        hi = rest[0] if rest else lo
        durs = list(Duration)
        return tuple(durs[lo.order:hi.order + 1])

    def duration_list(self, *durs):
        return tuple(sorted(set(durs), key=lambda d: d.order))

    def duration_single(self, d):
        return (d,)

    def aggregate_clause(self, _agg, *rest):
        by_attr = None
        items = list(rest)
        if items and isinstance(items[0], Token) and items[0].type == "BY":
            by_attr = items[1].attribute
            items = items[2:]
        # items: [EVERY token, durations tuple]
        durations = items[-1]
        return (by_attr, durations)

    @_with_meta
    def define_aggregation(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        _define, _aggregation, name, _from, stream, *clauses = rest
        selector = Selector()
        group_by = ()
        agg = (None, ())
        for c in clauses:
            if isinstance(c, Selector):
                selector = c
            elif isinstance(c, tuple) and c and isinstance(c[0], Variable):
                group_by = c
            elif isinstance(c, tuple):
                agg = c
        by_attr, durations = agg
        return AggregationDefinition(
            id=str(name), input_stream_id=str(stream),
            selector=Selector(attributes=selector.attributes,
                              group_by=group_by, having=selector.having),
            group_by=group_by, aggregate_attribute=by_attr,
            durations=durations, annotations=anns, loc=_loc(meta))

    def definition(self, d):
        return d

    # ---------------- query input ----------------

    def source(self, tok):
        if isinstance(tok, tuple) and tok and tok[0] == "anon":
            return tok
        s = str(tok)
        if s.startswith("#"):
            return ("inner", s[1:])
        if s.startswith("!"):
            return ("fault", s[1:])
        return ("plain", s)

    def anon_stream(self, *parts):
        """`from (from S select ...) ...`: desugar to a synthetic stream fed
        by the inner query (reference: AnonymousInputStream.java). The inner
        query is queued and emitted just before the enclosing query."""
        n = getattr(self, "_anon_n", 0)
        self._anon_n = n + 1
        name = f"_anon_{n}"
        inner = self.query(None, *parts)
        if isinstance(inner, tuple) and inner and inner[0] == "queries":
            qs = list(inner[1])
            inner = qs.pop()
            self._pending_anon = getattr(self, "_pending_anon", [])
            self._pending_anon.extend(qs)
        inner = dataclasses.replace(
            inner, output_stream=OutputStream(OutputAction.INSERT,
                                              target_id=name))
        if not hasattr(self, "_pending_anon"):
            self._pending_anon = []
        self._pending_anon.append(inner)
        return ("anon", name)

    def handler_chain(self, *handlers):
        return list(handlers)

    def stream_handler(self, h):
        return h

    def filter(self, expr):
        return _Filter(expr)

    def function_id_pair(self, *names):
        if len(names) == 2:
            return (str(names[0]), str(names[1]))
        return ("", str(names[0]))

    def function_id(self, name):
        return str(name)

    def stream_function_h(self, pair, *args):
        ns, name = pair
        params = args[0] if args else ()
        return _StreamFn(WindowHandler(ns, name, tuple(params)))

    def window_h(self, _window_kw, name, *args):
        params = args[0] if args else ()
        return _Window(WindowHandler("", str(name), tuple(params)))

    def standard_stream(self, source, handlers):
        kind, sid = source
        return SingleInputStream(
            stream_id=sid,
            handlers=_build_chain(handlers),
            is_inner=kind == "inner",
            is_fault=kind == "fault",
        )

    def alias_name(self, tok):
        return str(tok)

    def join_side(self, source, handlers, *rest):
        kind, sid = source
        alias = None
        unidirectional = False
        for r in rest:
            # NB: Token subclasses str — test Token first
            if isinstance(r, Token) and r.type == "UNIDIRECTIONAL":
                unidirectional = True
            elif isinstance(r, str):
                alias = str(r)
        s = SingleInputStream(stream_id=sid, alias=alias,
                              handlers=_build_chain(handlers),
                              is_inner=kind == "inner", is_fault=kind == "fault")
        return (s, unidirectional)

    def inner_join(self, *_):
        return JoinType.INNER

    def left_outer_join(self, *_):
        return JoinType.LEFT_OUTER

    def right_outer_join(self, *_):
        return JoinType.RIGHT_OUTER

    def full_outer_join(self, *_):
        return JoinType.FULL_OUTER

    def right_unidirectional(self, tok):
        return ("right_uni",)

    def within_clause(self, _within, tv):
        return ("within", tv.value)

    def per_clause(self, _per, e):
        return ("per", e)

    def join_stream(self, left_pair, join_type, right_pair, *rest):
        left, left_uni = left_pair
        right, right_uni = right_pair
        on = None
        within_ms = None
        per = None
        for r in rest:
            if isinstance(r, tuple) and r[0] == "within":
                within_ms = r[1]
            elif isinstance(r, tuple) and r[0] == "per":
                per = r[1]
            elif isinstance(r, tuple) and r[0] == "right_uni":
                right_uni = True
            elif isinstance(r, Expression):
                on = r
            elif isinstance(r, Token):
                continue
        if left_uni and right_uni:
            raise ValueError("both sides cannot be unidirectional")
        trigger = EventTrigger.ALL
        if left_uni:
            trigger = EventTrigger.LEFT
        elif right_uni:
            trigger = EventTrigger.RIGHT
        return JoinInputStream(left=left, right=right, join_type=join_type,
                               on=on, trigger=trigger, within_ms=within_ms, per=per)

    # ---------------- patterns / sequences ----------------

    def event_ref(self, tok):
        return str(tok)

    def event_def(self, *parts):
        ref = None
        items = list(parts)
        if isinstance(items[0], str) and not isinstance(items[0], tuple):
            ref = items.pop(0)
        source, handlers = items
        kind, sid = source
        if kind == "anon":
            from ..errors import SiddhiAppCreationError
            raise SiddhiAppCreationError(
                "anonymous streams are not supported inside patterns/"
                "sequences — define the inner query as its own stream")
        s = SingleInputStream(stream_id=sid, alias=ref,
                              handlers=_build_chain(handlers),
                              is_inner=kind == "inner", is_fault=kind == "fault")
        return StreamStateElement(s)

    def count_min_max(self, lo, hi):
        return (int(lo), int(hi))

    def count_min(self, lo):
        return (int(lo), CountStateElement.ANY)

    def count_max(self, hi):
        return (1, int(hi))

    def count_exact(self, n):
        return (int(n), int(n))

    def counted_state(self, elem, *count):
        if count:
            lo, hi = count[0]
            return CountStateElement(elem, lo, hi)
        return elem

    def absent_state(self, _not, elem, *rest):
        wait = None
        for r in rest:
            if isinstance(r, Constant):
                wait = r.value
            elif isinstance(r, Token) and r.type == "FOR":
                continue
        return AbsentStreamStateElement(elem.stream, waiting_time_ms=wait)

    def nested_chain(self, chain):
        return chain

    def logical_state(self, first, *rest):
        if not rest:
            return first
        op_tok, right = rest
        return LogicalStateElement(first, str(op_tok).lower(), right)

    def pattern_inner(self, e):
        return e

    def every_group(self, _every, inner):
        return EveryStateElement(inner)

    def every_part(self, _every, inner):
        return EveryStateElement(inner)

    def plain_part(self, inner):
        return inner

    def every_pattern_chain(self, *parts):
        within_ms = None
        elems = []
        for p in parts:
            if isinstance(p, tuple) and p and p[0] == "within":
                within_ms = p[1]
            elif isinstance(p, Token):
                continue
            else:
                elems.append(p)
        state = elems[0]
        for nxt in elems[1:]:
            state = NextStateElement(state, nxt)
        return ("chain", state, within_ms)

    def pattern_stream(self, chain):
        _tag, state, within_ms = chain
        return StateInputStream(StateType.PATTERN, state, within_ms)

    # sequences
    def counted_seq(self, elem, *spec):
        if spec:
            lo, hi = spec[0]
            return CountStateElement(elem, lo, hi)
        return elem

    def zero_or_more(self):
        return (0, CountStateElement.ANY)

    def one_or_more(self):
        return (1, CountStateElement.ANY)

    def zero_or_one(self):
        return (0, 1)

    def absent_seq(self, _not, elem, *rest):
        wait = None
        for r in rest:
            if isinstance(r, Constant):
                wait = r.value
        return AbsentStreamStateElement(elem.stream, waiting_time_ms=wait)

    def logical_state_seq(self, first, *rest):
        if not rest:
            return first
        op_tok, right = rest
        return LogicalStateElement(first, str(op_tok).lower(), right)

    def seq_part(self, e):
        return e

    def seq_first(self, *parts):
        if len(parts) == 2:  # EVERY part
            return EveryStateElement(parts[1])
        return parts[0]

    def sequence_chain(self, *parts):
        within_ms = None
        elems = []
        for p in parts:
            if isinstance(p, tuple) and p and p[0] == "within":
                within_ms = p[1]
            else:
                elems.append(p)
        state = elems[0]
        for nxt in elems[1:]:
            state = NextStateElement(state, nxt)
        return ("seq", state, within_ms)

    def sequence_stream(self, chain):
        _tag, state, within_ms = chain
        return StateInputStream(StateType.SEQUENCE, state, within_ms)

    def state_stream(self, s):
        return s

    def query_input(self, s):
        return s

    # ---------------- select / output ----------------

    def output_attr(self, expr, *rename):
        name = None
        for r in rename:
            if isinstance(r, Token) and r.type == "NAME":
                name = str(r)
        if name is None:
            if isinstance(expr, Variable):
                name = expr.attribute
            elif isinstance(expr, AttributeFunction):
                name = expr.name
            else:
                name = "expr"
        return OutputAttribute(name, expr)

    def select_clause(self, _select, *attrs):
        if len(attrs) == 1 and isinstance(attrs[0], Token) and attrs[0].type == "STAR":
            return Selector()
        return Selector(attributes=tuple(a for a in attrs if isinstance(a, OutputAttribute)))

    def group_by_clause(self, _group, _by, *vars_):
        return tuple(vars_)

    def having_clause(self, _having, e):
        return ("having", e)

    def order_item(self, var, *order):
        o = OrderByOrder.ASC
        for t in order:
            if isinstance(t, Token) and t.type == "DESC":
                o = OrderByOrder.DESC
        return OrderByAttribute(var, o)

    def order_by_clause(self, _order, _by, *items):
        return ("order_by", tuple(items))

    def limit_clause(self, _limit, n):
        return ("limit", int(n))

    def offset_clause(self, _offset, n):
        return ("offset", int(n))

    def rate_kind(self, tok):
        return tok

    def rate_time(self, _output, *rest):
        kind = OutputRateType.ALL
        tv = rest[-1]
        for r in rest:
            if isinstance(r, Token) and r.type in ("ALL", "FIRST", "LAST"):
                kind = OutputRateType(str(r).lower())
        return OutputRate(type=kind, time_ms=tv.value)

    def rate_events(self, _output, *rest):
        kind = OutputRateType.ALL
        n = None
        for r in rest:
            if isinstance(r, Token) and r.type in ("ALL", "FIRST", "LAST"):
                kind = OutputRateType(str(r).lower())
            elif isinstance(r, Token) and r.type == "INT_LITERAL":
                n = int(r)
        return OutputRate(type=kind, event_count=n)

    def rate_snapshot(self, _output, _snapshot, _every, tv):
        return OutputRate(type=OutputRateType.SNAPSHOT, time_ms=tv.value)

    def event_type(self, tok):
        return OutputEventType[str(tok).upper()]

    def sink_target(self, tok):
        return tok

    def insert_into(self, _insert, *rest):
        etype = OutputEventType.CURRENT
        target = None
        for r in rest:
            if isinstance(r, OutputEventType):
                etype = r
            elif isinstance(r, Token) and r.type in ("NAME", "INNER_STREAM_ID", "FAULT_STREAM_ID"):
                target = str(r)
        is_fault = target.startswith("!")
        is_inner = target.startswith("#")
        if target.startswith(("#", "!")):
            target = target[1:]
        return OutputStream(OutputAction.INSERT, target_id=target,
                            event_type=etype, is_fault=is_fault,
                            is_inner=is_inner)

    def set_item(self, var, expr):
        return UpdateSetAttribute(var, expr)

    def set_clause(self, _set, *items):
        return ("set", tuple(items))

    def delete_from(self, _delete, name, *rest):
        etype, cond, _ = _parse_output_rest(rest)
        return OutputStream(OutputAction.DELETE, target_id=str(name),
                            event_type=etype, on_condition=cond)

    def update_table(self, _update, name, *rest):
        etype, cond, sets = _parse_output_rest(rest)
        return OutputStream(OutputAction.UPDATE, target_id=str(name),
                            event_type=etype, on_condition=cond, set_attributes=sets)

    def update_or_insert(self, _update, _or, _insert, _into, name, *rest):
        etype, cond, sets = _parse_output_rest(rest)
        return OutputStream(OutputAction.UPDATE_OR_INSERT, target_id=str(name),
                            event_type=etype, on_condition=cond, set_attributes=sets)

    def return_query(self, _return, *rest):
        etype = OutputEventType.CURRENT
        for r in rest:
            if isinstance(r, OutputEventType):
                etype = r
        return OutputStream(OutputAction.RETURN, event_type=etype)

    def query_output(self, o):
        return o

    @_with_meta
    def query(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        input_stream = None
        selector_parts = {"selector": Selector(), "group_by": (), "having": None,
                          "order_by": (), "limit": None, "offset": None}
        output_rate = None
        output_stream = None
        for p in rest:
            if isinstance(p, Token):
                continue
            if isinstance(p, (SingleInputStream, JoinInputStream, StateInputStream)):
                input_stream = p
            elif isinstance(p, Selector):
                selector_parts["selector"] = p
            elif isinstance(p, tuple) and p and isinstance(p[0], Variable):
                selector_parts["group_by"] = p
            elif isinstance(p, tuple) and p and p[0] == "having":
                selector_parts["having"] = p[1]
            elif isinstance(p, tuple) and p and p[0] == "order_by":
                selector_parts["order_by"] = p[1]
            elif isinstance(p, tuple) and p and p[0] == "limit":
                selector_parts["limit"] = p[1]
            elif isinstance(p, tuple) and p and p[0] == "offset":
                selector_parts["offset"] = p[1]
            elif isinstance(p, OutputRate):
                output_rate = p
            elif isinstance(p, OutputStream):
                output_stream = p
        base = selector_parts["selector"]
        selector = Selector(
            attributes=base.attributes,
            group_by=selector_parts["group_by"],
            having=selector_parts["having"],
            order_by=selector_parts["order_by"],
            limit=selector_parts["limit"],
            offset=selector_parts["offset"],
        )
        q = Query(input_stream=input_stream, selector=selector,
                  output_stream=output_stream or OutputStream(OutputAction.RETURN),
                  output_rate=output_rate, annotations=anns, loc=_loc(meta))
        pending = getattr(self, "_pending_anon", None)
        if pending:
            # desugared anonymous-stream inner queries run before the query
            # that consumes their synthetic streams
            self._pending_anon = []
            return ("queries", (*pending, q))
        return q

    # ---------------- on-demand (store) query ----------------

    def od_on(self, _on, e):
        return ("od_on", e)

    def od_within(self, _within, *exprs):
        return ("od_within", tuple(exprs))

    def od_per(self, _per, e):
        return ("od_per", e)

    def on_demand_query(self, q):
        return q

    def od_insert(self, _insert, _into, name):
        return ("od_insert", str(name))

    def od_delete_q(self, _delete, name, on):
        # `delete T on <cond>` (reference: DeleteOnDemandQueryRuntime)
        return OnDemandQuery(
            input_store_id=str(name), action=OutputAction.DELETE,
            target_id=str(name), on_condition=on[1])

    def od_update_q(self, _update, name, set_c, *rest):
        # `update T set T.a = ... [on <cond>]` (UpdateOnDemandQueryRuntime)
        on_cond = rest[0][1] if rest else None
        return OnDemandQuery(
            input_store_id=str(name), action=OutputAction.UPDATE,
            target_id=str(name), on_condition=on_cond,
            set_attributes=set_c[1])

    def od_insert_q(self, selector, _insert, _into, name):
        # standalone `select <constants> insert into T` (reference: the
        # insert OnDemandQueryRuntime with no source store)
        return OnDemandQuery(
            input_store_id=None, action=OutputAction.INSERT,
            target_id=str(name), selector=selector)

    def od_update_or_insert_q(self, selector, _update, _or, _insert, _into,
                              name, *rest):
        # `select ... update or insert into T [set ...] on <cond>`
        # (UpdateOrInsertOnDemandQueryRuntime)
        sets = ()
        on_cond = None
        for r in rest:
            if isinstance(r, tuple) and r and r[0] == "set":
                sets = r[1]
            elif isinstance(r, tuple) and r and r[0] == "od_on":
                on_cond = r[1]
        return OnDemandQuery(
            input_store_id=str(name), action=OutputAction.UPDATE_OR_INSERT,
            target_id=str(name), on_condition=on_cond,
            set_attributes=sets, selector=selector)

    def od_from(self, _from, name, *clauses):
        parts = {"selector": Selector(), "group_by": (), "having": None,
                 "order_by": (), "limit": None, "offset": None}
        on_cond = None
        within = None
        per = None
        for c in clauses:
            if isinstance(c, Selector):
                parts["selector"] = c
            elif isinstance(c, tuple) and c and isinstance(c[0], Variable):
                parts["group_by"] = c
            elif isinstance(c, tuple) and c and c[0] == "having":
                parts["having"] = c[1]
            elif isinstance(c, tuple) and c and c[0] == "order_by":
                parts["order_by"] = c[1]
            elif isinstance(c, tuple) and c and c[0] == "limit":
                parts["limit"] = c[1]
            elif isinstance(c, tuple) and c and c[0] == "offset":
                parts["offset"] = c[1]
            elif isinstance(c, tuple) and c and c[0] == "od_on":
                on_cond = c[1]
            elif isinstance(c, tuple) and c and c[0] == "od_within":
                w = c[1]
                within = (w[0], w[1] if len(w) > 1 else None)
            elif isinstance(c, tuple) and c and c[0] == "od_per":
                per = c[1]
        insert_target = None
        for c in clauses:
            if isinstance(c, tuple) and c and c[0] == "od_insert":
                insert_target = c[1]
        base = parts["selector"]
        selector = Selector(
            attributes=base.attributes, group_by=parts["group_by"],
            having=parts["having"], order_by=parts["order_by"],
            limit=parts["limit"], offset=parts["offset"])
        return OnDemandQuery(
            input_store_id=str(name), on_condition=on_cond,
            within_range=within, per=per, selector=selector,
            action=(OutputAction.INSERT if insert_target else OutputAction.RETURN),
            target_id=insert_target)

    # ---------------- partition ----------------

    def value_partition(self, expr, _of, stream):
        return ValuePartitionType(stream_id=str(stream), expression=expr)

    def range_partition(self, *parts):
        stream = str(parts[-1])
        exprs, keys = [], []
        for p in parts[:-1]:
            if isinstance(p, Expression):
                exprs.append(p)
            elif isinstance(p, Token) and p.type == "STRING_LITERAL":
                keys.append(_unquote(p))
        ranges = tuple(RangePartitionProperty(k, e) for e, k in zip(exprs, keys))
        return RangePartitionType(stream_id=stream, ranges=ranges)

    def partition_item(self, item):
        return item

    @_with_meta
    def partition(self, meta, *parts):
        anns, rest = _split_annotations(parts)
        ptypes = []
        queries = []
        for p in rest:
            if isinstance(p, (ValuePartitionType, RangePartitionType)):
                ptypes.append(p)
            elif isinstance(p, Query):
                queries.append(p)
            elif isinstance(p, tuple) and p and p[0] == "queries":
                from ..errors import SiddhiAppCreationError
                raise SiddhiAppCreationError(
                    "anonymous streams are not supported inside partitions — "
                    "define the inner query as its own stream")
        return Partition(partition_types=tuple(ptypes), queries=tuple(queries),
                         annotations=anns, loc=_loc(meta))

    def execution_element(self, e):
        return e

    # ---------------- app ----------------

    def start(self, *items):
        app = SiddhiApp()
        for item in items:
            if isinstance(item, Annotation):
                app.annotations.append(item)
            elif isinstance(item, StreamDefinition):
                app.define_stream(item)
            elif isinstance(item, TableDefinition):
                app.define_table(item)
            elif isinstance(item, WindowDefinition):
                app.define_window(item)
            elif isinstance(item, TriggerDefinition):
                app.define_trigger(item)
            elif isinstance(item, AggregationDefinition):
                app.define_aggregation(item)
            elif isinstance(item, FunctionDefinition):
                app.define_function(item)
            elif isinstance(item, Query):
                app.add_query(item)
            elif isinstance(item, tuple) and item and item[0] == "queries":
                for q in item[1]:
                    app.add_query(q)
            elif isinstance(item, Partition):
                app.add_partition(item)
        return app


def _split_annotations(parts):
    anns = tuple(p for p in parts if isinstance(p, Annotation))
    rest = [p for p in parts if not isinstance(p, Annotation)]
    return anns, rest


def _parse_output_rest(rest):
    etype = OutputEventType.CURRENT
    cond = None
    sets = ()
    for r in rest:
        if isinstance(r, OutputEventType):
            etype = r
        elif isinstance(r, tuple) and r and r[0] == "set":
            sets = r[1]
        elif isinstance(r, Expression):
            cond = r
    return etype, cond, sets
