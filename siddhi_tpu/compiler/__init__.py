"""SiddhiQL text → SiddhiApp AST (reference: siddhi-query-compiler's
SiddhiCompiler.java:63)."""

from __future__ import annotations

import functools
import os
import re

from lark import Lark
from lark.exceptions import UnexpectedInput, VisitError

from ..errors import SiddhiParserError
from ..query_api import Query, SiddhiApp, StreamDefinition
from .grammar import GRAMMAR
from .transformer import AstTransformer


@functools.lru_cache(maxsize=1)
def _parser() -> Lark:
    # propagate_positions feeds tree meta (line/column) to the transformer,
    # which stamps `loc` onto queries/definitions for lint diagnostics
    return Lark(GRAMMAR, parser="earley", lexer="dynamic", maybe_placeholders=False,
                propagate_positions=True,
                start=["start", "on_demand_query", "expression"])


def _parse_error(e: UnexpectedInput, text: str) -> SiddhiParserError:
    """UnexpectedInput → SiddhiParserError with line:column AND the offending
    source snippet (lark's get_context: the line plus a caret marker)."""
    line = getattr(e, "line", None)
    column = getattr(e, "column", None)
    if isinstance(line, int) and line < 1:  # UnexpectedEOF reports -1
        # anchor end-of-input errors to the last source line instead
        line = text.count("\n") + 1
        column = len(text.rsplit("\n", 1)[-1]) + 1
    try:
        snippet = e.get_context(text)
    except Exception:  # token-less errors have no position to excerpt
        snippet = None
    return SiddhiParserError(str(e).split("\n")[0], line, column, snippet)


_VAR_PATTERN = re.compile(r"\$\{(\w+)\}")


def update_variables(siddhi_ql: str, env: dict | None = None) -> str:
    """`${var}` substitution from env/system properties (reference:
    SiddhiCompiler.updateVariables, called from SiddhiManager.java:95)."""
    source = env if env is not None else os.environ

    def sub(m: re.Match) -> str:
        name = m.group(1)
        if name not in source:
            raise SiddhiParserError(f"no system/environment variable for ${{{name}}}")
        return source[name]

    return _VAR_PATTERN.sub(sub, siddhi_ql)


def _transform(tree):
    """Run the AST transformer, unwrapping semantic rejections
    (SiddhiAppCreationError) from lark's VisitError so callers see the real
    error type; anything else is a parse/AST bug."""
    try:
        return AstTransformer().transform(tree)
    except VisitError as e:
        from ..errors import SiddhiAppCreationError
        if isinstance(e.orig_exc, SiddhiAppCreationError):
            raise e.orig_exc from e
        raise SiddhiParserError(f"error building AST: {e.orig_exc}") from e


def parse(siddhi_ql: str) -> SiddhiApp:
    """Parse a full SiddhiQL app definition string into a SiddhiApp AST."""
    try:
        tree = _parser().parse(siddhi_ql, start="start")
    except UnexpectedInput as e:
        raise _parse_error(e, siddhi_ql) from e
    return _transform(tree)


def parse_on_demand_query(text: str):
    """Parse an on-demand (store) query — `from Store [on cond] [within a,b]
    [per d] select ...` (reference: SiddhiCompiler.parseOnDemandQuery /
    parseStoreQuery)."""
    try:
        tree = _parser().parse(text, start="on_demand_query")
    except UnexpectedInput as e:
        raise _parse_error(e, text) from e
    return _transform(tree)


def parse_expression(text: str):
    """Parse a bare SiddhiQL expression string into an Expression AST
    (used by expression windows, whose condition arrives as a string
    parameter — reference: ExpressionWindowProcessor compiles its string
    with SiddhiCompiler internals)."""
    try:
        tree = _parser().parse(text, start="expression")
    except UnexpectedInput as e:
        raise _parse_error(e, text) from e
    return _transform(tree)


def parse_query(query_text: str) -> Query:
    """Parse a single query (reference: SiddhiCompiler.parseQuery)."""
    app = parse(query_text)
    if len(app.queries) != 1:
        raise SiddhiParserError("expected exactly one query")
    return app.queries[0]


def parse_stream_definition(text: str) -> StreamDefinition:
    app = parse(text)
    if len(app.stream_definitions) != 1:
        raise SiddhiParserError("expected exactly one stream definition")
    return next(iter(app.stream_definitions.values()))


class SiddhiCompiler:
    """Facade matching the reference's static API shape."""

    parse = staticmethod(parse)
    parse_query = staticmethod(parse_query)
    parse_stream_definition = staticmethod(parse_stream_definition)
    update_variables = staticmethod(update_variables)
