"""SiddhiQL grammar (Lark, earley).

Re-derived from the language surface described by the reference grammar
(modules/siddhi-query-compiler/src/main/antlr4/.../SiddhiQL.g4, 927 lines) —
NOT a translation of it: rule names and factoring follow Lark idioms, and the
AST is built by compiler/transformer.py. Keywords are case-insensitive like
SiddhiQL. Comments: `-- line` and block comments.
"""

GRAMMAR = r'''
start: (definition | execution_element | app_annotation)*

definition: define_stream ";"?
          | define_table ";"?
          | define_window ";"?
          | define_trigger ";"?
          | define_function ";"?
          | define_aggregation ";"?

execution_element: query ";"? | partition ";"?

// ---------------- annotations ----------------
// only `@app:...` is app-level (matches the reference grammar's app_annotation)
app_annotation.5: "@" APP_KW ":" NAME ("(" annotation_body? ")")?
APP_KW: "app"i
annotation: "@" qualified_name ("(" annotation_body? ")")?
qualified_name: NAME ((":"|".") NAME)?  // `:` and `.` both separate (`@suppress.lint`)
annotation_body: annotation_item ("," annotation_item)*
annotation_item: annotation | keyed_element | bare_element
keyed_element: NAME ("." NAME)* "=" literal_value
bare_element: literal_value
literal_value: STRING_LITERAL | NUMBER_FOR_ANNOTATION | TRUE | FALSE
NUMBER_FOR_ANNOTATION: /-?\d+(\.\d+)?[fFlLdD]?/

// ---------------- definitions ----------------
define_stream: annotation* DEFINE STREAM stream_id "(" attr_list ")"
define_table: annotation* DEFINE TABLE NAME "(" attr_list ")"
define_window: annotation* DEFINE WINDOW NAME "(" attr_list ")" window_spec? output_event_kw?
window_spec: function_id "(" expr_list? ")"
output_event_kw: OUTPUT event_type EVENTS
define_trigger: annotation* DEFINE TRIGGER NAME AT trigger_at
trigger_at: EVERY time_value   -> trigger_every
          | STRING_LITERAL     -> trigger_cron_or_start
define_function: annotation* DEFINE FUNCTION NAME "[" NAME "]" RETURN attr_type FUNCTION_BODY
FUNCTION_BODY: /\{[^}]*\}/
define_aggregation: annotation* DEFINE AGGREGATION NAME FROM stream_id select_clause group_by_clause? aggregate_clause
aggregate_clause: AGGREGATE (BY variable_ref)? EVERY duration_range
duration_range: duration_name "..." duration_name     -> duration_dots
              | duration_name ("," duration_name)+    -> duration_list
              | duration_name                          -> duration_single
duration_name: NAME

attr_list: attr_def ("," attr_def)*
attr_def: NAME attr_type
attr_type: NAME

// ---------------- query ----------------
query: annotation* FROM query_input select_clause? group_by_clause? having_clause? order_by_clause? limit_clause? offset_clause? output_rate? query_output

query_input: join_stream | state_stream | standard_stream

// standard single stream (priority: a bare `S[f]#window.w()` must win over a
// single-element pattern chain)
standard_stream.10: source handler_chain
source: INNER_STREAM_ID | FAULT_STREAM_ID | stream_id | anon_stream
// anonymous stream: `from (from S select ...) ...` — desugared by the
// transformer into a synthetic stream fed by the inner query (reference:
// api/execution/query/input/stream/AnonymousInputStream.java)
anon_stream: "(" FROM query_input select_clause? group_by_clause? having_clause? order_by_clause? limit_clause? offset_clause? ")"
stream_id: NAME
INNER_STREAM_ID: /#[A-Za-z_][A-Za-z_0-9]*/
FAULT_STREAM_ID: /![A-Za-z_][A-Za-z_0-9]*/
handler_chain: stream_handler*
stream_handler: filter | stream_function_h | window_h
filter: "[" expression "]"
stream_function_h: "#" function_id_pair "(" expr_list? ")"
window_h: "#" WINDOW_KW "." function_id "(" expr_list? ")"
WINDOW_KW: "window"i
function_id_pair: NAME (":" NAME)?
function_id: NAME

// join
join_stream: join_side join_kw join_side right_unidirectional? (ON expression)? within_clause? per_clause?
join_side: source handler_chain (AS alias_name)? UNIDIRECTIONAL?
alias_name: NAME
join_kw: LEFT OUTER JOIN -> left_outer_join
       | RIGHT OUTER JOIN -> right_outer_join
       | FULL OUTER JOIN -> full_outer_join
       | (INNER)? JOIN -> inner_join
right_unidirectional: UNIDIRECTIONAL
within_clause: WITHIN time_value
per_clause: PER expression

// patterns & sequences
state_stream: every_pattern_chain                     -> pattern_stream
            | sequence_chain                          -> sequence_stream
every_pattern_chain: pattern_part (ARROW pattern_part)* within_clause?
ARROW: "->"
pattern_part: EVERY "(" pattern_inner ")" -> every_group
            | EVERY pattern_inner          -> every_part
            | pattern_inner                -> plain_part
pattern_inner: logical_state
logical_state: primary_state (AND primary_state | OR primary_state)?
primary_state: NOT event_def (FOR time_value)?   -> absent_state
             | event_def count_spec?              -> counted_state
             | "(" every_pattern_chain ")"        -> nested_chain
event_def: (event_ref "=")? source handler_chain
event_ref: NAME
count_spec: "<" INT_LITERAL ":" INT_LITERAL ">"  -> count_min_max
          | "<" INT_LITERAL ":" ">"              -> count_min
          | "<" ":" INT_LITERAL ">"              -> count_max
          | "<" INT_LITERAL ">"                  -> count_exact
sequence_chain: seq_first ("," seq_part)+ within_clause?
seq_first: (EVERY)? seq_part
seq_part: logical_state_seq
logical_state_seq: primary_seq (AND primary_seq | OR primary_seq)?
primary_seq: NOT event_def (FOR time_value)? -> absent_seq
           | event_def regex_spec?            -> counted_seq
regex_spec: "*" -> zero_or_more
          | "+" -> one_or_more
          | "?" -> zero_or_one

// select
select_clause: SELECT (STAR | output_attr ("," output_attr)*)
STAR: "*"
output_attr: expression (AS NAME)?
group_by_clause: GROUP BY variable_ref ("," variable_ref)*
having_clause: HAVING expression
order_by_clause: ORDER BY order_item ("," order_item)*
order_item: variable_ref (ASC | DESC)?
limit_clause: LIMIT INT_LITERAL
offset_clause: OFFSET INT_LITERAL

// output rate
output_rate: OUTPUT rate_kind? EVERY time_value        -> rate_time
           | OUTPUT rate_kind? EVERY INT_LITERAL EVENTS -> rate_events
           | OUTPUT SNAPSHOT EVERY time_value           -> rate_snapshot
rate_kind: ALL | FIRST | LAST

// query output
query_output: INSERT (event_type EVENTS)? INTO sink_target            -> insert_into
            | DELETE NAME (FOR event_type EVENTS)? ON expression      -> delete_from
            | UPDATE OR INSERT INTO NAME set_clause? ON expression    -> update_or_insert
            | UPDATE NAME (FOR event_type EVENTS)? set_clause? ON expression -> update_table
            | RETURN (event_type EVENTS)?                             -> return_query
sink_target: INNER_STREAM_ID | FAULT_STREAM_ID | NAME
set_clause: SET set_item ("," set_item)*
set_item: variable_ref "=" expression
event_type: CURRENT | EXPIRED | ALL

// on-demand (store) query — reference grammar rule store_query; executed via
// SiddhiAppRuntime.query() against tables/windows/aggregations
on_demand_query: od_from | od_insert_q | od_delete_q | od_update_q | od_update_or_insert_q
od_insert_q: select_clause INSERT INTO NAME
od_from: FROM NAME od_on? od_within? od_per? select_clause? group_by_clause? having_clause? order_by_clause? limit_clause? offset_clause? od_insert?
od_insert: INSERT INTO NAME
od_delete_q: DELETE NAME od_on
od_update_q: UPDATE NAME set_clause od_on?
od_update_or_insert_q: select_clause UPDATE OR INSERT INTO NAME set_clause? od_on
od_on: ON expression
od_within: WITHIN expression ("," expression)?
od_per: PER expression

// partition
partition: annotation* PARTITION WITH "(" partition_item ("," partition_item)* ")" BEGIN (query ";"?)+ END
partition_item: expression AS STRING_LITERAL (OR expression AS STRING_LITERAL)* OF stream_id -> range_partition
              | expression OF stream_id                                                       -> value_partition

// ---------------- expressions ----------------
expression: or_expr
or_expr: and_expr (OR and_expr)*
and_expr: not_expr (AND not_expr)*
not_expr: NOT not_expr -> not_op
        | in_expr
// `cond in Table` binds tighter than AND/OR but looser than comparison, so
// `S.sym == T.sym in T` is (S.sym == T.sym) in T and
// `a in T and b > 5` is And(a in T, b > 5)
in_expr: comparison IN NAME -> in_op
       | comparison
comparison: addsub (comp_op addsub)?
          | addsub IS NULL -> is_null_op
comp_op: EQ | NEQ | GTE | LTE | GT | LT
EQ: "=="
NEQ: "!="
GTE: ">="
LTE: "<="
GT: ">"
LT: "<"
addsub: muldiv (addsub_op muldiv)*
addsub_op: PLUS | MINUS
PLUS: "+"
MINUS: "-"
muldiv: unary (muldiv_op unary)*
muldiv_op: MUL | DIV | MOD_OP
MUL: "*"
DIV: "/"
MOD_OP: "%"
unary: MINUS unary -> neg
     | atom
atom: "(" expression ")"
    | function_call
    | time_value
    | constant
    | variable_ref
function_call: NAME ":" NAME "(" expr_list? ")" -> ns_function
             | NAME "(" expr_list? ")"          -> plain_function
expr_list: expression ("," expression)*

variable_ref: NAME "[" stream_index "]" "." NAME  -> indexed_variable
            | NAME "." NAME                        -> qualified_variable
            | NAME                                 -> simple_variable
stream_index: INT_LITERAL | LAST_KW
LAST_KW: "last"i

constant: STRING_LITERAL        -> string_const
        | BOOL_LITERAL          -> bool_const
        | SIGNED_FLOAT_LITERAL  -> float_const
        | SIGNED_DOUBLE_LITERAL -> double_const
        | SIGNED_LONG_LITERAL   -> long_const
        | SIGNED_INT_LITERAL    -> int_const

time_value: time_part+
time_part: INT_LITERAL time_unit
time_unit: YEARS | MONTHS | WEEKS | DAYS | HOURS | MINUTES | SECONDS | MILLISECONDS

// ---------------- keywords (case-insensitive) ----------------
DEFINE: "define"i
STREAM: "stream"i
TABLE: "table"i
WINDOW: "window"i
TRIGGER: "trigger"i
FUNCTION: "function"i
AGGREGATION: "aggregation"i
FROM: "from"i
SELECT: "select"i
GROUP: "group"i
BY: "by"i
HAVING: "having"i
ORDER: "order"i
LIMIT: "limit"i
OFFSET: "offset"i
ASC: "asc"i
DESC: "desc"i
INSERT: "insert"i
DELETE: "delete"i
UPDATE: "update"i
RETURN: "return"i
INTO: "into"i
SET: "set"i
ON: "on"i
OUTPUT: "output"i
EVENTS: "events"i
EVERY: "every"i
AT: "at"i
SNAPSHOT: "snapshot"i
CURRENT: "current"i
EXPIRED: "expired"i
ALL: "all"i
FIRST: "first"i
LAST: "last"i
JOIN: "join"i
INNER: "inner"i
OUTER: "outer"i
LEFT: "left"i
RIGHT: "right"i
FULL: "full"i
UNIDIRECTIONAL: "unidirectional"i
WITHIN: "within"i
PER: "per"i
PARTITION: "partition"i
WITH: "with"i
BEGIN: "begin"i
END: "end"i
AND: "and"i
OR: "or"i
NOT: "not"i
IS: "is"i
NULL: "null"i
IN: "in"i
FOR: "for"i
AS: "as"i
OF: "of"i
AGGREGATE: "aggregate"i

YEARS: /years?/i
MONTHS: /months?/i
WEEKS: /weeks?/i
DAYS: /days?/i
HOURS: /hours?/i
MINUTES: /min(utes?)?/i
SECONDS: /sec(onds?)?/i
MILLISECONDS: /milli(sec(onds?)?)?/i

BOOL_LITERAL: /true|false/i
TRUE: "true"i
FALSE: "false"i

NAME: /[A-Za-z_][A-Za-z_0-9]*/
SIGNED_INT_LITERAL: /-?\d+/
INT_LITERAL: /\d+/
SIGNED_LONG_LITERAL: /-?\d+[lL]/
SIGNED_FLOAT_LITERAL: /-?(\d+\.\d*|\.\d+|\d+)[fF]/
SIGNED_DOUBLE_LITERAL: /-?(\d+\.\d*|\.\d+)[dD]?|-?\d+[dD]/
STRING_LITERAL: /'[^']*'|"[^"]*"|"""(.|\n)*?"""/

LINE_COMMENT: /--[^\n]*/
BLOCK_COMMENT: "/*" /(.|\n)*?/ "*/"
%ignore LINE_COMMENT
%ignore BLOCK_COMMENT
%ignore /\s+/
'''
