"""Lock-free per-thread metrics registry.

Design: every writer thread owns a private *shard* (a plain Python list —
the smallest mutable cell) reached through an instance-level
``threading.local``. The hot path is therefore two attribute loads and a
list-element increment with no lock, no CAS, and no allocation; under the
GIL a single-writer cell can never lose an update. Readers (the /metrics
scrape, statistics_report) sum across shards — a racing read may see a
value a few increments stale, which is the standard Prometheus contract
(scrapes are snapshots, not barriers).

Histograms are fixed-bucket log-scale: 28 power-of-two microsecond buckets
(≤1 µs … ≤2²⁶ µs ≈ 67 s, last bucket = +Inf). Bucket selection is one
integer ``bit_length`` — no search, no float math — and quantile
extraction (p50/p95/p99/p99.9) linearly interpolates inside the owning
bucket, so the relative error is bounded by the ×2 bucket ratio.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Sequence

from ..util.locks import named_lock

#: number of histogram buckets: index i covers (2^(i-1), 2^i] microseconds
#: for 0 < i < 27 (index 0 = ≤1 µs); index 27 is the +Inf overflow bucket.
N_BUCKETS = 28

#: upper bounds in SECONDS for the finite buckets (Prometheus `le` values)
BUCKET_BOUNDS_S = tuple((1 << i) * 1e-6 for i in range(N_BUCKETS - 1))


def bucket_index(ns: int) -> int:
    """Log2 bucket for a duration in nanoseconds (half-open upper bounds:
    exactly 2^i µs lands in bucket i, one nanosecond more in i+1)."""
    if ns <= 1000:
        return 0
    i = ((ns + 999) // 1000 - 1).bit_length()
    return i if i < N_BUCKETS - 1 else N_BUCKETS - 1


class Counter:
    """Monotonic counter with per-thread shards."""

    __slots__ = ("_shards", "_lock", "_tls")

    def __init__(self) -> None:
        self._shards: list[list] = []
        self._lock = named_lock("telemetry.metrics.shards")
        self._tls = threading.local()

    def _cell(self) -> list:
        c = [0]
        with self._lock:
            self._shards.append(c)
        self._tls.c = c
        return c

    def inc(self, n: int = 1) -> None:
        c = getattr(self._tls, "c", None)
        if c is None:
            c = self._cell()
        c[0] += n

    def value(self):
        return sum(c[0] for c in list(self._shards))


class Gauge:
    """Last-write-wins instantaneous value (no sharding: gauges are set
    from slow paths — scrape staleness is inherent to the type)."""

    __slots__ = ("_v",)

    def __init__(self) -> None:
        self._v = 0.0

    def set(self, v) -> None:
        self._v = v

    def value(self):
        return self._v


class Histogram:
    """Fixed-bucket log-scale latency histogram with per-thread shards.

    Each shard is one flat list: N_BUCKETS bucket counts, then the
    observation count, then the duration sum in ns — a single allocation
    per (thread, series)."""

    __slots__ = ("_shards", "_lock", "_tls")

    _COUNT = N_BUCKETS
    _SUM = N_BUCKETS + 1

    def __init__(self) -> None:
        self._shards: list[list] = []
        self._lock = named_lock("telemetry.metrics.shards")
        self._tls = threading.local()

    def _cell(self) -> list:
        c = [0] * (N_BUCKETS + 2)
        with self._lock:
            self._shards.append(c)
        self._tls.c = c
        return c

    def observe_ns(self, ns: int) -> None:
        c = getattr(self._tls, "c", None)
        if c is None:
            c = self._cell()
        c[bucket_index(ns)] += 1
        c[self._COUNT] += 1
        c[self._SUM] += ns

    def observe_ns_at(self, bi: int, ns: int) -> None:
        """`observe_ns` with the bucket index precomputed — fused groups
        (core/shared.py) record N per-query series sharing ONE measured
        span, so the log2 bucket is the same for all of them and computing
        it N times was measurable at fan-out scale."""
        c = getattr(self._tls, "c", None)
        if c is None:
            c = self._cell()
        c[bi] += 1
        c[self._COUNT] += 1
        c[self._SUM] += ns

    # ---------------------------------------------------------------- readers

    def snapshot(self) -> tuple[list, int, int]:
        """(bucket_counts, count, sum_ns) merged across shards."""
        buckets = [0] * N_BUCKETS
        count = 0
        total = 0
        for c in list(self._shards):
            for i in range(N_BUCKETS):
                buckets[i] += c[i]
            count += c[self._COUNT]
            total += c[self._SUM]
        return buckets, count, total

    def count(self) -> int:
        return sum(c[self._COUNT] for c in list(self._shards))

    def percentiles(self, qs: Sequence[float] = (0.5, 0.95, 0.99, 0.999)
                    ) -> Optional[dict]:
        """{q: value_ms} via linear interpolation inside the owning log2
        bucket; None when the histogram is empty."""
        buckets, count, _ = self.snapshot()
        if count == 0:
            return None
        return {q: quantile_from_buckets(buckets, count, q) / 1e6
                for q in qs}

    def summary(self) -> dict:
        """The JSON shape statistics_report()["latency"] carries."""
        buckets, count, total = self.snapshot()
        if count == 0:
            return {"count": 0}
        return {
            "count": count,
            "mean_ms": total / count / 1e6,
            "p50_ms": quantile_from_buckets(buckets, count, 0.5) / 1e6,
            "p95_ms": quantile_from_buckets(buckets, count, 0.95) / 1e6,
            "p99_ms": quantile_from_buckets(buckets, count, 0.99) / 1e6,
            "p999_ms": quantile_from_buckets(buckets, count, 0.999) / 1e6,
        }


def quantile_from_buckets(buckets: Sequence[int], count: int,
                          q: float) -> float:
    """Quantile in NANOSECONDS from merged log2-µs bucket counts."""
    target = q * count
    cum = 0
    for i, n in enumerate(buckets):
        if n == 0:
            continue
        if cum + n >= target:
            lo = 0 if i == 0 else (1 << (i - 1)) * 1000
            if i >= N_BUCKETS - 1:  # +Inf bucket: report its lower bound
                return float(lo)
            hi = (1 << i) * 1000
            frac = (target - cum) / n
            return lo + frac * (hi - lo)
        cum += n
    return 0.0


class Family:
    """One named metric family: a label schema plus get-or-create children
    keyed by label-value tuples. Child creation takes a lock once per
    series; steady-state lookup is a dict get."""

    __slots__ = ("name", "kind", "help", "labelnames", "_children", "_lock",
                 "_ctor")

    _CTORS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, name: str, kind: str, help_text: str,
                 labelnames: Sequence[str]) -> None:
        self.name = name
        self.kind = kind
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, object] = {}
        self._lock = named_lock("telemetry.metrics.family")
        self._ctor = self._CTORS[kind]

    def labels(self, *values: str):
        key = values
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._ctor()
                    self._children[key] = child
        return child

    def samples(self) -> list[tuple[tuple, object]]:
        return list(self._children.items())


class MetricsRegistry:
    """Per-app family registry. Families are declared once (usually at app
    construction) so every always-on family renders in /metrics even before
    traffic arrives."""

    def __init__(self) -> None:
        self._families: dict[str, Family] = {}
        self._lock = named_lock("telemetry.metrics.registry")

    def _family(self, name: str, kind: str, help_text: str,
                labelnames: Sequence[str]) -> Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = Family(name, kind, help_text, labelnames)
                    self._families[name] = fam
        if fam.kind != kind or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} re-registered with a different "
                f"kind/label schema")
        return fam

    def counter(self, name: str, help_text: str,
                labelnames: Sequence[str] = ()) -> Family:
        return self._family(name, "counter", help_text, labelnames)

    def gauge(self, name: str, help_text: str,
              labelnames: Sequence[str] = ()) -> Family:
        return self._family(name, "gauge", help_text, labelnames)

    def histogram(self, name: str, help_text: str,
                  labelnames: Sequence[str] = ()) -> Family:
        return self._family(name, "histogram", help_text, labelnames)

    def collect(self) -> Iterable[Family]:
        return [self._families[k] for k in sorted(self._families)]
