"""Flight recorder: always-on evidence ring + anomaly-triggered bundles.

Aviation flight recorders don't wait for the crash to start recording —
they keep a bounded ring of the recent past, and the crash freezes it.
Same here: the recorder piggybacks on state the telemetry layer already
maintains (the recent batch-trace deque, the worst-N slow-batch ring,
the per-stage histograms) and adds only one always-on cost of its own —
a bounded deque tail of recent structured log records, fed by a
logging.Handler on the "siddhi_tpu" logger.

On a **trigger** the recorder freezes everything into a versioned JSON
**diagnostic bundle** (a directory of small JSON files — greppable,
diffable, and `python -m siddhi_tpu.doctor`-loadable):

    <dir>/<app>-<trigger>-<seq>/
      manifest.json   schema version, app, trigger kind/reason, sequence
      stats.json      full statistics_report() (includes slo, breakers,
                      compile widths, ingress stage_ms, WAL position)
      traces.json     frozen recent batch summaries + slow-batch exemplars
      logs.json       recent structured-log tail
      plan.json       plan fingerprint + per-element fingerprints + lint
      config.json     env snapshot (SIDDHI_*/JAX_PLATFORMS), version,
                      backend, device count, schema of the bundle itself

Trigger kinds: "slo_breach", "breaker_open", "recovery",
"upgrade_rollback", "dead_letter_burst", "manual" (POST
/siddhi-apps/<name>/diagnostics). A flapping breaker must not fill the
disk, so triggers pass through two gates before any I/O happens:

  per-kind cooldown   the same kind re-triggering within
                      SIDDHI_DIAG_COOLDOWN_S (default 300 s) is counted
                      but suppressed
  global min-interval any two bundles must be SIDDHI_DIAG_MIN_INTERVAL_S
                      (default 30 s) apart

and `keep_last` (default 16) oldest-first pruning bounds total disk.
`force=True` (the explicit API trigger) bypasses both gates but still
counts toward them. Bundle writes are synchronous — triggers fire from
slow paths (breach transitions, breaker trips) and the gates make them
rare — and are wrapped so a full disk can never break delivery.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import time
from collections import deque
from typing import Optional

from ..util.locks import named_lock

#: bundle format version — bump on any backwards-incompatible layout change
#: (doctor refuses versions it does not know). v1: initial format.
SCHEMA_VERSION = 1

#: recent structured-log ring size
LOG_TAIL = 128

#: dead-letter burst detection: this many dead-lettered rows inside the
#: rolling window trips a "dead_letter_burst" trigger
DEAD_LETTER_BURST = 100
DEAD_LETTER_WINDOW_S = 60.0

TRIGGER_KINDS = ("slo_breach", "breaker_open", "recovery",
                 "upgrade_rollback", "dead_letter_burst", "manual",
                 "shard_failover", "splice_failure", "tenant_quota_breach")

log = logging.getLogger("siddhi_tpu")


class _TailHandler(logging.Handler):
    """Captures the last LOG_TAIL records (WARNING and up by default) as
    plain dicts into a bounded deque — same context fields the JSON log
    formatter lifts, so bundle log tails correlate with frozen traces by
    batch_id."""

    def __init__(self, ring: deque, level: int = logging.WARNING) -> None:
        super().__init__(level=level)
        self.ring = ring

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = {
                "t": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage(),
            }
            for attr in ("app", "query", "stream", "batch_id"):
                v = getattr(record, attr, None)
                if v is not None:
                    entry[attr] = v
            self.ring.append(entry)
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


def default_bundle_dir(app_name: str) -> str:
    env = os.environ.get("SIDDHI_DIAG_DIR")
    if env:
        return os.path.join(env, app_name)
    return os.path.join(tempfile.gettempdir(), "siddhi-diagnostics",
                        app_name)


class FlightRecorder:
    """One app's recorder. Constructed by SiddhiAppRuntime.__init__ and
    attached as `ctx.recorder`; trigger hooks live in core/stream.py
    (breaker open), telemetry/slo.py via the runtime's on_breach wiring,
    core/upgrade.py (rollback), io/sink.py (dead-letter burst),
    core/app_runtime.py recover(), and service.py (manual POST)."""

    def __init__(self, runtime, bundle_dir: Optional[str] = None,
                 cooldown_s: Optional[float] = None,
                 min_interval_s: Optional[float] = None,
                 keep_last: int = 16,
                 clock=time.monotonic) -> None:
        self.runtime = runtime
        self.app = runtime.app.name
        self.bundle_dir = bundle_dir or default_bundle_dir(self.app)
        if cooldown_s is None:
            cooldown_s = float(os.environ.get("SIDDHI_DIAG_COOLDOWN_S", 300))
        if min_interval_s is None:
            min_interval_s = float(
                os.environ.get("SIDDHI_DIAG_MIN_INTERVAL_S", 30))
        self.cooldown_s = cooldown_s
        self.min_interval_s = min_interval_s
        self.keep_last = keep_last
        self.clock = clock
        self._lock = named_lock("telemetry.recorder.gate")
        self._seq = 0
        self._last_by_kind: dict[str, float] = {}
        self._last_any: Optional[float] = None
        self.triggers_total: dict[str, int] = {}
        self.suppressed_total: dict[str, int] = {}
        self.bundles_written = 0
        self.last_bundle: Optional[str] = None
        # always-on log tail
        self.log_tail: deque = deque(maxlen=LOG_TAIL)
        self._handler = _TailHandler(self.log_tail)
        log.addHandler(self._handler)
        # dead-letter burst detector state: (t, rows) within the window
        self._dead_letters: deque = deque()

    # ------------------------------------------------------------- lifecycle

    def close(self) -> None:
        log.removeHandler(self._handler)

    # -------------------------------------------------------------- triggers

    def on_dead_letter(self, rows: int) -> Optional[str]:
        """Called by io/sink.py per dead-lettered publish; trips the
        dead_letter_burst trigger when the rolling-window total crosses
        DEAD_LETTER_BURST."""
        now = self.clock()
        dq = self._dead_letters
        dq.append((now, rows))
        horizon = now - DEAD_LETTER_WINDOW_S
        while dq and dq[0][0] < horizon:
            dq.popleft()
        total = sum(n for _, n in dq)
        if total >= DEAD_LETTER_BURST:
            return self.trigger(
                "dead_letter_burst",
                reason=f"{total} rows dead-lettered in "
                       f"{DEAD_LETTER_WINDOW_S:.0f}s")
        return None

    def trigger(self, kind: str, reason: str = "",
                force: bool = False) -> Optional[str]:
        """Request a bundle. Returns the bundle path, or None when the
        de-dup/rate-limit gates suppressed it (or the write failed)."""
        now = self.clock()
        with self._lock:
            self.triggers_total[kind] = self.triggers_total.get(kind, 0) + 1
            if not force:
                last_kind = self._last_by_kind.get(kind)
                if last_kind is not None and now - last_kind < self.cooldown_s:
                    self.suppressed_total[kind] = (
                        self.suppressed_total.get(kind, 0) + 1)
                    return None
                if (self._last_any is not None
                        and now - self._last_any < self.min_interval_s):
                    self.suppressed_total[kind] = (
                        self.suppressed_total.get(kind, 0) + 1)
                    return None
            self._last_by_kind[kind] = now
            self._last_any = now
            self._seq += 1
            seq = self._seq
        try:
            path = self._write_bundle(kind, reason, seq)
            with self._lock:
                self.bundles_written += 1
                self.last_bundle = path
            log.warning("flight recorder: wrote diagnostic bundle %s "
                        "(trigger=%s%s)", path, kind,
                        f", {reason}" if reason else "",
                        extra={"app": self.app})
            return path
        except Exception:  # noqa: BLE001 — a full disk must not kill delivery
            log.exception("flight recorder: bundle write failed "
                          "(trigger=%s)", kind, extra={"app": self.app})
            return None

    # --------------------------------------------------------------- reports

    def report(self) -> dict:
        with self._lock:
            return {
                "bundle_dir": self.bundle_dir,
                "bundles_written": self.bundles_written,
                "last_bundle": self.last_bundle,
                "triggers": dict(self.triggers_total),
                "suppressed": dict(self.suppressed_total),
                "cooldown_s": self.cooldown_s,
                "min_interval_s": self.min_interval_s,
            }

    # ---------------------------------------------------------- bundle write

    def _write_bundle(self, kind: str, reason: str, seq: int) -> str:
        rt = self.runtime
        created = time.time()
        name = f"{self.app}-{kind}-{seq:04d}"
        path = os.path.join(self.bundle_dir, name)
        tmp = path + ".tmp"
        os.makedirs(tmp, exist_ok=True)

        manifest = {
            "schema_version": SCHEMA_VERSION,
            "app": self.app,
            "trigger": {"kind": kind, "reason": reason},
            "created": created,
            "seq": seq,
        }
        # stats.json: the full report — includes slo, breakers, compile
        # widths, ingress stage_ms, WAL position, recorder counters
        try:
            stats = rt.statistics_report()
        except Exception:  # noqa: BLE001
            stats = {"error": "statistics_report failed"}
        # traces.json: freeze the rings NOW (they keep rolling after).
        # `rt` may be a runtime-shaped duck type without a ctx (the front
        # tier's shard_failover bundles) — its stats section stands alone
        tele = getattr(getattr(rt, "ctx", None), "telemetry", None)
        traces = {"recent": [], "slow_batches": []}
        if tele is not None:
            try:
                traces = {"recent": tele.recent_summaries(),
                          "slow_batches": tele.slow_batches()}
            except Exception:  # noqa: BLE001
                pass
        logs = list(self.log_tail)
        plan = self._plan_section()
        config = self._config_section()

        for fname, obj in (("manifest.json", manifest),
                           ("stats.json", stats),
                           ("traces.json", traces),
                           ("logs.json", logs),
                           ("plan.json", plan),
                           ("config.json", config)):
            with open(os.path.join(tmp, fname), "w") as f:
                json.dump(obj, f, indent=1, default=str)
        if os.path.exists(path):  # stale same-name bundle: replace it
            shutil.rmtree(path, ignore_errors=True)
        os.replace(tmp, path)
        self._prune()
        return path

    def _plan_section(self) -> dict:
        out: dict = {}
        app = self.runtime.app
        try:
            from ..analysis.plan import element_fingerprints, plan_fingerprint
            out["fingerprint"] = plan_fingerprint(app)
            out["elements"] = element_fingerprints(app)
        except Exception:  # noqa: BLE001
            out["fingerprint"] = None
        try:
            from ..analysis import analyze
            out["lint"] = analyze(app).to_dict()
        except Exception:  # noqa: BLE001
            out["lint"] = None
        return out

    def _config_section(self) -> dict:
        env = {k: v for k, v in sorted(os.environ.items())
               if k.startswith("SIDDHI_") or k == "JAX_PLATFORMS"}
        cfg = {"schema_version": SCHEMA_VERSION, "env": env}
        try:
            import siddhi_tpu as pkg
            cfg["version"] = getattr(pkg, "__version__", "unknown")
        except Exception:  # noqa: BLE001
            cfg["version"] = "unknown"
        try:
            import jax
            cfg["backend"] = jax.default_backend()
            cfg["device_count"] = jax.device_count()
        except Exception:  # noqa: BLE001
            cfg["backend"] = "unknown"
            cfg["device_count"] = 0
        return cfg

    def _prune(self) -> None:
        try:
            entries = [e for e in os.listdir(self.bundle_dir)
                       if not e.endswith(".tmp")]
        except OSError:
            return
        if len(entries) <= self.keep_last:
            return
        full = sorted(os.path.join(self.bundle_dir, e) for e in entries)
        for stale in full[:len(entries) - self.keep_last]:
            shutil.rmtree(stale, ignore_errors=True)
