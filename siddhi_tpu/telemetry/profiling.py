"""Profiling hooks: SIDDHI_PROFILE trace capture + per-query time splits.

Two independent mechanisms:

SIDDHI_PROFILE=<dir>
    When set, the first SiddhiAppRuntime.start() in the process opens a
    jax.profiler trace into <dir> (viewable in TensorBoard / Perfetto) and
    the runtime that opened it closes it on shutdown. One trace per
    process — concurrent apps share the capture.

SiddhiAppRuntime.profile(n_batches)
    One-shot, per-app: arms a ProfileSession on ctx.telemetry.profile.
    For the next `n_batches` query-step invocations each query runtime
    records (host wall, device wait) where device wait is measured by a
    block_until_ready() on the post-step state — the synchronization the
    steady-state pipeline deliberately avoids, which is exactly why this is
    a bounded one-shot and not an always-on metric. report() returns the
    host/device split per query.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from ..util.locks import named_lock

log = logging.getLogger("siddhi_tpu.telemetry")

_jax_trace_lock = named_lock("telemetry.profile.jax")
_jax_trace_dir: Optional[str] = None


def maybe_start_jax_profiler() -> bool:
    """Start the process-wide jax.profiler trace if SIDDHI_PROFILE is set
    and no capture is already running. Returns True when THIS call started
    the capture (the caller then owns stop_jax_profiler())."""
    target = os.environ.get("SIDDHI_PROFILE", "").strip()
    if not target:
        return False
    global _jax_trace_dir
    with _jax_trace_lock:
        if _jax_trace_dir is not None:
            return False
        try:
            import jax
            jax.profiler.start_trace(target)
        except Exception as e:  # pragma: no cover — platform-dependent
            log.warning("SIDDHI_PROFILE=%s: trace capture unavailable: %s",
                        target, e)
            return False
        _jax_trace_dir = target
        log.info("jax.profiler trace capture -> %s", target)
        return True


def stop_jax_profiler() -> None:
    global _jax_trace_dir
    with _jax_trace_lock:
        if _jax_trace_dir is None:
            return
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            log.warning("jax.profiler stop_trace failed: %s", e)
        _jax_trace_dir = None


class ProfileSession:
    """Bounded per-query host/device split, armed on telemetry.profile."""

    def __init__(self, telemetry, n_batches: int = 32) -> None:
        self._telemetry = telemetry
        self.n_batches = int(n_batches)
        self._remaining = self.n_batches
        self._lock = named_lock("telemetry.profile.session")
        self._done = threading.Event()
        self._per_query: dict[str, list] = {}  # [batches, host_ns, wait_ns]
        if self._remaining <= 0:
            self._done.set()

    @property
    def active(self) -> bool:
        return not self._done.is_set()

    def record(self, query: str, host_ns: int, device_wait_ns: int) -> None:
        with self._lock:
            if self._done.is_set():
                return
            cell = self._per_query.get(query)
            if cell is None:
                cell = self._per_query[query] = [0, 0, 0]
            cell[0] += 1
            cell[1] += host_ns
            cell[2] += device_wait_ns
            self._remaining -= 1
            if self._remaining <= 0:
                self._disarm()

    def _disarm(self) -> None:
        if self._telemetry is not None and self._telemetry.profile is self:
            self._telemetry.profile = None
        self._done.set()

    def stop(self) -> None:
        with self._lock:
            self._disarm()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def report(self) -> dict:
        """{query: {batches, host_ms, device_wait_ms, device_fraction}} —
        host_ms includes the device wait (it is wall time on the controller
        thread); device_fraction = wait / host."""
        with self._lock:
            out = {}
            for q, (n, host, wait) in sorted(self._per_query.items()):
                out[q] = {
                    "batches": n,
                    "host_ms": host / 1e6,
                    "device_wait_ms": wait / 1e6,
                    "device_fraction": (wait / host) if host else 0.0,
                }
            return out
