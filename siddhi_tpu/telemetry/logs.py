"""SIDDHI_LOG_FORMAT=json — one-line structured log records.

Emits each record as a single JSON object: ts (epoch seconds), level,
logger, event (the formatted message), plus any of app/query/stream passed
via logging's `extra=` mechanism, and exc on exceptions. Keeps service
logs machine-parseable next to /metrics without changing any call site —
the default (unset / "text") leaves logging exactly as it was.
"""

from __future__ import annotations

import json
import logging
import os

#: record attrs lifted into the JSON object when present (set via extra=)
_CONTEXT_ATTRS = ("app", "query", "stream", "batch_id")


class JsonLogFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "event": record.getMessage(),
        }
        for attr in _CONTEXT_ATTRS:
            v = getattr(record, attr, None)
            if v is not None:
                out[attr] = v
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def log_format() -> str:
    return os.environ.get("SIDDHI_LOG_FORMAT", "text").strip().lower()


def configure_logging(level: int = logging.INFO) -> None:
    """Install the JSON formatter on the root handlers when
    SIDDHI_LOG_FORMAT=json; no-op otherwise. Idempotent."""
    if log_format() != "json":
        return
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=level)
    for handler in root.handlers:
        if not isinstance(handler.formatter, JsonLogFormatter):
            handler.setFormatter(JsonLogFormatter())
