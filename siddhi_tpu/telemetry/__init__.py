"""Always-on, low-overhead observability layer (ISSUE 7).

The package threads one measurement substrate through the whole pipeline:

  metrics.py     lock-free per-thread metrics registry — counters, gauges,
                 fixed-bucket log-scale latency histograms with
                 p50/p95/p99/p99.9 extraction. Writers touch only a shard
                 owned by their thread (no lock, no CAS on the hot path);
                 readers sum shards at scrape time.
  tracing.py     batch tracing — a monotonically increasing batch ID minted
                 at ingress and carried through delivery, per-stage span
                 timings (accept→stage→H2D→device→sink) into per-stage
                 histograms, plus a bounded worst-N slow-batch exemplar ring
                 surfaced in statistics_report()["slow_batches"].
  prometheus.py  text-exposition rendering for GET /metrics (hand-rolled —
                 no prometheus_client dependency) + a conformance validator
                 used by tests and the CI smoke.
  profiling.py   SIDDHI_PROFILE=<dir> jax.profiler trace capture and the
                 SiddhiAppRuntime.profile(n_batches) host/device time split.
  logs.py        SIDDHI_LOG_FORMAT=json one-line structured log records.
  slo.py         declarative objectives (@app:slo / @slo) evaluated with
                 multi-window burn rates on a virtual-clock-testable engine
                 (ISSUE 10); surfaced via statistics_report()["slo"],
                 siddhi_slo_* families, and GET /slo.
  recorder.py    flight recorder — always-on evidence rings frozen into
                 versioned diagnostic bundles on anomaly triggers (SLO
                 breach, breaker open, recovery, upgrade rollback,
                 dead-letter burst, manual POST), rate-limited + de-duped;
                 analyzed offline by `python -m siddhi_tpu.doctor`.

Gating: SIDDHI_TELEMETRY=0 turns span/histogram recording off (the <5%
overhead budget is measured by bench.py's e2e_ingress config and guarded by
tests/test_telemetry.py); default is ON — the whole point is that production
always has the data.
"""

from __future__ import annotations

import os

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .recorder import SCHEMA_VERSION, FlightRecorder
from .slo import Objective, SloEngine, slo_engine_from_app
from .tracing import AppTelemetry, BatchTrace

__all__ = [
    "AppTelemetry",
    "BatchTrace",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Objective",
    "SCHEMA_VERSION",
    "SloEngine",
    "slo_engine_from_app",
    "telemetry_enabled",
]


def telemetry_enabled() -> bool:
    """Process-wide default for new apps: SIDDHI_TELEMETRY=0 disables the
    always-on span/histogram recording (overhead A/B runs flip this)."""
    return os.environ.get("SIDDHI_TELEMETRY", "1").strip() != "0"
