"""Prometheus text-exposition (version 0.0.4) rendering + validation.

Hand-rolled on purpose: the container bakes no prometheus_client, and the
format is small enough that owning the renderer keeps the scrape path
dependency-free. Two consumers:

  render_manager(manager)   GET /metrics body — every app's registry
                            families plus an adapter over the pre-existing
                            Statistics counters (ingress drops, breakers,
                            recovery, ingress pipeline), all labelled with
                            app/stream/query.
  validate_exposition(text) conformance checker (metric-name/label-name
                            grammar, escaping, TYPE placement, histogram
                            bucket ordering, _count/_sum consistency) used
                            by tests/test_telemetry.py and the CI smoke.

Scrape-safety: rendering never takes the service lock or the controller
lock — it reads GIL-atomic dict snapshots, so a wedged deploy or a slow
device step cannot wedge the scrape.
"""

from __future__ import annotations

import re

from .metrics import BUCKET_BOUNDS_S, Counter, Family, Gauge, Histogram

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: families every running deployment must expose (the CI smoke fails if a
#: scrape during traffic is missing one)
ALWAYS_ON_FAMILIES = (
    "siddhi_app_up",
    "siddhi_batches_total",
    "siddhi_events_total",
    "siddhi_stage_latency_seconds",
    "siddhi_query_latency_seconds",
    "siddhi_build_info",
    "siddhi_app_uptime_seconds",
    "siddhi_event_time_lag_seconds",
    "siddhi_watermark_lag_seconds",
    "siddhi_late_events_total",
    "siddhi_slo_breaches_total",
    "siddhi_cost_predicted_state_bytes",
    "siddhi_cost_compile_ladder",
    "siddhi_tenant_device_ms_total",
    "siddhi_tenant_queries",
    "siddhi_splices_total",
    "siddhi_splice_retrace_ms",
)


def _build_info() -> tuple[str, str, str]:
    """(version, backend, device_count) — resolved lazily and cached; the
    backend query initializes JAX, which must not happen at import time."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        try:
            import siddhi_tpu as pkg
            version = getattr(pkg, "__version__", "unknown")
        except Exception:  # noqa: BLE001 — partial import during teardown
            version = "unknown"
        try:
            import jax
            backend = jax.default_backend()
            devices = str(jax.device_count())
        except Exception:  # noqa: BLE001
            backend, devices = "unknown", "0"
        _BUILD_INFO = (version, backend, devices)
    return _BUILD_INFO


_BUILD_INFO = None


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Exposition:
    """Accumulates samples per family across apps so each metric name gets
    exactly one HELP/TYPE block (required by the format)."""

    def __init__(self) -> None:
        self._fams: dict[str, tuple[str, str, tuple]] = {}
        self._samples: dict[str, list[str]] = {}
        self._order: list[str] = []

    def declare(self, name: str, kind: str, help_text: str,
                labelnames: tuple) -> None:
        if name not in self._fams:
            self._fams[name] = (kind, help_text, labelnames)
            self._samples[name] = []
            self._order.append(name)

    def add(self, name: str, labelvalues: tuple, value) -> None:
        kind, _, labelnames = self._fams[name]
        self._samples[name].append(
            f"{name}{_labels_str(labelnames, labelvalues)} {_fmt(value)}")

    def add_histogram(self, name: str, labelvalues: tuple,
                      hist: Histogram) -> None:
        _, _, labelnames = self._fams[name]
        buckets, count, total_ns = hist.snapshot()
        cum = 0
        for i, bound in enumerate(BUCKET_BOUNDS_S):
            cum += buckets[i]
            ls = _labels_str(labelnames + ("le",),
                             labelvalues + (repr(bound),))
            self._samples[name].append(f"{name}_bucket{ls} {_fmt(cum)}")
        ls = _labels_str(labelnames + ("le",), labelvalues + ("+Inf",))
        self._samples[name].append(f"{name}_bucket{ls} {_fmt(count)}")
        plain = _labels_str(labelnames, labelvalues)
        self._samples[name].append(f"{name}_sum{plain} {total_ns / 1e9!r}")
        self._samples[name].append(f"{name}_count{plain} {_fmt(count)}")

    def render(self) -> str:
        out: list[str] = []
        for name in self._order:
            kind, help_text, _ = self._fams[name]
            out.append(f"# HELP {name} {_escape_help(help_text)}")
            out.append(f"# TYPE {name} {kind}")
            out.extend(self._samples[name])
        out.append("")
        return "\n".join(out)


def _add_family(exp: _Exposition, fam: Family, app: str) -> None:
    exp.declare(fam.name, fam.kind, fam.help, ("app",) + fam.labelnames)
    for labelvalues, child in fam.samples():
        if isinstance(child, Histogram):
            exp.add_histogram(fam.name, (app,) + labelvalues, child)
        elif isinstance(child, (Counter, Gauge)):
            exp.add(fam.name, (app,) + labelvalues, child.value())


def _add_dict_counter(exp: _Exposition, name: str, help_text: str,
                      app: str, label: str, d: dict) -> None:
    exp.declare(name, "counter", help_text, ("app", label))
    for k, v in list(d.items()):
        exp.add(name, (app, k), v)


def _stats_families(exp: _Exposition, app: str, runtime) -> None:
    """Adapter: export the pre-existing Statistics counters (tracked
    regardless of level) under the same namespace as the registry metrics —
    the 'one API' the JSON report and the scrape share."""
    st = runtime.ctx.statistics
    exp.declare("siddhi_app_up", "gauge",
                "1 while the app runtime reports state=running", ("app",))
    try:
        state = runtime.health().get("state")
    except Exception:  # pragma: no cover — mid-shutdown race
        state = None
    exp.add("siddhi_app_up", (app,), 1 if state == "running" else 0)

    # build/identity + uptime (always-on; the on-call first-glance pair)
    version, backend, devices = _build_info()
    exp.declare("siddhi_build_info", "gauge",
                "Engine build/runtime identity (value is always 1)",
                ("app", "version", "backend", "devices"))
    exp.add("siddhi_build_info", (app, version, backend, devices), 1)
    exp.declare("siddhi_app_uptime_seconds", "gauge",
                "Seconds since the app's statistics epoch (start or reset)",
                ("app",))
    import time as _time
    exp.add("siddhi_app_uptime_seconds", (app,),
            max(_time.time() - st.started_at, 0.0))

    # static cost model (analysis/cost.py): the prediction the admission
    # gate priced this app at — pair with live state for drift alerting
    exp.declare("siddhi_cost_predicted_state_bytes", "gauge",
                "Statically predicted device-resident state bytes "
                "(analysis/cost.py; SL501 admission control)", ("app",))
    exp.declare("siddhi_cost_compile_ladder", "gauge",
                "Statically predicted compile-ladder size (executables "
                "across shape buckets x queries x steps)", ("app",))
    try:
        cost = runtime.cost_report
        pred_state = cost.get("predicted_state_bytes", 0)
        pred_compiles = cost.get("predicted_compiles", 0)
    except Exception:  # advisory — a scrape must never fail on the model
        pred_state = pred_compiles = 0
    exp.add("siddhi_cost_predicted_state_bytes", (app,), pred_state)
    exp.add("siddhi_cost_compile_ladder", (app,), pred_compiles)

    # SLO engine (telemetry/slo.py): compliance + burn per objective
    exp.declare("siddhi_slo_compliance_ratio", "gauge",
                "Fraction of fast-window observations meeting the objective",
                ("app", "objective"))
    exp.declare("siddhi_slo_burn_rate", "gauge",
                "Error-budget burn rate per window (1.0 = burning exactly "
                "the budget)", ("app", "objective", "window"))
    exp.declare("siddhi_slo_breaches_total", "counter",
                "Objective transitions into the breached state",
                ("app", "objective"))
    eng = getattr(runtime, "slo_engine", None)
    if eng is not None:
        for oid, rep in eng.report()["objectives"].items():
            exp.add("siddhi_slo_compliance_ratio", (app, oid),
                    rep["fast"].get("compliance", 1.0))
            exp.add("siddhi_slo_burn_rate", (app, oid, "fast"),
                    rep["fast"].get("burn_rate", 0.0))
            exp.add("siddhi_slo_burn_rate", (app, oid, "slow"),
                    rep["slow"].get("burn_rate", 0.0))
            exp.add("siddhi_slo_breaches_total", (app, oid), rep["breaches"])

    # flight recorder (telemetry/recorder.py): trigger/bundle counters
    rec = getattr(runtime.ctx, "recorder", None)
    if rec is not None:
        rrep = rec.report()
        exp.declare("siddhi_diag_bundles_total", "counter",
                    "Diagnostic bundles written by the flight recorder",
                    ("app",))
        exp.add("siddhi_diag_bundles_total", (app,), rrep["bundles_written"])
        exp.declare("siddhi_diag_triggers_total", "counter",
                    "Flight-recorder trigger requests by kind", ("app",
                                                                 "kind"))
        exp.declare("siddhi_diag_suppressed_total", "counter",
                    "Triggers suppressed by de-dup/rate-limit gates",
                    ("app", "kind"))
        for kind, n in rrep["triggers"].items():
            exp.add("siddhi_diag_triggers_total", (app, kind), n)
        for kind, n in rrep["suppressed"].items():
            exp.add("siddhi_diag_suppressed_total", (app, kind), n)

    _add_dict_counter(exp, "siddhi_compiles_total",
                      "XLA compiles of jitted query steps (trace-time exact)",
                      app, "query", st.compiles)
    _add_dict_counter(exp, "siddhi_sink_retries_total",
                      "Sink reconnect/publish retries", app, "stream",
                      st.sink_retries)
    _add_dict_counter(exp, "siddhi_sink_dead_letters_total",
                      "Events dead-lettered to the error store", app,
                      "stream", st.sink_dead_letters)
    _add_dict_counter(exp, "siddhi_sink_dropped_total",
                      "Events dropped by sinks (on.error=LOG)", app,
                      "stream", st.sink_dropped)
    _add_dict_counter(exp, "siddhi_source_retries_total",
                      "Source reconnect attempts", app, "stream",
                      st.source_retries)
    _add_dict_counter(exp, "siddhi_backpressure_pauses_total",
                      "Source pause() calls on high-watermark crossings",
                      app, "stream", st.bp_pauses)
    _add_dict_counter(exp, "siddhi_backpressure_resumes_total",
                      "Source resume() calls on low-watermark crossings",
                      app, "stream", st.bp_resumes)
    _add_dict_counter(exp, "siddhi_overflow_rows_total",
                      "Rows lost to fixed device capacities", app,
                      "structure", st.overflow)
    _add_dict_counter(exp, "siddhi_breaker_failures_total",
                      "Query failures counted toward breaker trips", app,
                      "query", st.breaker_failures)
    _add_dict_counter(exp, "siddhi_breaker_opens_total",
                      "Circuit breaker open transitions", app, "query",
                      st.breaker_opens)
    _add_dict_counter(exp, "siddhi_breaker_diverted_rows_total",
                      "Rows diverted instead of dispatched to a broken "
                      "query", app, "query", st.breaker_diverted)

    exp.declare("siddhi_staged_depth_hwm", "gauge",
                "High-watermark of staged rows per stream", ("app", "stream"))
    for k, v in list(st.queue_hwm.items()):
        exp.add("siddhi_staged_depth_hwm", (app, k), v)

    exp.declare("siddhi_ingress_dropped_total", "counter",
                "Rows shed/diverted by bounded ingress, by policy",
                ("app", "stream", "policy"))
    for stream, per in list(st.ingress_dropped.items()):
        for policy, n in list(per.items()):
            exp.add("siddhi_ingress_dropped_total", (app, stream, policy), n)

    exp.declare("siddhi_recoveries_total", "counter",
                "recover() completions", ("app",))
    exp.add("siddhi_recoveries_total", (app,), st.recoveries)
    exp.declare("siddhi_wal_replayed_total", "counter",
                "Lifetime events re-sent by recover()", ("app",))
    exp.add("siddhi_wal_replayed_total", (app,), st.wal_replayed)

    # blue-green upgrade / historical replay (core/upgrade.py)
    exp.declare("siddhi_upgrades_total", "counter",
                "Committed blue-green hot-swaps", ("app",))
    exp.add("siddhi_upgrades_total", (app,), st.upgrades)
    exp.declare("siddhi_upgrade_rollbacks_total", "counter",
                "Hot-swaps that failed pre-commit and rolled back to v1",
                ("app",))
    exp.add("siddhi_upgrade_rollbacks_total", (app,), st.upgrade_rollbacks)
    exp.declare("siddhi_upgrade_cutover_pause_ms", "gauge",
                "Last hot-swap's source-paused (cutover) wall time", ("app",))
    exp.add("siddhi_upgrade_cutover_pause_ms", (app,),
            st.upgrade_cutover_pause_ms)
    exp.declare("siddhi_upgrade_wal_replayed_total", "counter",
                "Journal-tail events replayed into v2 during hot-swaps",
                ("app",))
    exp.add("siddhi_upgrade_wal_replayed_total", (app,),
            st.upgrade_wal_replayed)
    exp.declare("siddhi_replay_runs_total", "counter",
                "Historical WAL replay runs", ("app",))
    exp.add("siddhi_replay_runs_total", (app,), st.replay_runs)
    exp.declare("siddhi_replay_events_total", "counter",
                "Lifetime events driven by historical WAL replay", ("app",))
    exp.add("siddhi_replay_events_total", (app,), st.replay_events)

    # multi-query shared execution (core/shared.py optimizer report)
    opt = getattr(runtime, "optimizer_report", None) or {}
    groups = getattr(runtime, "shared_groups", ()) or ()
    exp.declare("siddhi_optimizer_enabled", "gauge",
                "1 when the multi-query optimizer rewrote this app", ("app",))
    exp.add("siddhi_optimizer_enabled", (app,),
            1 if opt.get("enabled") else 0)
    for name, help_text, key in (
            ("siddhi_optimizer_groups", "Shared step groups formed",
             "groups"),
            ("siddhi_optimizer_queries_fused",
             "Queries executing inside shared compiled steps",
             "queries_fused"),
            ("siddhi_optimizer_cse_hits",
             "Subexpressions shared across fused group members",
             "cse_hits"),
            ("siddhi_optimizer_pushdowns",
             "Predicates pushed ahead of windows by the optimizer",
             "pushdowns"),
            ("siddhi_optimizer_pane_candidates",
             "Span-correlated window aggregates sharing one traced step",
             "pane_candidates")):
        exp.declare(name, "gauge", help_text, ("app",))
        exp.add(name, (app,), opt.get(key, 0))
    exp.declare("siddhi_optimizer_compiles_avoided_total", "counter",
                "Per-query XLA compiles avoided by fused group compiles",
                ("app",))
    exp.add("siddhi_optimizer_compiles_avoided_total", (app,),
            sum(st.compiles.get(g.name, 0) * (len(g.members) - 1)
                for g in groups))

    # parallel-ingress pipeline gauges/counters (core/ingress.py)
    exp.declare("siddhi_ingress_pipeline_rows_total", "counter",
                "Rows accepted by the parallel ingress pipeline",
                ("app", "stream"))
    exp.declare("siddhi_ingress_pipeline_batches_total", "counter",
                "Batches delivered by the parallel ingress pipeline",
                ("app", "stream"))
    exp.declare("siddhi_ingress_worker_utilization", "gauge",
                "Decode/intern worker busy fraction", ("app", "stream"))
    exp.declare("siddhi_ingress_ring_depth_hwm", "gauge",
                "Columnar ring depth high-watermark", ("app", "stream"))
    exp.declare("siddhi_ingress_stage_seconds_total", "counter",
                "Cumulative wall time per ingress pipeline stage",
                ("app", "stream", "stage"))
    for sid, j in list(getattr(runtime, "junctions", {}).items()):
        p = getattr(j, "_pipeline", None)
        if p is None:
            continue
        snap = p.stats_snapshot()
        exp.add("siddhi_ingress_pipeline_rows_total", (app, sid),
                snap["rows_in"])
        exp.add("siddhi_ingress_pipeline_batches_total", (app, sid),
                snap["batches_delivered"])
        exp.add("siddhi_ingress_worker_utilization", (app, sid),
                snap["worker_utilization"])
        exp.add("siddhi_ingress_ring_depth_hwm", (app, sid),
                snap["ring_depth_hwm"])
        for stage, cell in snap["stage_ms"].items():
            exp.add("siddhi_ingress_stage_seconds_total", (app, sid, stage),
                    cell["total_ms"] / 1e3)


def _plane_families(exp: _Exposition, app: str, plane) -> None:
    """Shard-plane routing/skew families (parallel/shard_plane.py). The
    replicas themselves export the full per-app family set labelled
    `app="<name>@s<i>"`; these are the plane-level extras."""
    exp.declare("siddhi_shard_count", "gauge",
                "Replicas in the sharded execution plane", ("app",))
    exp.add("siddhi_shard_count", (app,), plane.n_shards)
    exp.declare("siddhi_shard_epoch", "gauge",
                "Current shard-assignment epoch (bumps on rebalance)",
                ("app",))
    exp.add("siddhi_shard_epoch", (app,), plane.epoch)
    exp.declare("siddhi_shard_rebalances_total", "counter",
                "Committed rebalance() epoch swaps", ("app",))
    exp.add("siddhi_shard_rebalances_total", (app,), plane.rebalances)
    exp.declare("siddhi_shard_routed_rows_total", "counter",
                "Rows routed to each shard this epoch", ("app", "shard"))
    skew = plane.router.skew_report()
    for shard, n in skew["per_shard"].items():
        exp.add("siddhi_shard_routed_rows_total", (app, shard), n)
    exp.declare("siddhi_shard_imbalance_ratio", "gauge",
                "Max shard load over the even-split ideal (the rebalance "
                "trigger)", ("app",))
    exp.add("siddhi_shard_imbalance_ratio", (app,), skew["imbalance"])


#: families a front-tier router exposes on every scrape, even with zero
#: traffic and zero hosts (tests/test_shard_failover.py asserts these the
#: way the CI smoke asserts ALWAYS_ON_FAMILIES against the main service —
#: deliberately a SEPARATE tuple: the plain service never exports them)
FRONT_TIER_ALWAYS_ON = (
    "siddhi_shard_failovers_total",
    "siddhi_router_spool_depth",
    "siddhi_router_spooled_frames_total",
    "siddhi_router_host_up",
    "siddhi_router_stale_epoch_total",
)


def render_front_tier(front) -> str:
    """/metrics body for one FrontTier router (parallel/front_tier.py).
    Lock-light: reads the tier's GIL-atomic counters and the same
    statistics snapshot the JSON report serves."""
    exp = _Exposition()
    stats = front.statistics_report()
    ft = stats["front_tier"]
    app = front.name

    exp.declare("siddhi_shard_failovers_total", "counter",
                "Completed shard takeovers (host death -> adoption commit)",
                ("app",))
    exp.add("siddhi_shard_failovers_total", (app,), ft["failovers_total"])
    exp.declare("siddhi_router_spool_depth", "gauge",
                "Frames durably spooled and awaiting replay, per shard",
                ("app", "shard"))
    for i in range(front.n_shards):
        exp.add("siddhi_router_spool_depth", (app, f"s{i}"),
                front._spool_frames[i])
    exp.declare("siddhi_router_spooled_frames_total", "counter",
                "Lifetime frames written to the durable router spool",
                ("app",))
    exp.add("siddhi_router_spooled_frames_total", (app,),
            ft["spooled_frames_total"])
    exp.declare("siddhi_router_host_up", "gauge",
                "1 while the worker host answers heartbeats", ("app",
                                                               "host"))
    for url, h in ft["hosts"].items():
        exp.add("siddhi_router_host_up", (app, url), 1 if h["up"] else 0)
    exp.declare("siddhi_router_stale_epoch_total", "counter",
                "Frames rejected by workers with 409 stale-epoch/not-owner "
                "(each is recounted and re-routed, never lost)", ("app",))
    exp.add("siddhi_router_stale_epoch_total", (app,),
            ft["stale_epoch_rejections"])

    exp.declare("siddhi_router_rows_total", "counter",
                "Rows through the front tier by outcome (the conservation "
                "identity: sent == delivered + replayed + diverted + "
                "pending)", ("app", "outcome"))
    cons = stats["conservation"]
    for outcome, key in (("sent", "sent"), ("delivered", "delivered"),
                         ("replayed", "spool_replayed"),
                         ("diverted", "diverted")):
        exp.add("siddhi_router_rows_total", (app, outcome), cons[key])
    exp.declare("siddhi_router_reroutes_total", "counter",
                "Frames re-dispatched after a 409 view refresh", ("app",))
    exp.add("siddhi_router_reroutes_total", (app,), ft["reroutes"])
    exp.declare("siddhi_router_forward_errors_total", "counter",
                "Transport-level forward failures (pre-retry)", ("app",))
    exp.add("siddhi_router_forward_errors_total", (app,),
            ft["forward_errors"])
    exp.declare("siddhi_router_deduped_frames_total", "counter",
                "Spool-replay frames skipped as already journaled "
                "(lost-ack dedupe)", ("app",))
    exp.add("siddhi_router_deduped_frames_total", (app,),
            ft["deduped_frames"])
    exp.declare("siddhi_router_unowned_slots", "gauge",
                "Slots whose shard has no live owner (frames divert to "
                "the error store)", ("app",))
    exp.add("siddhi_router_unowned_slots", (app,),
            len(ft["unowned_slots"]))
    exp.declare("siddhi_shard_epoch", "gauge",
                "Current shard-assignment epoch (bumps on rebalance)",
                ("app",))
    exp.add("siddhi_shard_epoch", (app,), ft["epoch"])

    rec = stats.get("recorder") or {}
    exp.declare("siddhi_diag_bundles_total", "counter",
                "Diagnostic bundles written by the flight recorder",
                ("app",))
    exp.add("siddhi_diag_bundles_total", (app,),
            rec.get("bundles_written", 0))
    exp.declare("siddhi_diag_triggers_total", "counter",
                "Flight-recorder trigger requests by kind", ("app", "kind"))
    for kind, n in (rec.get("triggers") or {}).items():
        exp.add("siddhi_diag_triggers_total", (app, kind), n)
    return exp.render()


def render_manager(manager) -> str:
    """Full /metrics body for every deployed app. Lock-free: iterates a
    point-in-time snapshot of the runtime table."""
    exp = _Exposition()
    # declare the always-on registry families even with zero apps deployed
    # (a fresh service must still expose its schema)
    from .tracing import AppTelemetry
    runtimes = list(getattr(manager, "runtimes", {}).items())
    if not runtimes:
        probe = AppTelemetry("", enabled=False)
        for fam in probe.registry.collect():
            exp.declare(fam.name, fam.kind, fam.help,
                        ("app",) + fam.labelnames)
        exp.declare("siddhi_app_up", "gauge",
                    "1 while the app runtime reports state=running", ("app",))
        exp.declare("siddhi_build_info", "gauge",
                    "Engine build/runtime identity (value is always 1)",
                    ("app", "version", "backend", "devices"))
        exp.declare("siddhi_app_uptime_seconds", "gauge",
                    "Seconds since the app's statistics epoch (start or "
                    "reset)", ("app",))
        exp.declare("siddhi_slo_breaches_total", "counter",
                    "Objective transitions into the breached state",
                    ("app", "objective"))
        exp.declare("siddhi_cost_predicted_state_bytes", "gauge",
                    "Statically predicted device-resident state bytes "
                    "(analysis/cost.py; SL501 admission control)", ("app",))
        exp.declare("siddhi_cost_compile_ladder", "gauge",
                    "Statically predicted compile-ladder size (executables "
                    "across shape buckets x queries x steps)", ("app",))
    for name, rt in runtimes:
        if getattr(rt, "is_shard_plane", False):
            # one full family set PER REPLICA (app="<name>@s<i>") + the
            # plane-level routing/skew extras under the plane's own name
            _plane_families(exp, name, rt)
            for i, srt in enumerate(rt.shards):
                if srt is None:
                    continue
                sub = f"{name}@s{i}"
                tele = getattr(srt.ctx, "telemetry", None)
                if tele is not None:
                    for fam in tele.registry.collect():
                        _add_family(exp, fam, sub)
                _stats_families(exp, sub, srt)
            continue
        tele = getattr(rt.ctx, "telemetry", None)
        if tele is not None:
            for fam in tele.registry.collect():
                _add_family(exp, fam, name)
        _stats_families(exp, name, rt)
    return exp.render()


# --------------------------------------------------------------- validation

def validate_exposition(text: str) -> list[str]:
    """Return a list of conformance errors (empty = valid).

    Checks the subset of the 0.0.4 text format a scraper relies on:
    metric/label name grammar, label escaping, one TYPE per family placed
    before its samples, parseable sample values, histogram `le` ordering
    ending in +Inf, and `_count` == the +Inf bucket.
    """
    errors: list[str] = []
    typed: dict[str, str] = {}
    seen_samples: set[str] = set()
    hist_buckets: dict[str, list[tuple[str, float]]] = {}
    hist_counts: dict[str, float] = {}

    sample_re = re.compile(
        r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?P<labels>\{.*\})?"
        r" (?P<value>[^ ]+)( [0-9]+)?$")
    label_re = re.compile(
        r'(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"')

    if text and not text.endswith("\n"):
        errors.append("exposition must end with a newline")

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4:
                errors.append(f"line {lineno}: malformed TYPE line")
                continue
            _, _, name, kind = parts
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                errors.append(f"line {lineno}: unknown type {kind!r}")
            if name in typed:
                errors.append(f"line {lineno}: duplicate TYPE for {name!r}")
            typed[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        if not _NAME_RE.match(name):
            errors.append(f"line {lineno}: bad metric name {name!r}")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name[: -len(suffix)] if name.endswith(suffix) else None
            if stripped and typed.get(stripped) == "histogram":
                base = stripped
                break
        if base not in typed and name not in typed:
            errors.append(
                f"line {lineno}: sample for {name!r} has no TYPE line")
        labels = m.group("labels")
        le_val = None
        if labels:
            body = labels[1:-1]
            consumed = label_re.sub("", body).replace(",", "").strip()
            if consumed:
                errors.append(
                    f"line {lineno}: malformed labels {labels!r}")
            for lm in label_re.finditer(body):
                lname = lm.group("name")
                if not _LABEL_RE.match(lname):
                    errors.append(
                        f"line {lineno}: bad label name {lname!r}")
                raw = lm.group("value")
                if re.search(r'(?<!\\)(?:\\\\)*"', raw):
                    errors.append(
                        f"line {lineno}: unescaped quote in label value")
                if lname == "le":
                    le_val = raw
        try:
            value = float(m.group("value"))
        except ValueError:
            if m.group("value") not in ("+Inf", "-Inf", "NaN"):
                errors.append(
                    f"line {lineno}: unparseable value "
                    f"{m.group('value')!r}")
                continue
            value = float("inf")
        key = f"{name}{labels or ''}"
        if key in seen_samples:
            errors.append(f"line {lineno}: duplicate sample {key!r}")
        seen_samples.add(key)
        if name.endswith("_bucket") and typed.get(base) == "histogram":
            series = f"{base}{_strip_le(labels)}"
            hist_buckets.setdefault(series, []).append(
                (le_val or "", value))
        if name.endswith("_count") and typed.get(base) == "histogram":
            hist_counts[f"{base}{labels or ''}"] = value

    for series, buckets in hist_buckets.items():
        bounds = []
        for le, v in buckets:
            bounds.append((float("inf") if le == "+Inf" else float(le), v))
        if not bounds or bounds[-1][0] != float("inf"):
            errors.append(f"{series}: histogram missing le=\"+Inf\" bucket")
            continue
        for (b1, v1), (b2, v2) in zip(bounds, bounds[1:]):
            if b2 < b1:
                errors.append(f"{series}: le bounds not sorted")
            if v2 < v1:
                errors.append(f"{series}: bucket counts not cumulative")
        if series in hist_counts and hist_counts[series] != bounds[-1][1]:
            errors.append(
                f"{series}: _count != +Inf bucket "
                f"({hist_counts[series]} vs {bounds[-1][1]})")

    # a declared family with zero samples is legal; nothing else to check
    return errors


def _strip_le(labels: str | None) -> str:
    if not labels:
        return ""
    body = labels[1:-1]
    parts = [p for p in re.findall(r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"',
                                   body) if not p.startswith('le="')]
    return "{" + ",".join(parts) + "}" if parts else ""
