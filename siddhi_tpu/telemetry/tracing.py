"""Batch tracing: monotone batch IDs + per-stage spans + slow-batch ring.

A BatchTrace is minted where a micro-batch is FORMED at ingress (the
parallel pipeline's feeder, the MPSC/staging flush, the columnar path) and
rides on the EventBatch as a plain instance attribute (`batch._trace`) —
invisible to JAX's pytree flatten, so it never reaches a jitted step or
perturbs compilation. StreamJunction._deliver adopts the trace (minting one
on the fly for derived-stream publishes and heartbeats), pushes it onto a
thread-local active stack for the duration of the fan-out, and query steps
and sinks attribute their spans to the innermost active trace without any
argument threading.

Stage model (all spans in ns, recorded into per-stage histograms):

  accept   trace mint: the instant the batch's first row left the staging
           structure and batch assembly began
  stage    mint → delivery start, minus h2d (encode + ring/queue wait +
           double-buffer residence)
  h2d      EventBatch.from_numpy (host→device transfer start)
  device   sum of query/join/pattern step wall time inside the fan-out,
           EXCLUSIVE of nested sink time — sinks publish from inside the
           query's own distribution, so the raw query span contains the
           sink span; subtracting it keeps device + sink additive and lets
           the doctor attribute a slow consumer to `sink`, not `device`
  sink     sum of Sink.publish_rows wall time inside the fan-out, credited
           to EVERY trace on the active stack (the derived output stream's
           trace and the ingress trace it is nested under)
  e2e      mint → delivery end

Slow-batch exemplars: a bounded worst-N ring (by e2e) with the stage
breakdown, query names, and batch size — statistics_report()
["slow_batches"]. A separate recent-completion deque
(`recent_summaries()`) exists for tests asserting ID propagation; both
are O(1) per batch (summary dicts are built on read, not on the hot
path).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Optional

from ..util.locks import named_lock
from .metrics import Histogram, MetricsRegistry, bucket_index

#: worst-N exemplar ring size
SLOW_RING = 8
#: recent-completion ring size (test/debug surface)
RECENT_RING = 64


class BatchTrace:
    __slots__ = ("batch_id", "stream", "size", "t0", "h2d_ns", "device_ns",
                 "sink_ns", "deliver_t0", "queries", "superstep")

    def __init__(self, batch_id: int, stream: str, size: Optional[int],
                 t0: int) -> None:
        self.batch_id = batch_id
        self.stream = stream
        self.size = size  # rows when known at mint; None for derived batches
        self.t0 = t0
        self.h2d_ns = 0
        self.device_ns = 0
        self.sink_ns = 0
        self.deliver_t0 = 0
        self.queries: list[str] = []
        #: K of the superstep this batch rode in (core/superstep.py), 0 for
        #: per-batch dispatch — the trace stays per INNER batch either way
        self.superstep = 0

    def summary(self, t_end: int) -> dict:
        e2e = t_end - self.t0
        stage = max(self.deliver_t0 - self.t0 - self.h2d_ns, 0)
        # sink publishes run nested inside query spans: report device
        # exclusive of sink so the stage shares stay additive
        device = max(self.device_ns - self.sink_ns, 0)
        out = {
            "batch_id": self.batch_id,
            "stream": self.stream,
            "batch_size": self.size,
            "queries": list(self.queries),
            "e2e_ms": e2e / 1e6,
            "stages_ms": {
                "stage": stage / 1e6,
                "h2d": self.h2d_ns / 1e6,
                "device": device / 1e6,
                "sink": self.sink_ns / 1e6,
            },
        }
        if self.superstep:
            out["superstep_k"] = self.superstep
        return out


class AppTelemetry:
    """Per-app telemetry façade: the metrics registry, the batch tracer
    state, and the (usually-None) profiling session. Attached to
    SiddhiAppContext.telemetry by the app runtime."""

    def __init__(self, app_name: str, enabled: Optional[bool] = None) -> None:
        from . import telemetry_enabled
        self.app = app_name
        self.on = telemetry_enabled() if enabled is None else enabled
        self.registry = MetricsRegistry()
        r = self.registry
        # always-on families, declared up front so /metrics renders them
        # (HELP/TYPE) even before the first batch
        self.batches = r.counter(
            "siddhi_batches_total",
            "Micro-batches delivered per stream junction", ("stream",))
        self.events = r.counter(
            "siddhi_events_total",
            "Rows delivered per stream (ingress batches with exact counts)",
            ("stream",))
        self.stage_hist = r.histogram(
            "siddhi_stage_latency_seconds",
            "Per-stage batch latency (stage|h2d|device|sink|e2e)",
            ("stream", "stage"))
        self.query_hist = r.histogram(
            "siddhi_query_latency_seconds",
            "Per-query step wall time (device dispatch + distribute)",
            ("query",))
        self.sink_hist = r.histogram(
            "siddhi_sink_latency_seconds",
            "Sink.publish_rows wall time per output stream", ("stream",))
        self.sink_events = r.counter(
            "siddhi_sink_published_total",
            "Rows handed to Sink.publish_rows per output stream",
            ("stream",))
        self.upgrade_hist = r.histogram(
            "siddhi_upgrade_cutover_seconds",
            "Blue-green hot-swap source-paused (cutover) wall time")
        self.lag_gauge = r.gauge(
            "siddhi_event_time_lag_seconds",
            "Event-time lag at delivery: wall clock minus the newest "
            "external row timestamp in the batch (epoch-ms producers only; "
            "also re-sampled at every watermark advance so idle streams "
            "don't freeze)",
            ("stream",))
        self.wm_gauge = r.gauge(
            "siddhi_watermark_lag_seconds",
            "Watermark lag: wall clock minus the stream's event-time "
            "watermark (max event ts minus allowed.lateness; epoch-ms "
            "producers only)",
            ("stream",))
        self.late_counter = r.counter(
            "siddhi_late_events_total",
            "Rows older than the event-time watermark diverted to the "
            "ErrorStore (kind=\"late\") per stream", ("stream",))
        self.tenant_ms = r.counter(
            "siddhi_tenant_device_ms_total",
            "Metered device milliseconds per tenant (equal-share "
            "attribution inside fused groups)", ("tenant",))
        self.tenant_queries = r.gauge(
            "siddhi_tenant_queries",
            "Attached queries per tenant", ("tenant",))
        self.splices = r.counter(
            "siddhi_splices_total",
            "One-retrace query splices by kind (in|out|declined|failed)",
            ("kind",))
        self.splice_ms = r.gauge(
            "siddhi_splice_retrace_ms",
            "Last successful splice's retrace+compile wall milliseconds")
        # tracer state
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._slow: list[tuple[float, int, dict]] = []  # (e2e_ms, id, summary)
        self._slow_floor = 0.0  # cheapest e2e_ms in a full ring (fast reject)
        self._slow_lock = named_lock("telemetry.trace.slow")
        self.recent: deque = deque(maxlen=RECENT_RING)  # (trace, t_end_ns)
        #: armed by SiddhiAppRuntime.profile(); checked by query runtimes
        self.profile = None
        # per-series child caches: Family.labels() is a guarded dict walk,
        # and pop_active touches seven series per delivery — resolving them
        # once per stream keeps the always-on path in single-dict-get
        # territory (racing first lookups are safe: labels() is idempotent)
        self._stream_cells: dict = {}
        self._query_cells: dict = {}
        self._sink_cells: dict = {}
        self._lag_cells: dict = {}
        self._wm_cells: dict = {}
        self._late_cells: dict = {}

    # ---------------------------------------------------------------- tracing

    def mint(self, stream: str, size: Optional[int] = None,
             t0: Optional[int] = None) -> BatchTrace:
        return BatchTrace(next(self._ids), stream, size,
                          time.perf_counter_ns() if t0 is None else t0)

    def push_active(self, trace: BatchTrace) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(trace)

    def active(self) -> Optional[BatchTrace]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def pop_active(self, trace: BatchTrace) -> None:
        """Close the delivery: record every stage span + counters, then
        retire the trace into the recent/slow rings."""
        stack = self._tls.stack
        stack.pop()
        t_end = time.perf_counter_ns()
        stream = trace.stream
        cells = self._stream_cells.get(stream)
        if cells is None:
            sh = self.stage_hist
            cells = (self.batches.labels(stream),
                     self.events.labels(stream),
                     sh.labels(stream, "stage"), sh.labels(stream, "h2d"),
                     sh.labels(stream, "device"), sh.labels(stream, "sink"),
                     sh.labels(stream, "e2e"))
            self._stream_cells[stream] = cells
        batches_c, events_c, stage_c, h2d_c, device_c, sink_c, e2e_c = cells
        stage_ns = trace.deliver_t0 - trace.t0 - trace.h2d_ns
        stage_c.observe_ns(stage_ns if stage_ns > 0 else 0)
        if trace.h2d_ns:
            h2d_c.observe_ns(trace.h2d_ns)
        device_ns = trace.device_ns - trace.sink_ns  # sink nests in query spans
        if device_ns > 0:
            device_c.observe_ns(device_ns)
        if trace.sink_ns:
            sink_c.observe_ns(trace.sink_ns)
        e2e_ns = t_end - trace.t0
        e2e_c.observe_ns(e2e_ns)
        batches_c.inc()
        if trace.size is not None:
            events_c.inc(trace.size)
        self.recent.append((trace, t_end))
        e2e_ms = e2e_ns / 1e6
        # summary dicts are built only for the worst-N ring; the common
        # (fast-batch) path does one float compare and moves on
        if len(self._slow) < SLOW_RING or e2e_ms > self._slow_floor:
            with self._slow_lock:
                if len(self._slow) < SLOW_RING:
                    heapq.heappush(
                        self._slow,
                        (e2e_ms, trace.batch_id, trace.summary(t_end)))
                elif e2e_ms > self._slow[0][0]:
                    heapq.heapreplace(
                        self._slow,
                        (e2e_ms, trace.batch_id, trace.summary(t_end)))
                if len(self._slow) >= SLOW_RING:
                    self._slow_floor = self._slow[0][0]

    # ------------------------------------------------------------ span hooks

    def record_query(self, query: str, ns: int) -> None:
        h = self._query_cells.get(query)
        if h is None:
            h = self._query_cells[query] = self.query_hist.labels(query)
        h.observe_ns(ns)
        tr = self.active()
        if tr is not None:
            tr.device_ns += ns
            tr.queries.append(query)

    def query_cell(self, query: str):
        """Pre-resolve the per-query histogram cell so fused groups can
        record their whole membership without N dict lookups per batch."""
        h = self._query_cells.get(query)
        if h is None:
            h = self._query_cells[query] = self.query_hist.labels(query)
        return h

    def record_query_block(self, cells, names, ns: int) -> None:
        """Bulk `record_query` for one fused group: every member reports
        the same share `ns` of the group's measured span, so the bucket
        index is computed once and the cells (from `query_cell`) are
        observed directly. Series produced are identical to calling
        `record_query(name, ns)` per member."""
        bi = bucket_index(ns)
        for h in cells:
            h.observe_ns_at(bi, ns)
        tr = self.active()
        if tr is not None:
            tr.device_ns += ns * len(names)
            tr.queries.extend(names)

    def record_splice(self, kind: str, ms=None) -> None:
        """One splice event (kind: in|out|declined|failed) — always on,
        like the counters in statistics: a failed/declined splice is an
        operational event, not a metric."""
        self.splices.labels(kind).inc()
        if ms is not None:
            self.splice_ms.labels().set(float(ms))

    def record_lag(self, stream: str, newest_ts_ms: int) -> None:
        """Event-time lag at delivery: how stale the newest row of the
        batch already was when the engine saw it (upstream queueing the
        processing-latency stages can't see). Meaningful only when the
        producer stamps epoch milliseconds — synthetic/logical timestamps
        (tests, playback counters) are ignored via a plausibility window
        so the gauge never reports a ~50-year lag for counter timestamps."""
        if newest_ts_ms < 1_000_000_000_000:  # pre-2001 epoch-ms: synthetic
            return
        g = self._lag_cells.get(stream)
        if g is None:
            g = self._lag_cells[stream] = self.lag_gauge.labels(stream)
        g.set(max(time.time() - newest_ts_ms / 1e3, 0.0))

    def record_watermark(self, stream: str, wm_ms: int) -> None:
        """Watermark lag at advance (event-time gates, core/event_time.py).
        Same epoch-ms plausibility guard as record_lag — synthetic/logical
        clocks must not render as a ~50-year lag."""
        if wm_ms < 1_000_000_000_000:
            return
        g = self._wm_cells.get(stream)
        if g is None:
            g = self._wm_cells[stream] = self.wm_gauge.labels(stream)
        g.set(max(time.time() - wm_ms / 1e3, 0.0))

    def record_late(self, stream: str, n: int) -> None:
        """Late-diversion counter — always on (a correctness signal, like
        the sink families), independent of the batch tracer."""
        c = self._late_cells.get(stream)
        if c is None:
            c = self._late_cells[stream] = self.late_counter.labels(stream)
        c.inc(n)

    def observe_upgrade(self, pause_ms: float) -> None:
        """One committed hot-swap's cutover pause (core/upgrade.py)."""
        self.upgrade_hist.labels().observe_ns(int(pause_ms * 1e6))

    def record_sink(self, stream: str, rows: int, ns: int) -> None:
        cells = self._sink_cells.get(stream)
        if cells is None:
            cells = (self.sink_hist.labels(stream),
                     self.sink_events.labels(stream))
            self._sink_cells[stream] = cells
        cells[0].observe_ns(ns)
        cells[1].inc(rows)
        # credit the sink span to the whole active stack: the innermost
        # (derived output stream) trace owns it directly, and each outer
        # trace needs it to net sink time OUT of its enclosing query spans
        stack = getattr(self._tls, "stack", None)
        if stack:
            for tr in stack:
                tr.sink_ns += ns

    # --------------------------------------------------------------- reports

    def slow_batches(self) -> list[dict]:
        """Worst-N exemplars, slowest first."""
        with self._slow_lock:
            items = sorted(self._slow, key=lambda x: -x[0])
        return [s for _, _, s in items]

    def recent_summaries(self) -> list[dict]:
        """Summaries of the last RECENT_RING completed deliveries (oldest
        first) — built on demand, the hot path stores raw traces."""
        return [tr.summary(t_end) for tr, t_end in list(self.recent)]

    def latency_snapshot(self) -> dict:
        """statistics_report()["latency"]: per-stream per-stage percentiles
        and per-query step percentiles, from the same histograms /metrics
        exports."""
        streams: dict[str, dict] = {}
        for (stream, stage), hist in self.stage_hist.samples():
            s = hist.summary()
            if s["count"]:
                streams.setdefault(stream, {})[stage] = s
        queries = {}
        for (query,), hist in self.query_hist.samples():
            s = hist.summary()
            if s["count"]:
                queries[query] = s
        lag = {stream: g.value()
               for (stream,), g in self.lag_gauge.samples()}
        return {"streams": streams, "queries": queries,
                "event_time_lag_s": lag}
