"""Declarative SLOs with multi-window burn-rate evaluation (ISSUE 10).

PR 7 produced the raw signals (per-stage histograms, batch traces); this
module is the layer above them: *objectives* declared in the app text,

    @app:slo(stream='TradeStream', p99.ms='50', min.rate='1000')
    define stream TradeStream (symbol string, price double, volume long);

    @slo(p99.ms='5', error.ratio='0.01')
    @info(name='q1')
    from TradeStream[price > 20.0] select symbol insert into Out;

evaluated continuously on rolling windows with the Google-SRE
**multi-window burn rate** scheme: an objective breaches only when the
error budget is burning faster than `burn.threshold` over BOTH the fast
window (default 5 min — catches the incident quickly) and the slow
window (default 1 h — confirms it is sustained, not a blip). Burn rate
1.0 means consuming exactly the budget an objective allows (e.g. a
p99 target tolerates 1% of observations over the threshold; twice that
fraction is a burn rate of 2.0).

Objective kinds (annotation element → kind):

  p50.ms / p95.ms / p99.ms / p999.ms   latency: fraction of observations
                                       above the target must stay inside
                                       the quantile's budget (0.5/0.05/
                                       0.01/0.001)
  min.rate                             throughput floor in events/s over
                                       the fast window (streams count
                                       delivered rows; query scope counts
                                       step executions)
  error.ratio                          bad-event ratio: dead-lettered +
                                       sink-dropped + breaker-diverted
                                       rows per delivered row

Everything is **virtual-clock testable**: the evaluator never calls
`time.*` directly — `SloEngine(clock=...)` and `tick(now=...)` follow
the same injectable-clock pattern as core/breaker.py, so the burn-rate
math is exercised in tests over simulated hours in microseconds.

Surfaces: `statistics_report()["slo"]`, the `siddhi_slo_*` Prometheus
families (telemetry/prometheus.py), the `GET /slo` readiness-style
endpoint (service.py), and breach transitions trigger the flight
recorder (telemetry/recorder.py).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from ..util.locks import named_lock
from .metrics import N_BUCKETS, bucket_index

#: defaults for the two burn windows (seconds) and the burn threshold
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
BURN_THRESHOLD = 1.0

#: latency element key -> (quantile, error budget = 1 - quantile)
_QUANTILE_KEYS = {
    "p50.ms": 0.5,
    "p95.ms": 0.95,
    "p99.ms": 0.99,
    "p999.ms": 0.999,
}

OK = "ok"
BREACHED = "breached"


def frac_over_threshold(buckets, count: int, threshold_ns: int) -> float:
    """Fraction of observations strictly above `threshold_ns`, from log2-µs
    bucket deltas, linearly interpolating inside the owning bucket (the
    same ×2-bounded estimate quantile extraction uses)."""
    if count <= 0:
        return 0.0
    bi = bucket_index(threshold_ns)
    above = float(sum(buckets[bi + 1:]))
    n = buckets[bi]
    if n:
        if bi >= N_BUCKETS - 1:
            above += n  # +Inf bucket: everything exceeds any finite target
        else:
            lo = 0 if bi == 0 else (1 << (bi - 1)) * 1000
            hi = (1 << bi) * 1000
            frac_above = (hi - threshold_ns) / (hi - lo)
            above += n * min(max(frac_above, 0.0), 1.0)
    return min(above / count, 1.0)


class Objective:
    """One declared objective: a cumulative-sample ring + the dual-window
    burn evaluation + the ok/breached state machine.

    `reader()` returns the CUMULATIVE sample for the objective's kind:

      latency      (count, bucket_tuple)      from Histogram.snapshot()
      rate         count                      monotone event/step count
      error_ratio  (bad, total)               monotone counters

    observe() appends (t, sample); evaluate() diffs the newest sample
    against the oldest inside each window (a window with less history
    than its span uses what exists — "up to window" semantics)."""

    def __init__(self, oid: str, kind: str, scope_type: str, scope: str,
                 *, target: float, quantile: Optional[float] = None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 burn_threshold: float = BURN_THRESHOLD,
                 min_samples: int = 1,
                 reader: Optional[Callable] = None) -> None:
        if kind not in ("latency", "rate", "error_ratio"):
            raise ValueError(f"unknown objective kind {kind!r}")
        self.id = oid
        self.kind = kind
        self.scope_type = scope_type  # "stream" | "query" | "app"
        self.scope = scope
        self.target = float(target)
        self.quantile = quantile
        self.budget = (1.0 - quantile) if quantile is not None else None
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.min_samples = int(min_samples)
        self.reader = reader
        self.state = OK
        self.breaches = 0
        self.recoveries = 0
        self._samples: deque = deque()  # (t, cumulative_sample)

    # ------------------------------------------------------------- sampling

    def observe(self, now: float) -> None:
        self._samples.append((now, self.reader()))
        horizon = now - self.slow_window_s
        # keep one sample OLDER than the slow window so its delta always
        # spans the full window once enough history exists
        while len(self._samples) > 2 and self._samples[1][0] <= horizon:
            self._samples.popleft()

    def _window(self, now: float, span_s: float):
        """(elapsed_s, oldest_sample, newest_sample) for one window."""
        newest = self._samples[-1]
        oldest = self._samples[0]
        horizon = now - span_s
        for t, s in self._samples:
            if t >= horizon:
                oldest = (t, s)
                break
        return max(newest[0] - oldest[0], 1e-9), oldest[1], newest[1]

    # ----------------------------------------------------------- evaluation

    def _burn(self, now: float, span_s: float) -> dict:
        elapsed, old, new = self._window(now, span_s)
        if self.kind == "latency":
            count = new[0] - old[0]
            buckets = [a - b for a, b in zip(new[1], old[1])]
            bad_frac = frac_over_threshold(
                buckets, count, int(self.target * 1e6))
            return {"samples": count,
                    "burn_rate": bad_frac / self.budget,
                    "compliance": 1.0 - bad_frac}
        if self.kind == "rate":
            events = new - old
            rate = events / elapsed
            return {"samples": events, "rate_eps": rate, "elapsed": elapsed,
                    # burn framing: how far below the floor we are
                    "burn_rate": max((self.target - rate) / self.target, 0.0)
                    if self.target > 0 else 0.0,
                    "compliance": min(rate / self.target, 1.0)
                    if self.target > 0 else 1.0}
        bad = new[0] - old[0]
        total = new[1] - old[1]
        ratio = bad / total if total > 0 else 0.0
        return {"samples": total,
                "burn_rate": ratio / self.target if self.target > 0 else 0.0,
                "compliance": 1.0 - ratio}

    def evaluate(self, now: float) -> Optional[dict]:
        """Re-evaluate both windows; returns a transition event dict when
        the state changed ({"objective", "from", "to", "at"}), else None."""
        fast = self._burn(now, self.fast_window_s)
        slow = self._burn(now, self.slow_window_s)
        self.last_fast, self.last_slow = fast, slow
        if self.kind == "rate":
            # a throughput floor is judged on the fast window alone (the
            # slow window would average an outage against healthy history);
            # require ≥1 s of real history so boot doesn't read as outage
            breaching = (fast.get("elapsed", 0.0) >= 1.0
                         and fast["rate_eps"] < self.target)
        else:
            breaching = (fast["samples"] >= self.min_samples
                         and fast["burn_rate"] >= self.burn_threshold
                         and slow["burn_rate"] >= self.burn_threshold)
        if breaching and self.state == OK:
            self.state = BREACHED
            self.breaches += 1
            return {"objective": self.id, "from": OK, "to": BREACHED,
                    "at": now}
        if not breaching and self.state == BREACHED:
            self.state = OK
            self.recoveries += 1
            return {"objective": self.id, "from": BREACHED, "to": OK,
                    "at": now}
        return None

    def report(self) -> dict:
        fast = getattr(self, "last_fast", None) or {"samples": 0,
                                                    "burn_rate": 0.0,
                                                    "compliance": 1.0}
        slow = getattr(self, "last_slow", None) or dict(fast)
        return {
            "kind": self.kind,
            "scope": f"{self.scope_type}:{self.scope}",
            "target": self.target,
            "quantile": self.quantile,
            "burn_threshold": self.burn_threshold,
            "windows_s": [self.fast_window_s, self.slow_window_s],
            "state": self.state,
            "breaches": self.breaches,
            "recoveries": self.recoveries,
            "fast": fast,
            "slow": slow,
        }


class SloEngine:
    """All of one app's objectives + the tick loop state. The engine never
    reads wall clock itself: `clock` is injectable and `tick(now=...)`
    overrides it, so tests drive simulated time."""

    def __init__(self, app_name: str = "",
                 clock: Callable[[], float] = time.monotonic,
                 interval_s: float = 1.0) -> None:
        self.app = app_name
        self.clock = clock
        self.interval_s = float(interval_s)
        self.objectives: list[Objective] = []
        #: called with (objective, event) on each ok->breached transition
        self.on_breach: Optional[Callable] = None
        self._lock = named_lock("telemetry.slo.tick")

    def add(self, objective: Objective) -> Objective:
        self.objectives.append(objective)
        objective.observe(self.clock())  # seed the cumulative baseline
        return objective

    def tick(self, now: Optional[float] = None) -> list[dict]:
        """One evaluation pass: sample every objective, re-judge both
        windows, fire on_breach for fresh breaches. Returns the state
        transitions this tick produced."""
        t = self.clock() if now is None else now
        events = []
        with self._lock:
            for o in self.objectives:
                o.observe(t)
                ev = o.evaluate(t)
                if ev is None:
                    continue
                events.append(ev)
                if ev["to"] == BREACHED and self.on_breach is not None:
                    try:
                        self.on_breach(o, ev)
                    except Exception:  # noqa: BLE001 — never kill the tick
                        import logging
                        logging.getLogger("siddhi_tpu").exception(
                            "SLO breach hook failed for %r", o.id)
        return events

    def breaching(self) -> bool:
        return any(o.state == BREACHED for o in self.objectives)

    def report(self) -> dict:
        return {
            "objectives": {o.id: o.report() for o in self.objectives},
            "breaching": self.breaching(),
        }


# --------------------------------------------------------------------------- #
# annotation binding
# --------------------------------------------------------------------------- #


def _objectives_from_annotation(ann, scope_type: str, scope: str,
                                engine: SloEngine, runtime,
                                default_streams) -> None:
    from ..core.partition import _parse_annotation_time
    from ..errors import SiddhiAppCreationError
    tele = runtime.ctx.telemetry
    st = runtime.ctx.statistics

    def _time_el(key: str, default_s: float) -> float:
        v = ann.element(key)
        return _parse_annotation_time(v) / 1000.0 if v else default_s

    try:
        fast_s = _time_el("fast.window", FAST_WINDOW_S)
        slow_s = _time_el("slow.window", SLOW_WINDOW_S)
        burn = float(ann.element("burn.threshold") or BURN_THRESHOLD)
        min_samples = int(ann.element("min.samples") or 1)
    except ValueError as e:
        raise SiddhiAppCreationError(f"bad @slo annotation: {e}") from e

    scopes = [(scope_type, scope)]
    if scope_type == "app":
        sel = ann.element("stream")
        streams = [sel] if sel else list(default_streams)
        if not streams:
            raise SiddhiAppCreationError(
                "@app:slo needs at least one defined stream")
        scopes = [("stream", s) for s in streams]

    def _latency_reader(hist):
        def read():
            buckets, count, _ = hist.snapshot()
            return (count, tuple(buckets))
        return read

    def _stream_rate_reader(counter):
        return counter.value

    def _query_rate_reader(hist):
        return hist.count

    def _error_reader(total_fn):
        def read():
            bad = (sum(st.sink_dead_letters.values())
                   + sum(st.sink_dropped.values())
                   + sum(st.breaker_diverted.values()))
            return (bad, total_fn())
        return read

    for s_type, s_name in scopes:
        if s_type == "stream":
            e2e_hist = tele.stage_hist.labels(s_name, "e2e")
            rate_counter = tele.events.labels(s_name)
            rate_reader = _stream_rate_reader(rate_counter)
        else:
            e2e_hist = tele.query_hist.labels(s_name)
            rate_reader = _query_rate_reader(e2e_hist)

        for key, q in _QUANTILE_KEYS.items():
            v = ann.element(key)
            if v is None:
                continue
            try:
                target_ms = float(v)
            except ValueError as e:
                raise SiddhiAppCreationError(
                    f"bad @slo {key}={v!r}: want milliseconds") from e
            engine.add(Objective(
                f"{s_type}:{s_name}:{key}", "latency", s_type, s_name,
                target=target_ms, quantile=q, fast_window_s=fast_s,
                slow_window_s=slow_s, burn_threshold=burn,
                min_samples=min_samples,
                reader=_latency_reader(e2e_hist)))
        v = ann.element("min.rate")
        if v is not None:
            engine.add(Objective(
                f"{s_type}:{s_name}:min.rate", "rate", s_type, s_name,
                target=float(v), fast_window_s=fast_s,
                slow_window_s=slow_s, burn_threshold=burn,
                reader=rate_reader))
        v = ann.element("error.ratio")
        if v is not None:
            engine.add(Objective(
                f"{s_type}:{s_name}:error.ratio", "error_ratio",
                s_type, s_name, target=float(v), fast_window_s=fast_s,
                slow_window_s=slow_s, burn_threshold=burn,
                min_samples=min_samples,
                reader=_error_reader(rate_reader if s_type != "stream"
                                     else rate_counter.value)))


def slo_engine_from_app(runtime) -> Optional[SloEngine]:
    """Build the app's SloEngine from `@app:slo(...)` (one or more, app
    level) and per-query `@slo(...)` annotations; None when the app
    declares no objectives or telemetry is disabled (the objectives read
    the telemetry histograms — without them every window would be empty)."""
    app = runtime.app
    tele = getattr(runtime.ctx, "telemetry", None)
    if tele is None or not tele.on:
        return None
    app_anns = [a for a in (app.annotations or ())
                if a.name.lower() == "app:slo"]
    query_anns = []
    for i, query in enumerate(app.queries):
        name = query.name or f"query{i + 1}"
        for a in (query.annotations or ()):
            if a.name.lower() == "slo":
                query_anns.append((name, a))
    if not app_anns and not query_anns:
        return None
    engine = SloEngine(app.name)
    ingress = list(app.stream_definitions)
    for ann in app_anns:
        _objectives_from_annotation(ann, "app", app.name, engine, runtime,
                                    ingress)
    for qname, ann in query_anns:
        _objectives_from_annotation(ann, "query", qname, engine, runtime,
                                    ingress)
    if not engine.objectives:
        from ..errors import SiddhiAppCreationError
        raise SiddhiAppCreationError(
            "@slo annotation present but no objective elements "
            "(want p99.ms= / min.rate= / error.ratio= ...)")
    return engine
