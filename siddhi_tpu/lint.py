"""`python -m siddhi_tpu.lint` — lint SiddhiQL apps from the command line.

    python -m siddhi_tpu.lint app.siddhi [more.siddhi ...]
    python -m siddhi_tpu.lint --json app.siddhi
    python -m siddhi_tpu.lint --jaxpr app.siddhi     # + compiled-step hazards
    python -m siddhi_tpu.lint --scan samples/        # every *.siddhi under
    python -m siddhi_tpu.lint --self                 # SL40x concurrency lint
                                                     # over the engine source

Exit codes: 0 = no ERROR findings anywhere, 1 = at least one ERROR,
2 = a file could not be read or parsed (parse failures also surface as an
SL000 ERROR diagnostic so JSON consumers see one uniform shape).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .analysis import Diagnostic, LintReport, Severity, analyze
from .errors import SiddhiParserError


def lint_text(text: str, *, name: str = "SiddhiApp",
              jaxpr: bool = False) -> LintReport:
    """Lint one app source. Parse failures become an SL000 ERROR diagnostic
    instead of an exception, so callers always get a report."""
    try:
        return analyze(text, jaxpr=jaxpr, name=name)
    except SiddhiParserError as e:
        report = LintReport(app_name=name)
        loc = (e.line, e.column) if e.line is not None else None
        # first line only: the Diagnostic re-renders loc, and the caret
        # snippet doesn't survive single-line report formats
        import re as _re
        msg = _re.sub(r"\s+at line -?\d+:-?\d+$", "",
                      str(e).split("\n")[0])
        report.add(Diagnostic("SL000", Severity.ERROR,
                              f"parse error: {msg}", element=name, loc=loc))
        return report


def _print_cost(path: str, cost: dict) -> None:
    """The --cost pretty-printer over a CostReport.to_dict() section."""
    from .analysis import format_size

    exact = "" if cost.get("exact") else " (estimate)"
    print(f"{path}: cost: "
          f"{format_size(cost['predicted_state_bytes'])} device state, "
          f"{cost['predicted_compiles']} compile(s){exact}")
    dom = cost.get("dominant")
    if dom:
        print(f"{path}: cost: dominant element {dom['element']!r} holds "
              f"{format_size(dom['state_bytes'])} ({dom['share']:.0%})")
    budget = cost.get("budget")
    if budget:
        state = budget.get("state_bytes")
        limit = (format_size(state) if state is not None else "-",
                 budget.get("compiles"))
        verdict = "over" if (
            (state is not None and cost["predicted_state_bytes"] > state)
            or (budget.get("compiles") is not None
                and cost["predicted_compiles"] > budget["compiles"])
        ) else "within"
        print(f"{path}: cost: budget state={limit[0]} "
              f"compiles={limit[1] if limit[1] is not None else '-'} "
              f"({budget.get('source')}, mode={budget.get('mode')}) — "
              f"{verdict} budget")
    for e in cost.get("elements", ()):
        if e.get("dispatch") == "host":
            print(f"{path}: cost: element {e['element']!r} takes a "
                  "host-callback hop every batch (SL504)")


def _collect(paths: list[str], scan: bool) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            if scan:
                files.extend(sorted(path.rglob("*.siddhi")))
            else:
                raise SystemExit(
                    f"{path} is a directory (use --scan to recurse)")
        else:
            files.append(path)
    return files


def main(argv: list[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.lint",
        description="Static lint for SiddhiQL apps (rule reference: "
                    "docs/LINT.md)")
    ap.add_argument("paths", nargs="*", help="*.siddhi files (or "
                    "directories with --scan)")
    ap.add_argument("--self", action="store_true", dest="self_mode",
                    help="lint the engine's own Python source with the "
                         "SL40x concurrency catalog instead of SiddhiQL "
                         "files (docs/CONCURRENCY.md)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object {file: report} on stdout")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also trace each query's compiled step for "
                         "host-callback/float64/upcast hazards (slower)")
    ap.add_argument("--scan", action="store_true",
                    help="recurse into directories for *.siddhi files")
    ap.add_argument("--max-severity", choices=["error", "warn", "info"],
                    default="info",
                    help="hide findings below this severity")
    ap.add_argument("--cost", action="store_true",
                    help="also print each app's static cost prediction "
                         "(state bytes, compile ladder, dominant element, "
                         "budget verdict — docs/COST.md)")
    args = ap.parse_args(argv)

    max_rank = {"error": 0, "warn": 1, "info": 2}[args.max_severity]
    if args.self_mode:
        from .analysis import lint_package
        report = lint_package()
        if args.as_json:
            print(json.dumps({report.app_name: report.to_dict()}, indent=2))
        else:
            for d in report.sorted():
                if d.severity.rank <= max_rank:
                    print(d.format())
            n_err, n_warn = len(report.errors), len(report.warnings)
            print(f"{report.app_name}: {n_err} error(s), {n_warn} "
                  f"warning(s), "
                  f"{len(report.diagnostics) - n_err - n_warn} info")
        return 1 if report.has_errors else 0
    if not args.paths:
        ap.error("paths are required unless --self is given")

    try:
        files = _collect(args.paths, args.scan)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    had_error = False
    had_io_or_parse_failure = False
    results: dict[str, dict] = {}

    for path in files:
        try:
            text = path.read_text()
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            had_io_or_parse_failure = True
            continue
        report = lint_text(text, name=str(path), jaxpr=args.jaxpr)
        if any(d.rule_id == "SL000" for d in report.diagnostics):
            had_io_or_parse_failure = True
        if report.has_errors:
            had_error = True
        if args.as_json:
            results[str(path)] = report.to_dict()
        else:
            shown = [d for d in report.sorted()
                     if d.severity.rank <= max_rank]
            for d in shown:
                print(f"{path}: {d.format()}")
            n_err = len(report.errors)
            n_warn = len(report.warnings)
            print(f"{path}: {n_err} error(s), {n_warn} warning(s), "
                  f"{len(report.diagnostics) - n_err - n_warn} info")
            if args.cost and report.cost is not None:
                _print_cost(str(path), report.cost)

    if args.as_json:
        print(json.dumps(results, indent=2))
    if had_io_or_parse_failure:
        return 2
    return 1 if had_error else 0


if __name__ == "__main__":
    raise SystemExit(main())
