"""Exception hierarchy (reference: core/exception/* and
siddhi-query-api/.../exception/*)."""


class SiddhiError(Exception):
    """Base for all framework errors."""


class SiddhiAppCreationError(SiddhiError):
    """App could not be planned/compiled (reference:
    core/exception/SiddhiAppCreationError... creation exceptions)."""


class SiddhiAppValidationError(SiddhiError):
    pass


class DuplicateDefinitionError(SiddhiAppValidationError):
    pass


class DefinitionNotExistError(SiddhiAppValidationError):
    pass


class SiddhiParserError(SiddhiError):
    """Syntax error with line/column context (reference:
    siddhi-query-compiler/.../exception/SiddhiParserException.java).

    `snippet` carries the offending source line with a caret marker; lint
    diagnostics (analysis/diagnostics.py) reuse the same " at line L:C"
    location format so every tool reports positions identically."""

    def __init__(self, message: str, line: int | None = None,
                 column: int | None = None, snippet: str | None = None):
        self.line, self.column, self.snippet = line, column, snippet
        loc = f" at line {line}:{column}" if line is not None else ""
        ctx = f"\n{snippet.rstrip()}" if snippet else ""
        super().__init__(f"{message}{loc}{ctx}")


class SiddhiAppRuntimeError(SiddhiError):
    pass


class CannotRestoreStateError(SiddhiError):
    pass


class ConnectionUnavailableError(SiddhiError):
    """Source/sink transport failure; triggers backoff retry (reference:
    core/exception/ConnectionUnavailableException.java)."""


class NoPersistenceStoreError(SiddhiError):
    pass


class OnDemandQueryCreationError(SiddhiError):
    pass


class CapacityExceededError(SiddhiAppRuntimeError):
    """A fixed-capacity device structure (window ring, NFA slots, key table)
    overflowed. TPU-specific: the reference's unbounded heap structures become
    static-shape device buffers; capacity is configurable per element."""


class StaleTransientCodeError(SiddhiAppRuntimeError):
    """A transient (UUID-ring) string code was decoded after its ring slot
    recycled: the retained code is older than the ring's capacity allows.
    Loud by design — silently decoding a NEWER uuid was the alternative."""
