"""Built-in scalar functions (reference: core/executor/function/*.java — 20
built-ins). Each registers a ScalarFunction whose `make` receives static arg
types and returns a traceable jnp lambda + return type, mirroring the
reference's parse-time monomorphic executor selection."""

from __future__ import annotations

import jax.numpy as jnp

from ..core import dtypes
from ..core.dtypes import NULL_CODE
from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from ..query_api.definition import AttributeType
from .expr_compile import ScalarFunction

_T = AttributeType


def _register(name: str, make, namespace: str = "") -> None:
    GLOBAL.register(ExtensionKind.FUNCTION, namespace, name, ScalarFunction(make))


# -- type conversion -------------------------------------------------------------

_NAME_TO_TYPE = {
    "int": _T.INT, "long": _T.LONG, "float": _T.FLOAT, "double": _T.DOUBLE,
    "bool": _T.BOOL, "string": _T.STRING,
}


def _make_convert(arg_types):
    # convert(x, 'type') — the target type is a compile-time string constant;
    # expr_compile passes string constants through as host strings.
    if len(arg_types) != 2:
        raise SiddhiAppCreationError("convert(value, 'type') takes 2 args")

    def fn(x, target):
        t = _NAME_TO_TYPE[str(target).lower()]
        if t == _T.STRING or arg_types[0] == _T.STRING:
            raise SiddhiAppCreationError("string conversion is host-side only")
        return x.astype(dtypes.device_dtype(t))

    # return type depends on the constant — resolved on first trace; for typing
    # purposes we conservatively report DOUBLE unless target known statically.
    return fn, _T.DOUBLE


def _make_cast(arg_types):
    return _make_convert(arg_types)


def _make_if_then_else(arg_types):
    if len(arg_types) != 3 or arg_types[0] != _T.BOOL:
        raise SiddhiAppCreationError("ifThenElse(bool, then, else)")
    if arg_types[1] != arg_types[2]:
        if dtypes.is_numeric(arg_types[1]) and dtypes.is_numeric(arg_types[2]):
            out_t = dtypes.promote(arg_types[1], arg_types[2])
        else:
            raise SiddhiAppCreationError("ifThenElse branches must share a type")
    else:
        out_t = arg_types[1]
    dt = dtypes.device_dtype(out_t)
    return (lambda c, a, b: jnp.where(c, jnp.asarray(a, dt), jnp.asarray(b, dt))), out_t


def _make_coalesce(arg_types):
    # Numeric columns carry no per-attribute null on device (core/dtypes.py), so
    # coalesce over numerics returns the first arg; over strings it picks the
    # first non-null code.
    t0 = arg_types[0]
    if all(t == _T.STRING for t in arg_types):
        def fn(*args):
            out = args[-1]
            for a in reversed(args[:-1]):
                out = jnp.where(a != NULL_CODE, a, out)
            return out
        return fn, _T.STRING
    out_t = t0
    for t in arg_types[1:]:
        out_t = dtypes.promote(out_t, t)
    return (lambda *args: args[0].astype(dtypes.device_dtype(out_t))), out_t


def _make_default(arg_types):
    if arg_types[0] == _T.STRING:
        return (lambda a, d: jnp.where(a != NULL_CODE, a, d)), _T.STRING
    return (lambda a, d: a), arg_types[0]


def _make_minmax(reducer):
    def make(arg_types):
        out_t = arg_types[0]
        for t in arg_types[1:]:
            out_t = dtypes.promote(out_t, t)
        dt = dtypes.device_dtype(out_t)

        def fn(*args):
            out = args[0].astype(dt)
            for a in args[1:]:
                out = reducer(out, a.astype(dt))
            return out

        return fn, out_t

    return make


def _make_event_timestamp(arg_types):
    def fn(*args):
        raise SiddhiAppCreationError("eventTimestamp resolved by planner")
    return fn, _T.LONG


def _make_current_time(arg_types):
    def fn(*args):
        raise SiddhiAppCreationError("currentTimeMillis resolved by planner")
    return fn, _T.LONG


def _make_instance_of(target: AttributeType):
    def make(arg_types):
        result = arg_types[0] == target
        return (lambda x, r=result: jnp.full(jnp.shape(x), r, dtype=bool)), _T.BOOL
    return make


def _make_math_unary(jfn, out=_T.DOUBLE):
    def make(arg_types):
        dt = dtypes.device_dtype(out)
        return (lambda x: jfn(x.astype(dt))), out
    return make


def _make_uuid(arg_types):
    """UUID() — reference UUIDFunctionExecutor. Random identifiers are a
    host concept: device lanes carry a placeholder string code and the
    runtime substitutes a fresh uuid4 per event at the host boundary
    (callbacks/sinks). Chaining UUID output through further device queries
    yields null — documented divergence (docs/PARITY.md)."""
    # reached only when UUID() is NOT a top-level SELECT attribute — the
    # selector substitutes those before compilation (ops/selector.py)
    raise SiddhiAppCreationError(
        "UUID() is only supported as a top-level SELECT attribute "
        "(host-boundary substitution); it cannot feed other expressions")


def _make_create_set(arg_types):
    raise SiddhiAppCreationError(
        "createSet() produces a host-opaque set object; on this engine only "
        "the sizeOfSet(unionSet(createSet(x))) composition is supported — "
        "it compiles to an exact distinct count on device")


def _make_size_of_set(arg_types):
    # Forwarded raw-unionSet columns are handled by the PLANNER
    # (expr_compile._compile_function): it verifies unionSet provenance via
    # Attribute.set_projection before reading the LONG set-size projection.
    # Reaching this factory means the argument is NOT a provenance-marked
    # attribute — raising here (instead of accepting any LONG, pre-r6
    # behavior) stops sizeOfSet(ordinaryLongAttr) from silently forwarding
    # the attribute value (ADVICE r5).
    raise SiddhiAppCreationError(
        "sizeOfSet() over an arbitrary expression is not supported; "
        "sizeOfSet(unionSet(...)) compiles to an exact distinct count, and "
        "a forwarded `select unionSet(x) as s` column carries a "
        "provenance-marked set-size projection (LONG) that sizeOfSet reads "
        "directly")


def register_all() -> None:
    _register("UUID", _make_uuid)
    _register("createSet", _make_create_set)
    _register("sizeOfSet", _make_size_of_set)
    _register("convert", _make_convert)
    _register("cast", _make_cast)
    _register("ifThenElse", _make_if_then_else)
    _register("coalesce", _make_coalesce)
    _register("default", _make_default)
    _register("maximum", _make_minmax(jnp.maximum))
    _register("minimum", _make_minmax(jnp.minimum))
    _register("instanceOfInteger", _make_instance_of(_T.INT))
    _register("instanceOfLong", _make_instance_of(_T.LONG))
    _register("instanceOfFloat", _make_instance_of(_T.FLOAT))
    _register("instanceOfDouble", _make_instance_of(_T.DOUBLE))
    _register("instanceOfBoolean", _make_instance_of(_T.BOOL))
    _register("instanceOfString", _make_instance_of(_T.STRING))
    # math namespace conveniences (subset of siddhi-execution-math)
    _register("abs", _make_math_unary(jnp.abs), "math")
    _register("sqrt", _make_math_unary(jnp.sqrt), "math")
    _register("log", _make_math_unary(jnp.log), "math")
    _register("exp", _make_math_unary(jnp.exp), "math")
    _register("floor", _make_math_unary(jnp.floor), "math")
    _register("ceil", _make_math_unary(jnp.ceil), "math")
    _register("round", _make_math_unary(jnp.round), "math")
    _register("sin", _make_math_unary(jnp.sin), "math")
    _register("cos", _make_math_unary(jnp.cos), "math")
    _register("power", _make_minmax(jnp.power), "math")


register_all()
